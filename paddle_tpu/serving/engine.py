"""ServingEngine: continuous-batching GPT inference over a paged KV cache.

The serving loop is TWO jit-compiled fixed-shape steps:

- a **batched chunked-prefill step** (ISSUE 6): one call advances EVERY
  admitted request's next prompt chunk at once — tokens (S, C), ragged
  per-slot valid counts, causal paged attention via
  ``decode_attention.ragged_paged_prefill_attention`` — replacing the
  old one-request-at-a-time chunk loop that made prefill the serving
  bottleneck (BENCH_SERVING showed it 4× slower than the dense path);
- a **decode step**: every slot advances a BLOCK of ``decode_block``
  tokens per call (an on-device ``fori_loop``, amortizing the host
  round-trip), attending over its own pages via
  ``decode_attention.ragged_paged_decode_attention``.

All shapes are static: ``num_slots``, the prefill chunk, and pow2-
bucketed block-table gather widths that track the LIVE high-water mark
(so work follows live tokens, not slot capacity, even on the lax
fallback). The cache pages are **donated** into both steps, and
:meth:`ServingEngine.warmup` precompiles every bucket — decode AND
prefill — so steady-state serving triggers zero recompiles and zero
cache copies (a :class:`~paddle_tpu.observability.RecompileDetector`
wired to the step proves it).

Prefill and decode **interleave** under a per-step token budget
(``prefill_budget``): each ``step()`` spends at most
``max(prefill_budget, prefill_chunk)`` prompt tokens on prefill before
running the decode block — the chunk floor is a single liveness lane
for budgets below one chunk — so a burst of long prompts cannot starve
in-flight decodes and vice versa.

Prefix sharing: admission maps published prompt-prefix pages straight
into the new slot's block table (refcount bump, prefill skipped for the
shared tokens — see ``paged_cache``) and the engine performs the single
copy-on-write page copy a borrowed *tail* page requires before the
slot's first write.

Int8 paged KV (ISSUE 13): ``cache_dtype=jnp.int8`` stores the page
pool quantized with per-token-row fp32 scales (``paged_cache``) —
roughly half the HBM per live token of bf16, so the same pool hosts
~2x the slots — and both fixed-shape steps write int8 rows + scales
and attend through the **dequant-attend** kernel variants (scales
fused into the QK/PV products inside the online-softmax page stream;
no fp page materialized). The PR 7 cost model proves the bytes
reduction statically (`tools/cost_budgets.json` gates it in CI), and
migration shards carry page + scales under one hash.

Speculative decoding (ISSUE 13): pass ``draft_model``/``draft_params``
(+ ``spec_k``) and the decode phase becomes draft-then-verify: the
draft proposes ``spec_k`` greedy tokens per slot on its OWN paged
cache (same slot/page geometry, allocations in lockstep), and the
target verifies the whole chunk in ONE fixed-shape batched-prefill-
shaped step (`_verify_step_impl` — per-position greedy argmax). Each
round accepts the longest draft prefix the target agrees with plus the
target's next token, so **greedy outputs are bit-exact vs
non-speculative decoding**; rollback is a host-side cursor rewind
(rejected tokens' K/V stay masked behind the slot length and are
overwritten — pages were reserved up front, nothing leaks). Accept
quality lands in ``serving_spec_accept_rate`` /
``serving_spec_proposed_total`` / ``serving_spec_accepted_total`` and
per-request ``request_stats``; ``warmup()`` precompiles the draft /
draft-prefill / verify buckets so steady state still compiles nothing
(bucket-coverage lint proves it ahead of time). Speculation disables
prefix sharing (the draft must prefill every prompt token) and slot
migration (the draft cache is not carried in snapshots).

Tensor parallel (ISSUE 15): ``mesh=`` (or the shorthand ``tp=N``)
shards the whole paged stack over the mesh's ``tp`` axis — the page
pools hold per-shard head slices (``H/tp``), both fixed-shape steps run
under ``shard_map`` with head-major Megatron param slices
(``parallel/plan.serving_tp_plan``) and ONE ``psum`` per layer at the
attention output (the only collective: MLP/embeddings stay replicated —
decode is KV-bandwidth-bound, and the KV term is what tp divides).
Greedy tokens are identical to the tp=1 engine (int8 pools pmax each
token's abs-max so quantization matches bit-for-bit), slot migration
moves one sha256 shard per (page, tp shard), ``health()`` reports the
mesh shape, and ``warmup()`` covers the same bucket plan — zero
steady-state recompiles with tp on. ``tp_probe=True`` builds the
bench's busy-time vehicle: ONE shard's local computation on one device,
collectives elided.

Scheduling is SLO-aware by default (``scheduler_policy="slo"``):
priority lanes, TTFT deadlines with earliest-deadline-first boosting,
no head-of-line blocking (bounded-skip anti-starvation), and load
shedding via structured :class:`~paddle_tpu.serving.LoadShedError`
rejects instead of unbounded queueing. ``scheduler_policy="fifo"``
restores the plain head-blocking FIFO.

Metrics (observability registry): ``serving_requests_total``,
``serving_rejected_total``, ``serving_tokens_total``,
``serving_prefill_tokens_total`` (tokens actually COMPUTED — shared
prefix tokens are skipped and show up in
``serving_prefix_shared_tokens_total`` instead),
``serving_prompt_tokens_total`` (tokens submitted),
``serving_prefix_cow_total``, ``serving_steps_total``, and the latency
split: ``serving_queue_wait_seconds`` (submit → admit),
``serving_admit_to_first_token_seconds`` (admit → first token: the pure
prefill cost), ``serving_ttft_seconds`` (their end-to-end sum), plus
``serving_prefill_step_seconds``, ``serving_decode_step_seconds``,
``serving_slot_occupancy``, ``serving_page_utilization``, and
``serving_decode_recompiles_total`` via the detector.

Observability (ISSUE 10): pass ``tracer=`` for request-lifecycle
tracing — one root span per request with scheduler-decision /
prefix-share / CoW events, child spans per prefill chunk and decode
block (all host-side; the zero-recompile invariant holds with tracing
on); ``ttft_budget_s=`` arms an SLO burn-rate monitor over the TTFT
histogram (``slo_burn_rate`` gauge + edge-triggered
``slo_alerts_total`` + ``slo.alert`` trace spans); ``health()`` /
``start_exposition()`` serve live ``/metrics`` ``/healthz``
``/traces``.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.analysis.concurrency import guarded_by
from paddle_tpu.serving import decode_attention as DA
from paddle_tpu.serving.paged_cache import (_ROOT_KEY, _chain,
                                            PagedCacheConfig, PagedKVCache,
                                            payload_digest, quantize_kv)
from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                          Reject, Request, SLOScheduler,
                                          SlotState)

# TTFT/queue-wait histograms need sub-second resolution around
# interactive SLO budgets; the default span (100us..100s) is too coarse
# for p99 interpolation there. SLO budgets should sit ON an edge: the
# burn-rate monitor counts violations conservatively (count_over), so a
# mid-bucket budget can never see violations inside its own bucket —
# 4.0 is here for the CPU bench's stated budget.
_LATENCY_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.2, 0.35,
                    0.5, 0.75, 1.0, 1.5, 2.0, 3.0, 4.0, 5.0, 7.5, 10.0,
                    15.0, 30.0, 60.0)

MIGRATION_FORMAT = "paddle_tpu.serving.slot-migration-v1"

# fleet-global prefix reuse (ISSUE 20): committed prefix pages travel
# between replicas in the SAME per-(page, tp-shard) sha256 shard layout
# as slot migration, wrapped per published page with its chain key and
# token content so the importer can re-verify the whole hash chain
PREFIX_BUNDLE_FORMAT = "paddle_tpu.serving.prefix-pages-v1"


class SlotMigrationError(RuntimeError):
    """A slot snapshot cannot be restored: corrupt shard (sha256
    mismatch), incompatible cache geometry, or no free slot/pages on
    the target engine."""


@guarded_by("_health_lock", "_health_snap")
class ServingEngine:
    """Continuous-batching front end over a ``models.gpt.GPT``.

    ``submit()`` enqueues a request (optionally tagging an SLO lane and
    a TTFT deadline), ``step()`` advances the engine one iteration
    (admit + budgeted batched prefill + one decode block + evict), and
    ``generate_many()`` drives the loop to completion. Decoding is
    greedy — the deterministic serving mode the paged-vs-dense parity
    tests pin down.
    """

    def __init__(self, model, params, *, num_slots: int = 8,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 max_tokens_per_slot: Optional[int] = None,
                 prefill_chunk: int = 32, decode_block: int = 8,
                 prefill_budget: Optional[int] = None,
                 attn_impl: str = "auto", cache_dtype=None,
                 prefix_sharing: bool = True,
                 scheduler_policy: str = "slo",
                 lanes: Sequence[str] = ("interactive", "default", "batch"),
                 max_queue_depth: Optional[int] = None,
                 starvation_skips: int = 64,
                 registry=None, tracer=None,
                 ttft_budget_s: Optional[float] = None,
                 slo_windows=(60.0, 300.0),
                 draft_model=None, draft_params=None, spec_k: int = 4,
                 draft_cache_dtype=None,
                 snapshot_every_blocks: Optional[int] = None,
                 mesh=None, tp: Optional[int] = None,
                 tp_probe: bool = False,
                 anatomy_probe_every: Optional[int] = None,
                 tier: str = "colocated",
                 host_spill_pages: int = 0):
        cfg = model.cfg
        if cfg.pipeline or cfg.stacked_layers:
            raise ValueError(
                "ServingEngine needs the LayerList GPT layout; convert "
                "stacked/pipeline checkpoints for serving first")
        # -- disaggregation tier (ISSUE 19): a "prefill" engine runs
        # only the batched chunked prefill step and PARKS prefill-done
        # slots for handoff (poll_handoffs snapshots + releases them); a
        # "decode" engine accepts only restored slots and runs only the
        # decode block. "colocated" (default) is the classic engine —
        # every existing shape, bucket, and test is untouched.
        if tier not in ("colocated", "prefill", "decode"):
            raise ValueError(
                f"tier must be 'colocated', 'prefill' or 'decode', "
                f"got {tier!r}")
        if tier != "colocated" and draft_model is not None:
            raise ValueError(
                "speculative decoding does not compose with a "
                "disaggregated tier (draft caches do not migrate)")
        self.tier = tier
        # handoff-fallback slots allowed to decode on a prefill-tier
        # engine (restore_slot honors snap["decode_in_place"])
        self._decode_in_place: set = set()
        self.model = model
        self.params = params
        self.attn_impl = attn_impl
        self.prefill_chunk = int(prefill_chunk)
        self.decode_block = max(int(decode_block), 1)
        # -- tensor parallel (ISSUE 15): heads sharded H/tp over the
        # mesh's "tp" axis — per-shard page pools, both jitted steps
        # under shard_map with ONE psum at each layer's attention
        # output (the MLP/embeddings stay replicated: decode is
        # KV-bandwidth-bound, and that is what holds the sharded step
        # to a single collective kind). ``tp_probe=True`` instead runs
        # ONE shard's local computation on a single device with the
        # collectives elided — the bench's per-chip busy-time vehicle
        # (its outputs lack the other shards' head contributions).
        from paddle_tpu.core import mesh as mesh_lib
        mesh_tp = int(dict(mesh.shape).get("tp", 1)) if mesh is not None \
            else None
        if mesh is not None and tp is not None and int(tp) != mesh_tp:
            raise ValueError(f"tp={tp} disagrees with the mesh's tp "
                             f"axis ({mesh_tp})")
        if mesh is not None:
            tp = mesh_tp
        tp = int(tp or 1)
        if tp_probe:
            if tp < 2:
                raise ValueError("tp_probe needs tp >= 2")
            mesh = None            # one shard's work, one device
        elif tp > 1 and mesh is None:
            devs = jax.devices()
            if len(devs) < tp:
                raise ValueError(
                    f"tp={tp} needs {tp} devices, have {len(devs)}")
            mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(tp=tp),
                                      devices=devs[:tp])
        if tp > 1:
            if cfg.num_heads % tp:
                raise ValueError(
                    f"tp={tp} must divide num_heads={cfg.num_heads}")
            if draft_model is not None:
                raise ValueError(
                    "speculative decoding does not compose with tensor "
                    "parallelism (the draft cache is single-device)")
        # a mesh whose tp axis is 1 adds nothing here — drop it so
        # health()'s chip accounting cannot read replication-only axes
        # (dp etc.) as serving capacity
        self.mesh = mesh if tp > 1 else None
        self.tp = tp
        self.tp_probe = bool(tp_probe)
        self.tp_spmd = self.mesh is not None and tp > 1
        self._tp_heads = cfg.num_heads // tp
        # prefill tier + spmd tp: shard the MLP too (Megatron ffn_up
        # column / down row split) — prefill is flops-bound, so the MLP
        # matmuls are worth the second psum per layer. Gated to the
        # prefill tier so the colocated/decode step HLO (and every
        # pre-existing cost surface) stays byte-identical.
        self._mlp_sharded = self.tier == "prefill" and self.tp_spmd
        # -- speculative decoding (ISSUE 13): a draft model proposes
        # spec_k tokens per slot per round; the target verifies them all
        # in ONE fixed-shape batched-prefill-shaped step
        self.draft_model = draft_model
        self.draft_params = draft_params
        self.speculative = draft_model is not None
        self.spec_k = int(spec_k)
        if self.speculative:
            if draft_params is None:
                raise ValueError("draft_model needs draft_params")
            if draft_model.cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    "draft and target models must share a vocabulary "
                    f"({draft_model.cfg.vocab_size} != {cfg.vocab_size})")
            if self.spec_k < 2:
                raise ValueError("spec_k must be >= 2 (spec_k=1 is "
                                 "plain decoding — drop the draft)")
            # the draft cache must hold EVERY prompt token (the draft
            # prefills alongside the target), so target-side prefix
            # sharing — which skips prefilling shared tokens — would
            # desynchronize the two caches; speculation disables it
            prefix_sharing = False
        # prefill/decode interleaving budget: prompt tokens per step()
        # (default = one full batched call across every slot)
        self.prefill_budget = int(prefill_budget or
                                  num_slots * self.prefill_chunk)
        if max_tokens_per_slot is None:
            max_tokens_per_slot = cfg.max_position
        max_pages_per_slot = -(-max_tokens_per_slot // page_size)
        if num_pages is None:
            # enough for every slot full, +1 null page — callers can size
            # DOWN to bet on early EOS (that is the paging win)
            num_pages = num_slots * max_pages_per_slot + 1
        # like generate(cache_dtype=...): a bf16 page pool halves KV
        # gather traffic (softmax still runs fp32 inside the kernel);
        # cache_dtype=jnp.int8 stores quantized pages with per-token-row
        # fp32 scales and attends through the dequant-attend kernels —
        # HBM per live token roughly halves AGAIN vs bf16
        dtype = cache_dtype or params["wte"]["weight"].dtype
        # a probe engine's pool holds ONE shard's head slice; an spmd
        # engine's pool is globally shaped but placed sharded H/tp
        self.cache = PagedKVCache(PagedCacheConfig(
            num_layers=cfg.num_layers,
            num_heads=self._tp_heads if self.tp_probe else cfg.num_heads,
            head_dim=cfg.hidden_size // cfg.num_heads,
            num_slots=num_slots, page_size=page_size, num_pages=num_pages,
            max_pages_per_slot=max_pages_per_slot, dtype=dtype,
            share_prefix=prefix_sharing),
            mesh=mesh if self.tp_spmd else None,
            host_spill_pages=host_spill_pages)
        self.quantized = self.cache.config.quantized
        self.draft_cache = None
        self._draft_quantized = False
        if self.speculative:
            dcfg = draft_model.cfg
            ddtype = draft_cache_dtype or cache_dtype or \
                draft_params["wte"]["weight"].dtype
            # same slot/page geometry as the target cache: allocations
            # run in lockstep (reserve/free the same slots for the same
            # token counts), so target admission implies draft admission
            self.draft_cache = PagedKVCache(PagedCacheConfig(
                num_layers=dcfg.num_layers, num_heads=dcfg.num_heads,
                head_dim=dcfg.hidden_size // dcfg.num_heads,
                num_slots=num_slots, page_size=page_size,
                num_pages=num_pages,
                max_pages_per_slot=max_pages_per_slot, dtype=ddtype,
                share_prefix=False))
            self._draft_quantized = self.draft_cache.config.quantized
        if scheduler_policy == "slo":
            self.scheduler = SLOScheduler(
                num_slots, can_admit=self._can_admit, lanes=lanes,
                max_queue_depth=max_queue_depth,
                starvation_skips=starvation_skips)
        elif scheduler_policy == "fifo":
            self.scheduler = ContinuousBatchingScheduler(
                num_slots, can_admit=self._can_admit)
        else:
            raise ValueError(
                f"scheduler_policy must be 'slo' or 'fifo', "
                f"got {scheduler_policy!r}")

        from paddle_tpu import observability as obs
        self._reg = registry or obs.default()
        self.recompile_detector = obs.RecompileDetector(
            "serving_decode", warmup=1, registry=self._reg)
        # request-lifecycle tracing: one root span per request, children
        # per prefill chunk / decode block, scheduler verdicts as events.
        # All host-side — nothing below touches jitted code, so tracing
        # on/off cannot change compiled shapes (zero-recompile invariant
        # is RecompileDetector-asserted with tracing enabled in tests).
        self.tracer = tracer or obs.tracing.default()
        self._req_spans: Dict[int, object] = {}
        self._phase_acc: Dict[int, Dict[str, float]] = {}
        self.scheduler.event_cb = self._sched_event
        # SLO burn-rate monitor over the TTFT histogram: deadline
        # pressure becomes visible (gauge + alert counter + trace
        # events) BEFORE requests start getting shed
        self.ttft_budget_s = ttft_budget_s
        self.slo_monitor = None
        if ttft_budget_s is not None:
            self.slo_monitor = obs.BurnRateMonitor(
                "serving_ttft_seconds", ttft_budget_s,
                windows=slo_windows, registry=self._reg,
                tracer=self.tracer)
        # step-time anatomy (ISSUE 16): host gap / phase-split device
        # busy / host assembly per step, plus the sampled collective-
        # exposed probe below; the flight recorder rides along as the
        # replica's crash black box (the router dumps it on eject)
        self.anatomy = obs.StepAnatomy(registry=self._reg,
                                       tracer=self.tracer)
        self.flight = obs.FlightRecorder(
            "engine", anatomy=self.anatomy, registry=self._reg,
            tracer=self.tracer)
        if anatomy_probe_every is not None and anatomy_probe_every < 0:
            raise ValueError("anatomy_probe_every must be >= 0")
        # collective-exposed sampling: every N decode rounds an spmd
        # engine re-runs the SAME decode shapes through a collectives-
        # elided probe twin (the tp_probe discipline, in-engine); the
        # wall delta is the exposed collective time. 0 disables; the
        # default arms it only where there ARE collectives to expose.
        self.anatomy_probe_every = (
            anatomy_probe_every if anatomy_probe_every is not None
            else (64 if self.tp_spmd else 0))
        if not self.tp_spmd:
            self.anatomy_probe_every = 0
        self._decode_rounds = 0

        # step-side params: tp re-lays the attention projections out
        # head-major (qkv (D,3,H,Dh) col-sharded, out (H,Dh,D)
        # row-sharded — parallel/plan.serving_tp_plan, the SpecLayout
        # Megatron split at head granularity); tp=1 uses the model's
        # own tree untouched
        self._probe_params = None
        self._probe_pages = None
        if self.tp > 1:
            from paddle_tpu.parallel import plan as plan_lib
            tp_params = self._make_tp_params(params)
            if self.tp_spmd:
                if self.anatomy_probe_every:
                    # the collective probe's params: shard 0's local
                    # slice, taken host-side BEFORE the sharded
                    # device_put consumes the tree
                    self._probe_params = self._tp_shard_slice(
                        tp_params, 0)
                tp_plan = (plan_lib.serving_prefill_tp_plan()
                           if self._mlp_sharded
                           else plan_lib.serving_tp_plan())
                self._param_specs = tp_plan.params_specs(tp_params)
                self._step_params = jax.device_put(
                    tp_params,
                    plan_lib.named_shardings(mesh, self._param_specs))
            else:                  # probe: shard 0's local slice
                self._step_params = self._tp_shard_slice(tp_params, 0)
            # don't pin the caller's unsharded attention projections
            # for the engine's lifetime next to their sharded copies:
            # under tp, self.params IS the step-side (re-laid-out,
            # sharded) tree
            self.params = self._step_params
        else:
            self._step_params = params
        if self.tp_spmd:
            from jax.sharding import PartitionSpec as PSpec

            from paddle_tpu.core.compat import shard_map
            from paddle_tpu.parallel import plan as plan_lib
            rep = PSpec()
            self._page_specs = plan_lib.paged_pool_specs(self.cache.pages)
            step_specs = (self._param_specs, self._page_specs,
                          rep, rep, rep, rep)
            self.decode_step = jax.jit(shard_map(
                self._decode_step_impl, mesh=mesh, in_specs=step_specs,
                out_specs=(rep, self._page_specs), check_vma=False),
                donate_argnums=(1,))
            self.prefill_step = jax.jit(shard_map(
                self._prefill_step_impl, mesh=mesh, in_specs=step_specs,
                out_specs=(rep, self._page_specs), check_vma=False),
                donate_argnums=(1,))
        else:
            self.decode_step = jax.jit(self._decode_step_impl,
                                       donate_argnums=(1,))
            self.prefill_step = jax.jit(self._prefill_step_impl,
                                        donate_argnums=(1,))
        if self._probe_params is not None:
            # collectives-elided decode twin: ONE shard's local math on
            # one device against a dedicated zero page pool with the
            # per-shard head slice — same shapes per width bucket, so
            # warmup covers it and sampling stays zero-recompile
            self._probe_pages = self._make_probe_pool()
            self.decode_probe_step = jax.jit(
                self._decode_probe_step_impl, donate_argnums=(1,))
        if self.speculative:
            # draft pages donate into their own steps; the verify step
            # donates the TARGET pages exactly like prefill does
            self.draft_prefill_step = jax.jit(
                self._draft_prefill_step_impl, donate_argnums=(1,))
            self.draft_propose_step = jax.jit(
                self._draft_propose_step_impl, donate_argnums=(1,))
            self.verify_step = jax.jit(self._verify_step_impl,
                                       donate_argnums=(1,))
        self.copy_page_step = jax.jit(self._copy_page_impl,
                                      donate_argnums=(0,))
        # migration page IO (fleet drain): src/dst are traced scalars,
        # so ONE compile each covers every page ever moved
        self.read_page_step = jax.jit(self._read_page_impl)
        self.write_page_step = jax.jit(self._write_page_impl,
                                       donate_argnums=(0,))
        # HBM->host spill tier (ISSUE 20): the cache calls back through
        # the SAME warmed ("page_read",) signature when it pages a cold
        # published page out, so spill traffic compiles nothing
        self.cache.attach_spill_io(self._spill_read)
        # finished-request store for result(); pop-on-read + bounded, so
        # a server that only consumes step()'s return dict still cannot
        # grow host memory with the total requests ever served
        self._results: "OrderedDict[int, np.ndarray]" = OrderedDict()
        self._rejects: "OrderedDict[int, Reject]" = OrderedDict()
        self._stats: "OrderedDict[int, Dict[str, float]]" = OrderedDict()
        self._results_cap = max(64, 16 * num_slots)
        # filled by warmup(): compiled bucket signatures + their static
        # cost reports (the bucket-coverage proof reads warmup_plan()
        # when warmup has not run yet)
        self.warmed_signatures: set = set()
        self.bucket_costs: Dict[tuple, object] = {}
        # micro-checkpoints (fleet fault tolerance): every K decode
        # blocks an in-flight slot's snapshot_slot lands in a host-side
        # outbox the replica handle drains to the router — a crashed
        # replica's requests then warm-restore on a peer instead of
        # re-decoding from the prompt. Host-side page reads only
        # (("page_read",) is a warmed signature), so the zero-recompile
        # invariant holds with checkpointing on.
        if snapshot_every_blocks is not None:
            if self.speculative:
                raise ValueError(
                    "micro-checkpoints need slot migration, which "
                    "speculative engines do not support")
            if snapshot_every_blocks < 1:
                raise ValueError("snapshot_every_blocks must be >= 1")
        self.snapshot_every_blocks = snapshot_every_blocks
        self._micro_snaps: Dict[int, Dict] = {}
        self._last_snap_blocks: Dict[int, int] = {}
        # externally-minted trace ids (router propagation) so
        # request_stats carries them even with tracing disabled
        self._ext_trace: Dict[int, int] = {}
        self.migrated_in_total = 0
        self.migrated_out_total = 0
        # resource-headroom plane (ISSUE 16): static per-bucket flops x
        # observed step counts vs elapsed busy time, with the best
        # per-call rate as the utilization ceiling (the high-water mark
        # this hardware + bucket set actually demonstrated)
        self._busy_s = 0.0
        self._flops_done = 0.0
        self._flops_rate_peak = 0.0
        self._anat_steps = 0
        # health(): a fleet router polls from ITS thread while step()
        # mutates the scheduler/cache books — the engine publishes a
        # consistent snapshot at safe points and health() only ever
        # reads that, under a lock (never the live books)
        self._health_lock = threading.Lock()
        self._health_snap: Dict[str, object] = {}
        self._refresh_health()

    # -- request surface --------------------------------------------------

    def _can_admit(self, req) -> bool:
        return self.cache.can_reserve(req.total_tokens, prompt=req.prompt)

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None, *, lane: str = "default",
               ttft_deadline_s: Optional[float] = None,
               trace_id: Optional[int] = None) -> int:
        """Enqueue a request; returns its rid. ``lane`` and
        ``ttft_deadline_s`` feed the SLO scheduler (ignored under
        ``scheduler_policy="fifo"``). ``trace_id`` adopts an externally
        minted trace id (the fleet router's) for the request's root
        span instead of starting a fresh trace, and is carried through
        ``request_stats`` even with tracing off — one Perfetto timeline
        then shows the request crossing router and replica. Raises
        :class:`~paddle_tpu.serving.LoadShedError` (with a structured
        :class:`~paddle_tpu.serving.Reject`) when the scheduler sheds
        the request instead of queueing it."""
        from paddle_tpu.serving.scheduler import LoadShedError
        if self.tier == "decode":
            # fresh prompts would run prefill buckets this tier never
            # warms; the two-tier router routes prompts to the prefill
            # tier and this engine only ever sees restore_slot
            raise ValueError(
                "decode-tier engines accept only restored slots "
                "(restore_slot), not fresh prompts")
        total = len(np.asarray(prompt).reshape(-1)) + max_new_tokens
        limit = min(self.cache.config.max_tokens_per_slot,
                    self.model.cfg.max_position)
        if total > limit:
            raise ValueError(f"request needs {total} tokens > per-slot "
                             f"limit {limit}")
        if self.cache.config.pages_for(total) > self.cache.config.num_pages - 1:
            raise ValueError("request exceeds the whole page pool")
        try:
            rid = self.scheduler.submit(prompt, max_new_tokens, eos_id,
                                        lane=lane,
                                        ttft_deadline_s=ttft_deadline_s)
        except LoadShedError as e:
            self._reg.counter("serving_rejected_total",
                              "requests load-shed instead of queued").inc(
                                  reason=e.reject.reason)
            if self.tracer.enabled:
                # shed-at-submit: a zero-length request span whose
                # attributes carry the structured verdict
                self.tracer.record_span(
                    "serving.request", duration_s=0.0, status="shed",
                    lane=lane, shed_reason=e.reject.reason,
                    queue_depth=e.reject.queue_depth,
                    est_ttft_s=round(e.reject.est_ttft_s, 6))
            raise
        self._reg.counter("serving_requests_total",
                          "requests submitted to the engine").inc()
        self._reg.counter("serving_prompt_tokens_total",
                          "prompt tokens submitted").inc(total -
                                                         max_new_tokens)
        self._phase_acc[rid] = {"prefill_s": 0.0, "decode_s": 0.0,
                                "prefill_chunks": 0.0,
                                "decode_blocks": 0.0,
                                "shared_tokens": 0.0,
                                "spec_proposed": 0.0,
                                "spec_accepted": 0.0}
        if trace_id is not None:
            self._ext_trace[rid] = int(trace_id)
        if self.tracer.enabled:
            root = self.tracer.start_span(
                "serving.request", trace_id=trace_id, rid=rid, lane=lane,
                prompt_tokens=total - max_new_tokens,
                max_new_tokens=max_new_tokens)
            root.add_event("submitted",
                           queue_depth=self.scheduler.queue_depth())
            self._req_spans[rid] = root
        self._refresh_health()
        return rid

    def _sched_event(self, rid: int, name: str, **attrs):
        """Scheduler decision → event on the request's trace span."""
        root = self._req_spans.get(rid)
        if root is not None:
            root.add_event(name, **attrs)

    def result(self, rid: int) -> Optional[np.ndarray]:
        """Generated tokens for a finished request (None while running
        or already consumed). Pop-on-read, and the store keeps only the
        most recent finishers (``step()``'s return dict is the primary
        delivery path) — consume results promptly."""
        return self._results.pop(rid, None)

    def reject_reason(self, rid: int) -> Optional[Reject]:
        """Structured reject for a request shed AFTER queueing (its
        TTFT deadline expired before admission); pop-on-read."""
        return self._rejects.pop(rid, None)

    def request_stats(self, rid: int) -> Optional[Dict[str, float]]:
        """Per-request latency record for a finished request — the wall
        split (``ttft_s``, ``queue_wait_s``, ``prefill_s``) plus the
        per-phase breakdown sourced from the request's trace spans:
        ``prefill_compute_s`` / ``decode_s`` (time inside the batched
        fixed-shape calls), ``prefill_chunks`` / ``decode_blocks``,
        ``shared_tokens`` (prefix-share savings), ``tokens``, and
        ``trace_id`` (0 when tracing was off) — the exact per-request
        numbers behind the histogram aggregates (SLO audits read these;
        pop-on-read, bounded like ``result``)."""
        return self._stats.pop(rid, None)

    def _refresh_health(self):
        """Recompute the health snapshot from the live scheduler/cache
        books. Called only from the engine's own thread at consistent
        points (construction, submit, end of step, migration), so the
        reads here never race the step loop; cross-thread readers get
        the last published snapshot via :meth:`health`."""
        h: Dict[str, object] = {
            "slot_occupancy": self.scheduler.occupancy(),
            "queue_depth": self.scheduler.queue_depth(),
            "page_utilization": self.cache.utilization(),
            "free_slots": len(self.scheduler.free_slots()),
            "recompiles": self.recompile_detector.recompiles,
            "requests_in_flight": len(self.scheduler.active_slots()),
            "steps": int(self._reg.counter(
                "serving_steps_total").value()),
            # mesh shape (ISSUE 15): the autoscaler and /healthz must
            # distinguish a 4-chip tp replica from a 1-chip one. The
            # chip count is the TP degree, not the raw mesh size — a
            # dp axis only replicates this engine's work
            "tp": self.tp,
            "mesh_devices": self.tp if self.tp_spmd else 1,
            "tp_probe": self.tp_probe,
            # disaggregation tier: the two-tier router and the
            # autoscaler key placement/scaling decisions off this
            "tier": self.tier,
            # hierarchical KV (ISSUE 20): bumps on ANY publication
            # change in EITHER tier (device index or host spill pool),
            # so fleet affinity snapshots can detect a replica that
            # dropped a prefix it used to advertise
            "prefix_gen": int(self.cache.prefix_gen),
        }
        if self.slo_monitor is not None:
            h["slo"] = self.slo_monitor.status()
        h["headroom"] = self._headroom()
        with self._health_lock:
            self._health_snap = h

    def _headroom(self) -> Dict[str, float]:
        """The resource-headroom plane (ISSUE 16): per-resource spare
        capacity in [0, 1] — the routing signal the two-tier dispatcher
        reads (prefill placement wants flops headroom, decode placement
        wants page/slot headroom), published as ``serving_headroom``
        gauges and aggregated fleet-wide by ``FleetMonitor``."""
        util = self.cache.utilization()
        free = len(self.scheduler.free_slots())
        s_tot = self.scheduler.num_slots
        cap_b = self.cache.capacity_bytes()
        live_b = self.cache.live_bytes()
        # flops utilization: static bucket flops actually retired per
        # busy second, against the best per-call rate ever observed —
        # 0.0 (full headroom) until warmup(cost_gauges=True) priced the
        # buckets and a step ran
        flops_util = 0.0
        if self._busy_s > 0 and self._flops_rate_peak > 0:
            flops_util = min(
                (self._flops_done / self._busy_s)
                / self._flops_rate_peak, 1.0)
        tokens = self._reg.counter("serving_tokens_total").value()
        saved = self._reg.counter(
            "serving_prefix_shared_tokens_total").value()
        head = {
            "flops_utilization": round(flops_util, 6),
            "flops": round(1.0 - flops_util, 6),
            "pages": round(max(1.0 - util, 0.0), 6),
            "slots": round(free / s_tot, 6),
            "hbm": round(max(1.0 - (live_b / cap_b if cap_b else 0.0),
                             0.0), 6),
            "hbm_live_bytes": int(live_b),
            "hbm_capacity_bytes": int(cap_b),
            "flops_per_busy_s": (self._flops_done / self._busy_s
                                 if self._busy_s > 0 else 0.0),
            "prefix_saved_per_token": round(
                saved / tokens if tokens else 0.0, 6),
        }
        # host spill tier: headroom 1.0 when the tier is off (it can
        # never veto anything), else spare host-pool capacity — the
        # autoscaler's scale-in veto reads this so a fleet does not
        # shrink away the replica holding everyone's cold prefixes
        pool = self.cache.spill_pool
        if pool is None:
            head["spill"] = 1.0
            head["spill_pages"] = 0
            head["spill_bytes"] = 0
        else:
            head["spill"] = round(
                max(1.0 - len(pool) / pool.capacity, 0.0), 6)
            head["spill_pages"] = len(pool)
            head["spill_bytes"] = int(pool.spilled_bytes())
        g = self._reg.gauge(
            "serving_headroom",
            "spare capacity per resource (1 = idle, 0 = saturated)")
        for res in ("flops", "pages", "slots", "hbm", "spill"):
            g.set(head[res], resource=res)
        self._reg.gauge(
            "serving_spill_pages",
            "published KV pages resident in the host spill pool"
        ).set(head["spill_pages"])
        self._reg.gauge(
            "serving_spill_bytes",
            "bytes of KV (incl. int8 scale rows) in the host spill pool"
        ).set(head["spill_bytes"])
        self._reg.gauge(
            "serving_flops_utilization",
            "retired static flops per busy second / best observed rate"
        ).set(flops_util)
        self._reg.gauge(
            "serving_prefix_saved_per_token",
            "prefill tokens skipped via prefix sharing per served token"
        ).set(head["prefix_saved_per_token"])
        return head

    def _note_busy(self, sigs, dur: float):
        """Headroom accounting for one jitted call: busy seconds plus
        the static flops of the bucket(s) it retired (when warmup
        priced them)."""
        self._busy_s += dur
        flops = 0.0
        for sig in sigs:
            cost = self.bucket_costs.get(sig)
            if cost is not None:
                flops += cost.total_flops
        if flops > 0:
            self._flops_done += flops
            if dur > 0:
                self._flops_rate_peak = max(self._flops_rate_peak,
                                            flops / dur)

    def health(self) -> Dict[str, object]:
        """Structured live health (the ``/healthz`` payload and the
        fleet router's load signal): slot occupancy, queue depth, page
        utilization, free slots, recompile count, and the SLO monitor's
        burn/alert state when one is configured. Safe (and cheap) to
        call from any thread WHILE ``step()`` runs: it returns the
        engine's last published snapshot under a lock rather than
        reading the scheduler's live queue/slot books mid-mutation."""
        with self._health_lock:
            return dict(self._health_snap)

    def start_exposition(self, port: int = 0, host: str = "127.0.0.1"):
        """Opt-in live exposition for THIS engine: starts a background
        :class:`~paddle_tpu.observability.ExpositionServer` over the
        engine's registry + tracer with the engine registered as the
        ``serving`` health provider. Port 0 (default) binds an
        ephemeral port — read ``server.port``. Caller stops it."""
        from paddle_tpu import observability as obs
        srv = obs.ExpositionServer(registry=self._reg,
                                   tracer=self.tracer,
                                   port=port, host=host)
        srv.add_health("serving", self.health)
        srv.add_postmortem("serving", self.flight.bundles)
        return srv.start()

    # -- engine loop ------------------------------------------------------

    def step(self) -> Dict[int, np.ndarray]:
        """One engine iteration: shed expired-deadline queue entries,
        admit into free slots, advance every admitted request's prefill
        under the interleaving budget, advance every decoding slot one
        block, evict finished sequences. Returns ``{rid: generated
        tokens}`` for requests that finished now."""
        finished: Dict[int, np.ndarray] = {}
        self._anat_steps += 1
        self.anatomy.begin_step(self._anat_steps)
        step_tokens = 0
        if isinstance(self.scheduler, SLOScheduler):
            for req in self.scheduler.shed_expired():
                rej = Reject("deadline_expired", req.lane,
                             self.scheduler.queue_depth(),
                             self.scheduler.est_ttft_s(), 0.001)
                self._rejects[req.rid] = rej
                while len(self._rejects) > self._results_cap:
                    self._rejects.popitem(last=False)
                self._reg.counter("serving_rejected_total",
                                  "requests load-shed instead of queued"
                                  ).inc(reason=rej.reason)
                self._phase_acc.pop(req.rid, None)
                self._ext_trace.pop(req.rid, None)
                root = self._req_spans.pop(req.rid, None)
                if root is not None:
                    root.add_event("shed", reason=rej.reason,
                                   deadline_s=req.ttft_deadline_s)
                    root.finish(status="shed")
        budget = self.prefill_budget
        prefilled_any = False
        while True:  # admissions can cascade as early-EOS slots free up
            # pages are reserved inside the admit callback, so each
            # can_admit check sees the pool net of earlier admissions
            # in the same call (no over-commit on a down-sized pool)
            admitted = self.scheduler.admit(on_admit=self._on_admit)
            done = self._prefill_round(budget,
                                       allow_liveness=not prefilled_any)
            prefilled_any = prefilled_any or done > 0
            budget -= done
            finished.update(self._evict())
            if (not admitted and done == 0) or budget <= 0:
                break

        dslots = self.scheduler.decode_slots()
        if self.tier == "prefill":
            # prefill-done slots PARK for handoff (the replica handle
            # drains them via poll_handoffs); only the handoff-fallback
            # slots explicitly flagged decode-in-place decode here
            dslots = [i for i in dslots if i in self._decode_in_place]
        if dslots:
            # occupancy/utilization of the batch the decode step
            # actually runs with (recorded before eviction, which
            # empties finished slots' lengths)
            self._reg.gauge("serving_slot_occupancy",
                            "fraction of decode slots live").set(
                                len(dslots) / self.scheduler.num_slots)
            self._reg.gauge("serving_page_utilization",
                            "live tokens / page-pool capacity").set(
                                self.cache.utilization())
            if self.speculative:
                kept = self._speculative_round(dslots)
            else:
                kept = self._decode_round(dslots)
            step_tokens += kept
            self._reg.counter("serving_tokens_total",
                              "decode tokens produced").inc(kept)
            self._reg.counter("serving_steps_total").inc()
            self.recompile_detector.check()
            finished.update(self._evict())
            if self.snapshot_every_blocks is not None:
                self._take_micro_snapshots()

        if self.slo_monitor is not None:
            self.slo_monitor.check()
        if prefilled_any or dslots:
            self.anatomy.end_step(tokens=step_tokens)
        else:
            # an idle tick is not a serving step: recording it would
            # count queue-empty waiting as "host gap"
            self.anatomy.cancel_step()
        self._refresh_health()
        with self._health_lock:
            snap = self._health_snap
        self.flight.note(snap)
        return finished

    def _decode_round(self, dslots) -> int:
        """Advance every decoding slot one block of ``decode_block``
        tokens through the jitted decode step; returns tokens kept."""
        n = self.decode_block
        s_tot = self.scheduler.num_slots
        tokens = np.zeros((s_tot,), np.int32)
        active = np.zeros((s_tot,), np.int32)
        for i in dslots:
            tokens[i] = self.scheduler.slots[i].generated[-1]
            active[i] = 1
        w = self._pow2_width(max(
            self.cache.config.pages_for(
                int(self.cache.lengths[i]) + n) for i in dslots))
        t0 = time.monotonic()
        out, self.cache.pages = self.decode_step(
            self._step_params, self.cache.pages,
            jnp.asarray(self.cache.block_tables[:, :w]),
            jnp.asarray(self.cache.lengths), jnp.asarray(tokens),
            jnp.asarray(active))
        out = np.asarray(out)                    # (S, decode_block)
        t1 = time.monotonic()
        self._reg.histogram(
            "serving_decode_step_seconds",
            "wall time per decode block (sync included)").observe(
                t1 - t0)
        self.anatomy.add_phase("decode", t0, t1)
        self._note_busy((("decode", w),), t1 - t0)
        self._decode_rounds += 1
        if self.anatomy_probe_every and self._probe_pages is not None \
                and self._decode_rounds % self.anatomy_probe_every == 0:
            # collective-exposed sample: the SAME decode shapes through
            # the collectives-elided probe twin (zero probe pool, shard
            # 0's params); every shape below is a warmed
            # ("decode_probe", w) bucket, so steady state compiles
            # nothing — the RecompileDetector asserts it
            p0 = time.monotonic()
            pout, self._probe_pages = self.decode_probe_step(
                self._probe_params, self._probe_pages,
                jnp.asarray(self.cache.block_tables[:, :w]),
                jnp.asarray(self.cache.lengths), jnp.asarray(tokens),
                jnp.asarray(active))
            np.asarray(pout)                     # sync the probe wall
            p1 = time.monotonic()
            self.anatomy.set_collective(t1 - t0, p1 - p0)
        tr_on = self.tracer.enabled
        kept = 0
        for i in dslots:
            st = self.scheduler.slots[i]
            req = st.request
            budget_i = req.max_new_tokens - len(st.generated)
            kept_i = 0
            for j in range(min(n, budget_i)):
                tok = int(out[i, j])
                st.generated.append(tok)
                kept_i += 1
                if req.eos_id is not None and tok == req.eos_id:
                    break
            kept += kept_i
            if not st.finished():
                # device advanced this slot the full block
                self.cache.lengths[i] += n
            acc = self._phase_acc.get(req.rid)
            if acc is not None:
                acc["decode_s"] += t1 - t0
                acc["decode_blocks"] += 1
            if tr_on:
                # lanes run in the same batched call, so the spans
                # share the interval — a parallel track per request
                self.tracer.record_span(
                    "serving.decode_block", start=t0, end=t1,
                    parent=self._req_spans.get(req.rid),
                    slot=i, tokens=kept_i)
        return kept

    def _speculative_round(self, dslots) -> int:
        """One speculative decode round (ISSUE 13): the draft model
        proposes ``spec_k`` greedy tokens per slot on its own paged
        cache, the target verifies the whole chunk ``[pending, d_1 ..
        d_{k-1}]`` in ONE fixed-shape batched-prefill-shaped step
        (per-position greedy argmax), and each slot accepts the longest
        prefix of draft tokens the target agrees with PLUS the target's
        own next token — so every accepted token is exactly what
        non-speculative greedy decoding would have produced (the
        bit-exactness gate), and each round yields 1..spec_k tokens.

        Rollback is a host-side cursor rewind: both caches advance
        their write cursors by only the accepted inputs; rejected
        tokens' K/V stay behind the slot length (masked as dead by the
        ragged kernels, overwritten by the next round) and their pages
        were part of the slot's up-front all-or-nothing reservation, so
        nothing leaks. Returns tokens kept."""
        n = self.spec_k
        s_tot = self.scheduler.num_slots
        tokens = np.zeros((s_tot,), np.int32)
        active = np.zeros((s_tot,), np.int32)
        nv = np.zeros((s_tot,), np.int32)
        for i in dslots:
            st = self.scheduler.slots[i]
            tokens[i] = st.generated[-1]
            active[i] = 1
            # never write past the slot's reservation: the chunk is
            # capped at the remaining generation budget
            nv[i] = min(n, st.request.max_new_tokens - len(st.generated))
        w = self._pow2_width(max(
            self.cache.config.pages_for(
                int(self.cache.lengths[i]) + n) for i in dslots))
        t0 = time.monotonic()
        nv_dev = jnp.asarray(nv)
        props_dev, self.draft_cache.pages = self.draft_propose_step(
            self.draft_params, self.draft_cache.pages,
            jnp.asarray(self.draft_cache.block_tables[:, :w]),
            jnp.asarray(self.draft_cache.lengths), jnp.asarray(tokens),
            jnp.asarray(active), nv_dev)
        # verify dispatches on the UN-materialized proposals (the chunk
        # is assembled inside the jitted step), so the draft->verify
        # chain never blocks on a host round-trip; the props transfer
        # below overlaps the verify compute
        ver, self.cache.pages = self.verify_step(
            self._step_params, self.cache.pages,
            jnp.asarray(self.cache.block_tables[:, :w]),
            jnp.asarray(self.cache.lengths), jnp.asarray(tokens),
            props_dev, nv_dev)
        props = np.asarray(props_dev)          # (S, spec_k) proposals
        # the props transfer completes when the draft chain has; the
        # clock read between the two materializations splits the round
        # into draft/verify anatomy without changing dispatch overlap
        t_mid = time.monotonic()
        ver = np.asarray(ver)                  # (S, spec_k) target greedy
        t1 = time.monotonic()
        self._reg.histogram(
            "serving_decode_step_seconds",
            "wall time per decode block (sync included)").observe(
                t1 - t0)
        self.anatomy.add_phase("draft", t0, t_mid)
        self.anatomy.add_phase("verify", t_mid, t1)
        self._note_busy((("draft", w), ("verify", w)), t1 - t0)
        tr_on = self.tracer.enabled
        kept = 0
        for i in dslots:
            st = self.scheduler.slots[i]
            req = st.request
            c = int(nv[i])
            # accept: t_1, plus t_{j+1} for every draft token d_j the
            # target reproduced — the canonical greedy accept-prefix
            a = 1
            while a < c and props[i, a - 1] == ver[i, a - 1]:
                a += 1
            kept_i = 0
            for j in range(a):
                tok = int(ver[i, j])
                st.generated.append(tok)
                kept_i += 1
                if req.eos_id is not None and tok == req.eos_id:
                    break
            kept += kept_i
            if not st.finished():
                # commit exactly the accepted inputs on BOTH caches;
                # the rejected tail is rewound by simply not advancing
                self.cache.lengths[i] += a
                self.draft_cache.lengths[i] += a
            proposed, accepted = max(c - 1, 0), a - 1
            self._reg.counter(
                "serving_spec_proposed_total",
                "draft tokens proposed for verification").inc(proposed)
            self._reg.counter(
                "serving_spec_accepted_total",
                "draft tokens the target verified and kept").inc(accepted)
            if proposed:
                self._reg.histogram(
                    "serving_spec_accept_rate",
                    "accepted/proposed draft tokens per verify round",
                    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                             0.875, 1.0)).observe(accepted / proposed)
            acc = self._phase_acc.get(req.rid)
            if acc is not None:
                acc["decode_s"] += t1 - t0
                acc["decode_blocks"] += 1
                acc["spec_proposed"] += proposed
                acc["spec_accepted"] += accepted
            if tr_on:
                self.tracer.record_span(
                    "serving.verify_block", start=t0, end=t1,
                    parent=self._req_spans.get(req.rid), slot=i,
                    tokens=kept_i, proposed=proposed, accepted=accepted)
        return kept

    def generate_many(self, prompts: Sequence, max_new_tokens: int = 32,
                      eos_id: Optional[int] = None,
                      max_steps: Optional[int] = None) -> List[np.ndarray]:
        """Submit ``prompts`` and run the loop until all finish; returns
        each request's generated tokens in submission order."""
        rids = [self.submit(p, max_new_tokens, eos_id) for p in prompts]
        collected: Dict[int, np.ndarray] = {}
        steps = 0
        while not self.scheduler.idle():
            collected.update(self.step())
            steps += 1
            if max_steps is not None and steps > max_steps:
                raise RuntimeError(f"no convergence in {max_steps} steps")
        for r in rids:          # consumed here; drop from the store
            self._results.pop(r, None)
        return [collected[r] for r in rids]

    def _evict(self) -> Dict[int, np.ndarray]:
        out = {}
        for slot, st in self.scheduler.evict_finished().items():
            self.cache.free_slot(slot)
            self._decode_in_place.discard(slot)
            if self.speculative:
                self.draft_cache.free_slot(slot)
            toks = np.asarray(st.generated, np.int32)
            req = st.request
            self._results[req.rid] = toks
            acc = self._phase_acc.pop(req.rid, None) or {}
            root = self._req_spans.pop(req.rid, None)
            # per-phase breakdown: the wall split (queue wait, admit →
            # first token, total) from the lifecycle timestamps plus the
            # compute split (prefill/decode seconds + chunk/block/share
            # counts) whose numbers ARE the request's trace spans —
            # identical values to summing its serving.prefill_chunk /
            # serving.decode_block children
            self._stats[req.rid] = {
                "ttft_s": st.first_token_at - req.submitted_at,
                "queue_wait_s": st.admitted_at - req.submitted_at,
                "prefill_s": st.first_token_at - st.admitted_at,
                "prefill_compute_s": acc.get("prefill_s", 0.0),
                "decode_s": acc.get("decode_s", 0.0),
                "prefill_chunks": acc.get("prefill_chunks", 0.0),
                "decode_blocks": acc.get("decode_blocks", 0.0),
                "shared_tokens": acc.get("shared_tokens", 0.0),
                "spec_proposed": acc.get("spec_proposed", 0.0),
                "spec_accepted": acc.get("spec_accepted", 0.0),
                "tokens": float(len(st.generated)),
                # handoff timestamps (ISSUE 19): monotonic stamps that
                # attribute the TTFT split's transfer time honestly —
                # 0.0 on requests that never crossed a tier boundary
                "prefill_done_s": acc.get("prefill_done_s", 0.0),
                "handoff_s": acc.get("handoff_s", 0.0),
                "decode_start_s": acc.get("decode_start_s", 0.0),
                "trace_id": float(root.trace_id) if root is not None
                else float(self._ext_trace.pop(req.rid, 0)),
            }
            self._ext_trace.pop(req.rid, None)
            self._micro_snaps.pop(req.rid, None)
            self._last_snap_blocks.pop(req.rid, None)
            if root is not None:
                root.add_event("finished", tokens=len(st.generated))
                root.set_attrs(
                    tokens=len(st.generated),
                    shared_tokens=int(acc.get("shared_tokens", 0)))
                root.finish()
            out[req.rid] = toks
        while len(self._results) > self._results_cap:
            self._results.popitem(last=False)   # oldest unconsumed
        while len(self._stats) > self._results_cap:
            self._stats.popitem(last=False)
        return out

    # -- prefill ----------------------------------------------------------

    def _spill_read(self, pid: int):
        """Cache spill callback: read one page to host through the
        warmed ``("page_read",)`` signature. Returns the host arrays
        the spill pool stores — ``(kv,)`` or ``(kv, scales)`` when
        quantized, so int8 scale rows always travel with their page."""
        page = self.read_page_step(self.cache.pages,
                                   jnp.asarray(pid, jnp.int32))
        if self.quantized:
            return (np.asarray(page[0]), np.asarray(page[1]))
        return (np.asarray(page),)

    def _restore_spilled(self, prompt, rid: int) -> int:
        """Admission-overlapped restore (the DeviceEmbeddingCache
        ``pull_async`` pattern): before reserving pages for ``prompt``,
        pull any host-spilled pages of its published chain back to the
        device so ``reserve`` maps them as ordinary shared-prefix hits.
        All ``device_put`` transfers start first (async, overlapping
        each other and this thread's bookkeeping), then each page is
        adopted + written through the warmed ``("page_write",)``
        signature — zero compiles, zero new shapes. A payload whose
        sha256 no longer matches is dropped and the chain walk stops
        there: a corrupt page must cause a re-prefill, never a
        corrupt hit."""
        pool = self.cache.spill_pool
        if pool is None:
            return 0
        plan = self.cache.spill_restore_plan(prompt)
        if not plan:
            return 0
        entries, devs = [], []
        for ent in plan:
            if payload_digest(ent.payload) != ent.sha256:
                pool.pop(ent.key)
                self._reg.counter(
                    "serving_spill_corrupt_total",
                    "host-spilled pages refused on restore "
                    "(sha256 mismatch)").inc()
                break
            entries.append(ent)
            devs.append(tuple(jax.device_put(a) for a in ent.payload))
        nbytes = 0
        for ent, dv in zip(entries, devs):
            pid = self.cache.adopt_published_page(ent.key, ent.tokens)
            self.cache.pages = self.write_page_step(
                self.cache.pages, jnp.asarray(pid, jnp.int32), *dv)
            nbytes += ent.nbytes
        if entries:
            pool.note_restored(len(entries), nbytes)
            self._reg.counter(
                "serving_spill_restored_pages_total",
                "host-spilled pages restored to HBM on a prefix hit"
            ).inc(len(entries))
            self._reg.counter(
                "serving_spill_restored_bytes_total",
                "bytes restored from the host spill pool"
            ).inc(nbytes)
            root = self._req_spans.get(rid)
            if root is not None:
                root.add_event("spill_restored", pages=len(entries),
                               bytes=nbytes)
        return len(entries)

    def _on_admit(self, slot: int, req):
        """Admission callback: reserve pages (mapping any published
        shared prefix), seed the slot's prefill cursor past the shared
        tokens, and record the queue-wait half of the TTFT split."""
        self._restore_spilled(req.prompt, req.rid)
        shared = self.cache.reserve(slot, req.total_tokens,
                                    prompt=req.prompt)
        if self.speculative:
            # lockstep reservation: same geometry + same alloc/free
            # history as the target cache, so this cannot overflow when
            # the target reserve succeeded (sharing is off — the draft
            # prefills the whole prompt, so nothing is skipped)
            self.draft_cache.reserve(slot, req.total_tokens)
        st = self.scheduler.slots[slot]
        st.prefilled = shared
        if shared:
            self._reg.counter(
                "serving_prefix_shared_tokens_total",
                "prompt tokens skipped via shared prefix pages").inc(shared)
        self._reg.histogram(
            "serving_queue_wait_seconds",
            "submit -> slot admission wait",
            buckets=_LATENCY_BUCKETS).observe(
                max(st.admitted_at - req.submitted_at, 0.0))
        acc = self._phase_acc.get(req.rid)
        if acc is not None:
            acc["shared_tokens"] = float(shared)
        root = self._req_spans.get(req.rid)
        if root is not None:
            root.add_event("admitted", slot=slot, queue_wait_s=round(
                max(st.admitted_at - req.submitted_at, 0.0), 6))
            if shared:
                root.add_event("prefix_shared", tokens=shared)

    def _prefill_round(self, budget: int,
                       allow_liveness: bool = True) -> int:
        """Advance in-prefill slots' next prompt chunks through the
        batched fixed-shape prefill step, spending at most ``budget``
        prompt tokens. Returns tokens computed. Slots whose prompt
        completes get their first generated token from the same call
        (closing the admit→first-token half of the TTFT split).

        Each batched call computes up to ``lanes × prefill_chunk``
        tokens, so the lane count is capped by the budget left; when
        less than one chunk remains the round stops rather than
        overshoot — except the ``allow_liveness`` single-lane exception
        (used once per ``step()``), which keeps an admitted slot
        progressing even with ``prefill_budget < prefill_chunk``. Net
        per-step contract: at most ``max(prefill_budget,
        prefill_chunk)`` prompt tokens."""
        consumed = 0
        c = self.prefill_chunk
        cfgc = self.cache.config
        while budget - consumed > 0:
            pslots = [i for i in self.scheduler.active_slots()
                      if not self.scheduler.slots[i].prefill_done]
            if not pslots:
                break
            lane_cap = (budget - consumed) // c
            if lane_cap == 0:
                if consumed > 0 or not allow_liveness:
                    break
                lane_cap = 1    # the once-per-step liveness lane
            # when lanes must wait, run the slots closest to their first
            # token: that closes TTFTs soonest, and each completion
            # shrinks the set so no admitted slot waits forever
            if len(pslots) > lane_cap:
                pslots.sort(key=lambda i: int(
                    self.scheduler.slots[i].request.prompt.shape[0])
                    - self.scheduler.slots[i].prefilled)
                pslots = pslots[:lane_cap]
            # compact batch: pow2-bucketed over the number of slots
            # actually prefilling (a lone late admission does not pay
            # for num_slots lanes of attention); padding lanes are
            # inert (n_valid 0, null-page block tables)
            sb = self._pow2_count(len(pslots))
            tokens = np.zeros((sb, c), np.int32)
            starts = np.zeros((sb,), np.int32)
            nv = np.zeros((sb,), np.int32)
            bt_rows = np.zeros((sb, cfgc.max_pages_per_slot), np.int32)
            dbt_rows = np.zeros_like(bt_rows) if self.speculative else None
            for j, i in enumerate(pslots):
                st = self.scheduler.slots[i]
                pc = self.cache.pending_copy(i)
                if pc is not None:
                    # copy-on-write of a borrowed tail page, owed before
                    # this slot's first write lands in it
                    src, dst = pc
                    self.cache.pages = self.copy_page_step(
                        self.cache.pages, jnp.asarray(src, jnp.int32),
                        jnp.asarray(dst, jnp.int32))
                    self.cache.copy_done(i)
                    self._reg.counter(
                        "serving_prefix_cow_total",
                        "copy-on-write page copies for shared tails"
                    ).inc()
                    root = self._req_spans.get(st.request.rid)
                    if root is not None:
                        root.add_event("cow_copy", src_page=int(src),
                                       dst_page=int(dst))
                prompt = st.request.prompt
                lo = st.prefilled
                # borrower write isolation: the page this chunk starts
                # writing into must be slot-owned (a shared tail page
                # must have been CoW-resolved above, never written)
                assert self.cache.writable(i, lo // cfgc.page_size), \
                    f"slot {i} would write a borrowed page"
                n = min(c, int(prompt.shape[0]) - lo)
                tokens[j, :n] = prompt[lo:lo + n]
                starts[j] = lo
                nv[j] = n
                bt_rows[j] = self.cache.block_tables[i]
                if self.speculative:
                    dbt_rows[j] = self.draft_cache.block_tables[i]
            w = self._pow2_width(max(
                cfgc.pages_for(int(starts[j]) + int(nv[j]))
                for j in range(len(pslots))))
            t0 = time.monotonic()
            nxt, self.cache.pages = self.prefill_step(
                self._step_params, self.cache.pages,
                jnp.asarray(bt_rows[:, :w]),
                jnp.asarray(starts), jnp.asarray(tokens), jnp.asarray(nv))
            if self.speculative:
                # the draft cache ingests the SAME chunks so its pages
                # mirror the target's committed prefix (its next-token
                # output is discarded — proposals start at decode time)
                _, self.draft_cache.pages = self.draft_prefill_step(
                    self.draft_params, self.draft_cache.pages,
                    jnp.asarray(dbt_rows[:, :w]),
                    jnp.asarray(starts), jnp.asarray(tokens),
                    jnp.asarray(nv))
            nxt = np.asarray(nxt)
            now = time.monotonic()
            self._reg.histogram(
                "serving_prefill_step_seconds",
                "wall time per batched prefill call (sync included)"
            ).observe(now - t0)
            self.anatomy.add_phase("prefill", t0, now)
            self._note_busy((("prefill", w, sb),)
                            + ((("draft_prefill", w, sb),)
                               if self.speculative else ()), now - t0)
            call_tokens = 0
            tr_on = self.tracer.enabled
            for j, i in enumerate(pslots):
                st = self.scheduler.slots[i]
                rid = st.request.rid
                n = int(nv[j])
                st.prefilled += n
                self.cache.lengths[i] += n
                if self.speculative:
                    self.draft_cache.lengths[i] += n
                call_tokens += n
                self.cache.publish_prefix(i, st.request.prompt,
                                          st.prefilled)
                acc = self._phase_acc.get(rid)
                if acc is not None:
                    acc["prefill_s"] += now - t0
                    acc["prefill_chunks"] += 1
                if tr_on:
                    self.tracer.record_span(
                        "serving.prefill_chunk", start=t0, end=now,
                        parent=self._req_spans.get(rid), slot=i,
                        tokens=n, start_pos=st.prefilled - n)
                if st.prefill_done:
                    st.generated.append(int(nxt[j]))
                    st.first_token_at = now
                    if acc is not None:
                        acc["prefill_done_s"] = now
                    ttft = now - st.request.submitted_at
                    self._reg.histogram(
                        "serving_ttft_seconds",
                        "submit -> first token latency",
                        buckets=_LATENCY_BUCKETS).observe(ttft)
                    self._reg.histogram(
                        "serving_admit_to_first_token_seconds",
                        "admit -> first token (prefill cost, net of "
                        "queue wait)",
                        buckets=_LATENCY_BUCKETS).observe(
                            now - st.admitted_at)
                    self._reg.counter("serving_tokens_total").inc()
                    self.scheduler.note_ttft(ttft)
                    root = self._req_spans.get(rid)
                    if root is not None:
                        root.add_event("first_token",
                                       ttft_s=round(ttft, 6))
            consumed += call_tokens
            self._reg.counter(
                "serving_prefill_tokens_total",
                "prompt tokens actually computed by prefill (shared "
                "prefix tokens are skipped)").inc(call_tokens)
        return consumed

    def _pow2_width(self, need: int) -> int:
        """Pow2 page count covering ``need`` pages — the gathers (and
        the Pallas grids) then scale with the LIVE high-water mark, not
        full slot capacity, while the set of compiled shapes stays
        log-sized; :meth:`warmup` precompiles them all."""
        w = 1
        while w < need:
            w *= 2
        return min(w, self.cache.config.max_pages_per_slot)

    def _pow2_count(self, need: int) -> int:
        """Pow2 lane count for the compact prefill batch."""
        s = 1
        while s < need:
            s *= 2
        return min(s, self.scheduler.num_slots)

    def warmup_plan(self):
        """The signatures ``warmup()`` precompiles, in compile order:
        ``("decode", width)``, ``("prefill", width, lanes)``, and
        ``("copy_page",)`` — a speculative engine swaps the decode
        buckets for ``("draft", width)`` + ``("verify", width)`` and
        adds the draft's ``("draft_prefill", width, lanes)`` twins (the
        verify/draft buckets are part of the coverage proof like any
        other). Derived from the warmup-side doubling loops —
        :func:`~paddle_tpu.analysis.hlo_lint.serving_bucket_coverage`
        proves this plan covers :meth:`reachable_signatures`, turning
        the runtime zero-recompile invariant into an ahead-of-time
        proof."""
        c = self.cache.config
        s_tot = self.scheduler.num_slots
        widths, w = [], 1
        while w < c.max_pages_per_slot:
            widths.append(w)
            w *= 2
        widths.append(c.max_pages_per_slot)
        widths = sorted(set(widths))
        counts, s = [], 1
        while s < s_tot:
            counts.append(s)
            s *= 2
        counts.append(s_tot)
        counts = sorted(set(counts))
        plan = []
        for w in widths:
            if self.speculative:
                plan.append(("draft", w))
                plan.append(("verify", w))
            else:
                plan.append(("decode", w))
                if self._probe_params is not None:
                    # the collective probe twin samples the same width
                    # buckets; precompiling them keeps sampling
                    # zero-recompile in steady state
                    plan.append(("decode_probe", w))
            for sb in counts:
                plan.append(("prefill", w, sb))
                if self.speculative:
                    plan.append(("draft_prefill", w, sb))
        plan.append(("copy_page",))
        # migration page IO: scalar-indexed, so one signature each
        # covers every page a fleet drain ever reads or writes
        plan.append(("page_read",))
        plan.append(("page_write",))
        return [sig for sig in plan if self._tier_sig(sig)]

    def _tier_sig(self, sig) -> bool:
        """Tier filter over bucket signatures (ISSUE 19): a prefill
        replica warms only prefill + page-IO buckets, a decode replica
        only decode + page-IO buckets — the per-tier half of the
        bucket-coverage proof (plan == reachable per tier). Page IO and
        the CoW copy stay on both tiers: handoff reads pages on the
        prefill side and writes them on the decode side."""
        if self.tier == "prefill" and sig[0] in ("decode", "decode_probe"):
            return False
        if self.tier == "decode" and sig[0] == "prefill":
            return False
        return True

    def reachable_signatures(self):
        """Every bucket signature the steady-state ``step()`` loop can
        request, enumerated from the STEP-side bucketing functions
        (``_pow2_width`` over every possible live page count,
        ``_pow2_count`` over every in-prefill slot count) — the other
        half of the bucket-coverage proof. A speculative engine's
        decode phase requests draft + verify buckets instead of decode
        buckets, plus the draft-prefill twins."""
        c = self.cache.config
        widths = {self._pow2_width(n)
                  for n in range(1, c.max_pages_per_slot + 1)}
        counts = {self._pow2_count(n)
                  for n in range(1, self.scheduler.num_slots + 1)}
        if self.speculative:
            sigs = {("draft", w) for w in widths}
            sigs |= {("verify", w) for w in widths}
            sigs |= {("draft_prefill", w, sb)
                     for w in widths for sb in counts}
        else:
            sigs = {("decode", w) for w in widths}
            if self._probe_params is not None:
                sigs |= {("decode_probe", w) for w in widths}
        sigs |= {("prefill", w, sb) for w in widths for sb in counts}
        sigs.add(("copy_page",))
        sigs.add(("page_read",))
        sigs.add(("page_write",))
        return {sig for sig in sigs if self._tier_sig(sig)}

    def warmup(self, cost_gauges: bool = True):
        """Compile every decode AND prefill gather-width bucket plus the
        CoW page copy up front (all against the null page — no live
        state is touched), so a serving process takes its compiles at
        startup and the steady-state loop stays at ZERO recompiles.
        Records the compiled set in :attr:`warmed_signatures`.

        ``cost_gauges`` additionally lowers each bucket through the
        static cost model (tracing only — cheap next to the compile the
        bucket already pays) and publishes per-bucket flops / peak-HBM
        into ``serving_bucket_cost_flops`` /
        ``serving_bucket_cost_peak_hbm_bytes`` gauges (labels: phase,
        width, lanes), with the full reports kept in
        :attr:`bucket_costs` for budget audits."""
        s_tot = self.scheduler.num_slots
        zeros = jnp.zeros((s_tot,), jnp.int32)
        self.warmed_signatures = set()
        self.bucket_costs = {}
        for sig in self.warmup_plan():
            if sig[0] == "decode":
                w = sig[1]
                args = (self._step_params, self.cache.pages,
                        jnp.zeros((s_tot, w), jnp.int32), zeros, zeros,
                        zeros)
                if cost_gauges:
                    self._bucket_cost_gauges(sig, self.decode_step, args)
                _, self.cache.pages = self.decode_step(*args)
            elif sig[0] == "decode_probe":
                w = sig[1]
                args = (self._probe_params, self._probe_pages,
                        jnp.zeros((s_tot, w), jnp.int32), zeros, zeros,
                        zeros)
                if cost_gauges:
                    self._bucket_cost_gauges(sig, self.decode_probe_step,
                                             args)
                _, self._probe_pages = self.decode_probe_step(*args)
            elif sig[0] == "draft":
                w = sig[1]
                args = (self.draft_params, self.draft_cache.pages,
                        jnp.zeros((s_tot, w), jnp.int32), zeros, zeros,
                        zeros, zeros)
                if cost_gauges:
                    self._bucket_cost_gauges(sig, self.draft_propose_step,
                                             args)
                _, self.draft_cache.pages = self.draft_propose_step(*args)
            elif sig[0] == "verify":
                w = sig[1]
                args = (self._step_params, self.cache.pages,
                        jnp.zeros((s_tot, w), jnp.int32), zeros, zeros,
                        jnp.zeros((s_tot, self.spec_k), jnp.int32),
                        zeros)
                if cost_gauges:
                    self._bucket_cost_gauges(sig, self.verify_step, args)
                _, self.cache.pages = self.verify_step(*args)
            elif sig[0] == "prefill":
                w, sb = sig[1], sig[2]
                zb = jnp.zeros((sb,), jnp.int32)
                args = (self._step_params, self.cache.pages,
                        jnp.zeros((sb, w), jnp.int32), zb,
                        jnp.zeros((sb, self.prefill_chunk), jnp.int32),
                        zb)
                if cost_gauges:
                    self._bucket_cost_gauges(sig, self.prefill_step, args)
                _, self.cache.pages = self.prefill_step(*args)
            elif sig[0] == "draft_prefill":
                w, sb = sig[1], sig[2]
                zb = jnp.zeros((sb,), jnp.int32)
                args = (self.draft_params, self.draft_cache.pages,
                        jnp.zeros((sb, w), jnp.int32), zb,
                        jnp.zeros((sb, self.prefill_chunk), jnp.int32),
                        zb)
                if cost_gauges:
                    self._bucket_cost_gauges(sig, self.draft_prefill_step,
                                             args)
                _, self.draft_cache.pages = self.draft_prefill_step(*args)
            elif sig[0] == "page_read":
                jax.block_until_ready(self.read_page_step(
                    self.cache.pages, jnp.asarray(0, jnp.int32)))
            elif sig[0] == "page_write":
                c = self.cache.config
                blank = jnp.zeros((2, c.num_layers, c.page_size,
                                   c.num_heads, c.head_dim),
                                  jnp.int8 if self.quantized else c.dtype)
                if self.quantized:
                    blank_sc = jnp.zeros((2, c.num_layers, c.page_size),
                                         jnp.float32)
                    self.cache.pages = self.write_page_step(
                        self.cache.pages, jnp.asarray(0, jnp.int32),
                        blank, blank_sc)
                else:
                    self.cache.pages = self.write_page_step(
                        self.cache.pages, jnp.asarray(0, jnp.int32),
                        blank)
            else:
                self.cache.pages = self.copy_page_step(
                    self.cache.pages, jnp.asarray(0, jnp.int32),
                    jnp.asarray(0, jnp.int32))
            self.warmed_signatures.add(sig)

    def _bucket_cost_gauges(self, sig, step_fn, args):
        """Static cost of one warmup bucket -> observability gauges
        (lower-only; donation must not consume the live cache pages, so
        the lowering runs on abstracted args)."""
        from paddle_tpu.analysis import cost_model

        phase, width = sig[0], sig[1]
        lanes = sig[2] if len(sig) > 2 else self.scheduler.num_slots
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), args)
        cost = cost_model.estimate_cost(
            step_fn, *abstract, name=f"{phase}_w{width}")
        self.bucket_costs[sig] = cost
        labels = dict(phase=phase, width=str(width), lanes=str(lanes))
        self._reg.gauge(
            "serving_bucket_cost_flops",
            "static flops per compiled bucket (cost model)").set(
                cost.total_flops, **labels)
        self._reg.gauge(
            "serving_bucket_cost_peak_hbm_bytes",
            "static peak-HBM estimate per compiled bucket").set(
                cost.peak_hbm_bytes, **labels)

    # -- live migration (fleet drain) -------------------------------------

    def snapshot_slot(self, slot: int) -> Dict[str, object]:
        """Portable snapshot of one in-flight request: its full
        ``Request``/``SlotState`` bookkeeping plus the slot's live KV
        pages, each page carried as one sha256-digested shard (the
        resilience manifest discipline as a live-migration transfer
        format). The slot keeps running — snapshotting mutates nothing;
        pair with :meth:`release_slot` to actually drain it. A pending
        copy-on-write tail reads THROUGH to its source page (the dst
        has not been copied yet), so the snapshot always carries the
        logical KV content. An int8 cache's shards carry the pages'
        scale rows alongside the int8 KV — ONE shard, one hash over
        both, so a transfer can never split a page from its scales."""
        if self.speculative:
            raise SlotMigrationError(
                "speculative engines do not migrate slots (the draft "
                "cache state is not carried in a snapshot)")
        st = self.scheduler.slots[slot]
        if st is None:
            raise SlotMigrationError(f"slot {slot} is empty")
        req = st.request
        cfgc = self.cache.config
        length = int(self.cache.lengths[slot])
        n_live = cfgc.pages_for(length) if length else 0
        pids = [int(p) for p in self.cache.block_tables[slot, :n_live]]
        pc = self.cache.pending_copy(slot)
        if pc is not None:
            src, dst = pc
            pids = [src if p == dst else p for p in pids]
        shards, manifest = [], []
        hl = self._tp_heads
        for k, pid in enumerate(pids):
            page = self.read_page_step(self.cache.pages,
                                       jnp.asarray(pid, jnp.int32))
            if self.quantized:
                kv_all, sc_all = np.asarray(page[0]), np.asarray(page[1])
            else:
                kv_all, sc_all = np.asarray(page), None
            # per-shard shards (ISSUE 15): one sha256-digested shard per
            # (page, tp shard) — the head axis of (2, L, ps, H, Dh) cut
            # at mesh-shard boundaries, so each shard's KV travels and
            # verifies independently (an int8 shard carries the
            # replicated scale rows alongside — one hash over both, as
            # before)
            for t in range(self.tp if self.tp_spmd else 1):
                kv_t = kv_all[..., t * hl:(t + 1) * hl, :]
                shard = (kv_t, sc_all) if self.quantized else kv_t
                shards.append(shard)
                manifest.append({
                    "index": k,
                    "tp_shard": t,
                    "sha256": self._shard_digest(shard),
                    "bytes": self._shard_bytes(shard),
                })
        root = self._req_spans.get(req.rid)
        trace_id = (root.trace_id if root is not None
                    else self._ext_trace.get(req.rid, 0))
        acc = self._phase_acc.get(req.rid) or {}
        return {
            "format": MIGRATION_FORMAT,
            "geometry": {"num_layers": cfgc.num_layers,
                         "num_heads": cfgc.num_heads,
                         "head_dim": cfgc.head_dim,
                         "page_size": cfgc.page_size,
                         "dtype": str(jnp.dtype(cfgc.dtype)),
                         "tp": self.tp if self.tp_spmd else 1},
            "request": {"prompt": np.asarray(req.prompt, np.int32),
                        "max_new_tokens": req.max_new_tokens,
                        "eos_id": req.eos_id, "lane": req.lane,
                        "ttft_deadline_s": req.ttft_deadline_s,
                        "submitted_at": req.submitted_at},
            "state": {"generated": list(st.generated),
                      "prefilled": int(st.prefilled),
                      "length": length,
                      "admitted_at": st.admitted_at,
                      "first_token_at": st.first_token_at,
                      "phase_acc": dict(acc)},
            "trace_id": int(trace_id),
            "shards": shards,
            "manifest": manifest,
        }

    def _take_micro_snapshots(self):
        """Refresh the micro-checkpoint outbox: any in-flight decode
        slot that crossed another ``snapshot_every_blocks`` decode
        blocks gets a fresh :meth:`snapshot_slot` keyed by rid (newest
        wins — the outbox holds at most one snapshot per request)."""
        k = self.snapshot_every_blocks
        for i in self.scheduler.decode_slots():
            st = self.scheduler.slots[i]
            rid = st.request.rid
            acc = self._phase_acc.get(rid)
            blocks = int(acc["decode_blocks"]) if acc else 0
            if blocks and blocks % k == 0 \
                    and self._last_snap_blocks.get(rid) != blocks:
                self._micro_snaps[rid] = self.snapshot_slot(i)
                self._last_snap_blocks[rid] = blocks

    def poll_micro_snapshots(self) -> Dict[int, Dict]:
        """Drain the micro-checkpoint outbox (``{rid: snapshot}``,
        newest per request). The fleet replica handle forwards these to
        the router, which keeps the latest as the warm-restore seed
        bounding re-decode work after a crash."""
        out, self._micro_snaps = self._micro_snaps, {}
        return out

    def poll_handoffs(self) -> List:
        """Drain the prefill tier's handoff outbox (ISSUE 19): every
        PARKED prefill-done slot — prompt fully prefilled, first token
        emitted, not finished, not flagged decode-in-place — is
        :meth:`snapshot_slot`-ted (the exact migration transfer format:
        per-(page, tp-shard) sha256 shards) and released, freeing its
        slot for the next prompt immediately. Returns ``[(rid,
        snapshot), ...]``; the router streams each snapshot to a
        decode-tier replica's :meth:`restore_slot`. Empty on
        non-prefill tiers (and on an idle prefill tier)."""
        if self.tier != "prefill":
            return []
        out = []
        now = time.monotonic()
        for slot in list(self.scheduler.active_slots()):
            st = self.scheduler.slots[slot]
            if not st.prefill_done or st.finished() \
                    or slot in self._decode_in_place:
                continue
            rid = st.request.rid
            snap = self.snapshot_slot(slot)
            # the transfer-time half of the handoff timestamp split;
            # restore_slot stamps decode_start_s on the receiving tier
            snap["state"]["phase_acc"]["handoff_s"] = now
            self.release_slot(slot)
            out.append((rid, snap))
        self._refresh_health()
        return out

    def _shard_digest(self, shard) -> str:
        """sha256 of one migration shard — a quantized shard hashes the
        int8 KV AND its scale rows as one digest (a scale-only
        corruption is as fatal as a KV corruption and must be refused
        the same way)."""
        if self.quantized:
            kv, sc = shard
            h = hashlib.sha256(np.asarray(kv).tobytes())
            h.update(np.asarray(sc).tobytes())
            return h.hexdigest()
        return hashlib.sha256(np.asarray(shard).tobytes()).hexdigest()

    def _shard_bytes(self, shard) -> int:
        if self.quantized:
            return int(shard[0].nbytes + shard[1].nbytes)
        return int(shard.nbytes)

    def cancel_queued(self) -> List[Request]:
        """Pop every queued (not yet admitted) request and close its
        engine-side bookkeeping — the open root span finishes with
        status ``requeued`` (the fleet drain path re-submits the
        request on a peer, which starts a fresh span on the same
        trace), and the phase/trace maps are cleaned so nothing leaks.
        Returns the popped :class:`~paddle_tpu.serving.Request`s in
        queue order."""
        out: List[Request] = []
        sched = self.scheduler
        while sched.queue:
            r = sched.queue.popleft()
            self._phase_acc.pop(r.rid, None)
            self._ext_trace.pop(r.rid, None)
            root = self._req_spans.pop(r.rid, None)
            if root is not None:
                root.add_event("requeued")
                root.finish(status="requeued")
            out.append(r)
        self._refresh_health()
        return out

    def release_slot(self, slot: int):
        """Drop a migrated-out slot WITHOUT recording a result: free
        its pages, close its trace span as ``migrated``, and return the
        popped :class:`~paddle_tpu.serving.SlotState` (the drain path's
        receipt). The request lives on wherever its snapshot was
        restored."""
        st = self.scheduler.slots[slot]
        if st is None:
            raise SlotMigrationError(f"slot {slot} is empty")
        self.scheduler.slots[slot] = None
        self.cache.free_slot(slot)
        self._decode_in_place.discard(slot)
        if self.speculative:
            self.draft_cache.free_slot(slot)
        rid = st.request.rid
        self._phase_acc.pop(rid, None)
        self._ext_trace.pop(rid, None)
        self._micro_snaps.pop(rid, None)
        self._last_snap_blocks.pop(rid, None)
        root = self._req_spans.pop(rid, None)
        if root is not None:
            root.add_event("migrated_out", slot=slot,
                           tokens=len(st.generated))
            root.finish(status="migrated")
        self.migrated_out_total += 1
        self._reg.counter("serving_migrated_out_total",
                          "in-flight requests migrated away").inc()
        self._refresh_health()
        return st

    def restore_slot(self, snap: Dict[str, object], *,
                     parent_span=None) -> int:
        """Restore a :meth:`snapshot_slot` snapshot into a free slot of
        THIS engine and resume it exactly where it left off: every
        shard is sha256-verified before any page lands (corrupt
        transfers are refused, never decoded), pages are reserved
        all-or-nothing (unshared — the restored slot owns and may write
        every page), and decode continues from the carried token
        stream, so greedy outputs are byte-identical to an unmigrated
        run. Returns the request's NEW rid on this engine. The restored
        root span adopts the snapshot's ``trace_id`` (under
        ``parent_span`` when given), keeping one timeline across the
        migration."""
        if self.speculative:
            raise SlotMigrationError(
                "speculative engines do not migrate slots (the draft "
                "cache state is not carried in a snapshot)")
        if snap.get("format") != MIGRATION_FORMAT:
            raise SlotMigrationError(
                f"unknown snapshot format {snap.get('format')!r}")
        cfgc = self.cache.config
        geo = snap["geometry"]
        mine = {"num_layers": cfgc.num_layers, "num_heads": cfgc.num_heads,
                "head_dim": cfgc.head_dim, "page_size": cfgc.page_size,
                "dtype": str(jnp.dtype(cfgc.dtype)),
                "tp": self.tp if self.tp_spmd else 1}
        if geo != mine:
            # cross-tp restore is refused like any other geometry
            # mismatch: the shard layout IS part of the transfer format
            raise SlotMigrationError(
                f"cache geometry mismatch: snapshot {geo} != engine {mine}")
        shards, manifest = snap["shards"], snap["manifest"]
        if len(shards) != len(manifest):
            raise SlotMigrationError(
                f"{len(shards)} shards != {len(manifest)} manifest entries")
        for shard, rec in zip(shards, manifest):
            digest = self._shard_digest(shard)
            if digest != rec["sha256"]:
                raise SlotMigrationError(
                    f"shard {rec['index']} sha256 mismatch "
                    f"({digest[:12]}… != {rec['sha256'][:12]}…) — "
                    "refusing to restore a corrupt page")
        free = self.scheduler.free_slots()
        if not free:
            raise SlotMigrationError("no free slot to restore into")
        rq = snap["request"]
        prompt = np.asarray(rq["prompt"], np.int32).reshape(-1)
        total = int(prompt.shape[0]) + int(rq["max_new_tokens"])
        # shard count must agree with the carried live length AND fit
        # the reservation: an excess shard would index past the block
        # table's reserved entries (fill value 0) and overwrite the
        # null page other live requests gather from
        length = int(snap["state"]["length"])
        n_live = cfgc.pages_for(length) if length > 0 else 0
        tp_shards = self.tp if self.tp_spmd else 1
        if length < 0 or length > total or \
                len(shards) != n_live * tp_shards:
            raise SlotMigrationError(
                f"{len(shards)} shards for {length} live tokens "
                f"({tp_shards} per page) of a {total}-token "
                "reservation — snapshot state inconsistent, refusing "
                "to restore")
        if self.tier == "decode" and \
                int(snap["state"]["prefilled"]) < int(prompt.shape[0]):
            # a mid-prefill slot would run prefill buckets this tier
            # never warms; such snapshots restore on prefill/colocated
            # peers (which finish the prefill and hand off again)
            raise SlotMigrationError(
                "decode-tier engines restore only prefill-complete "
                f"slots ({int(snap['state']['prefilled'])} of "
                f"{int(prompt.shape[0])} prompt tokens prefilled)")
        if not self.cache.can_reserve(total):
            raise SlotMigrationError(
                f"no page capacity for {total} tokens")
        slot = free[0]
        # prompt=None: never map shared pages — the restore WRITES the
        # carried KV into every live page, so the slot must own them all
        self.cache.reserve(slot, total)
        stt = snap["state"]
        for k in range(n_live):
            # reassemble each page from its tp shards: hash-verified
            # head-axis chunks concatenated back in mesh-shard order
            chunks = shards[k * tp_shards:(k + 1) * tp_shards]
            dst = int(self.cache.block_tables[slot, k])
            if self.quantized:
                kv = np.concatenate([np.asarray(c[0]) for c in chunks],
                                    axis=3)
                sc = chunks[0][1]
                self.cache.pages = self.write_page_step(
                    self.cache.pages, jnp.asarray(dst, jnp.int32),
                    jnp.asarray(kv), jnp.asarray(sc))
            else:
                kv = np.concatenate([np.asarray(c) for c in chunks],
                                    axis=3)
                self.cache.pages = self.write_page_step(
                    self.cache.pages, jnp.asarray(dst, jnp.int32),
                    jnp.asarray(kv))
        self.cache.lengths[slot] = int(stt["length"])
        rid = next(self.scheduler._ids)     # fresh local rid, no collision
        req = Request(rid, prompt, int(rq["max_new_tokens"]),
                      rq["eos_id"], submitted_at=rq["submitted_at"],
                      lane=rq["lane"],
                      ttft_deadline_s=rq["ttft_deadline_s"])
        st = SlotState(req, generated=list(stt["generated"]),
                       prefilled=int(stt["prefilled"]),
                       admitted_at=stt["admitted_at"],
                       first_token_at=stt["first_token_at"])
        self.scheduler.slots[slot] = st
        if snap.get("decode_in_place") and self.tier == "prefill":
            # handoff fallback (ISSUE 19): no decode-tier capacity, so
            # this prefill engine decodes the slot itself — the one
            # documented exception to the prefill tier's decode gate
            # (and to its zero-recompile steady state)
            self._decode_in_place.add(slot)
        acc = {"prefill_s": 0.0, "decode_s": 0.0, "prefill_chunks": 0.0,
               "decode_blocks": 0.0, "shared_tokens": 0.0}
        acc.update(stt.get("phase_acc") or {})
        if acc.get("handoff_s") and not acc.get("decode_start_s"):
            # the decode-side half of the handoff timestamp split
            acc["decode_start_s"] = time.monotonic()
        self._phase_acc[rid] = acc
        trace_id = int(snap.get("trace_id") or 0)
        if trace_id:
            self._ext_trace[rid] = trace_id
        if self.tracer.enabled:
            root = self.tracer.start_span(
                "serving.request", parent=parent_span,
                trace_id=trace_id or None, rid=rid, lane=req.lane,
                migrated=True, prompt_tokens=int(prompt.shape[0]),
                max_new_tokens=req.max_new_tokens)
            root.add_event("migrated_in", slot=slot,
                           tokens=len(st.generated),
                           kv_tokens=int(stt["length"]))
            self._req_spans[rid] = root
        self.migrated_in_total += 1
        self._reg.counter("serving_migrated_in_total",
                          "in-flight requests migrated in").inc()
        self._refresh_health()
        return rid

    # -- fleet-global prefix reuse (ISSUE 20) ------------------------------

    def export_prefix_pages(self, digests) -> Optional[Dict[str, object]]:
        """Package the leading run of ``digests`` this engine still
        holds — device-published OR host-spilled — as a prefix-page
        bundle a peer can :meth:`import_prefix_pages`. Each page ships
        its chain key, its token content, and per-(page, tp-shard)
        sha256 shards (the slot-migration layout, so int8 scale rows
        travel inside the shard hash). Stops at the first digest this
        cache no longer holds: later pages could not chain onto a
        missing parent on the importer anyway. Returns None when
        nothing is exportable — the router degrades to re-prefill."""
        if not self.cache.config.share_prefix:
            return None
        cfgc = self.cache.config
        hl = self._tp_heads
        tp_shards = self.tp if self.tp_spmd else 1
        pages, total_bytes = [], 0
        for key in digests:
            key = int(key)
            hit = self.cache.lookup_prefix_page(key)
            if hit is None:
                break
            if hit[0] == "device":
                _, pid, tokens = hit
                page = self.read_page_step(self.cache.pages,
                                           jnp.asarray(pid, jnp.int32))
                if self.quantized:
                    kv_all = np.asarray(page[0])
                    sc_all = np.asarray(page[1])
                else:
                    kv_all, sc_all = np.asarray(page), None
            else:
                ent = hit[1]
                if payload_digest(ent.payload) != ent.sha256:
                    # a rotted host copy must never leave this replica;
                    # drop it so the advertisement goes stale too
                    self.cache.spill_pool.pop(ent.key)
                    self._reg.counter(
                        "serving_spill_corrupt_total",
                        "host-spilled pages refused on restore "
                        "(sha256 mismatch)").inc()
                    break
                tokens = ent.tokens
                kv_all = ent.payload[0]
                sc_all = ent.payload[1] if self.quantized else None
            shards, manifest = [], []
            for t in range(tp_shards):
                kv_t = kv_all[..., t * hl:(t + 1) * hl, :]
                shard = (kv_t, sc_all) if self.quantized else kv_t
                shards.append(shard)
                manifest.append({
                    "index": len(pages),
                    "tp_shard": t,
                    "sha256": self._shard_digest(shard),
                    "bytes": self._shard_bytes(shard),
                })
                total_bytes += manifest[-1]["bytes"]
            pages.append({"key": key,
                          "tokens": np.asarray(tokens, np.int32),
                          "shards": shards, "manifest": manifest})
        if not pages:
            return None
        self._reg.counter(
            "serving_prefix_exported_pages_total",
            "published prefix pages exported to fleet peers"
        ).inc(len(pages))
        return {
            "format": PREFIX_BUNDLE_FORMAT,
            "geometry": {"num_layers": cfgc.num_layers,
                         "num_heads": cfgc.num_heads,
                         "head_dim": cfgc.head_dim,
                         "page_size": cfgc.page_size,
                         "dtype": str(jnp.dtype(cfgc.dtype)),
                         "tp": tp_shards},
            "pages": pages,
            "bytes": int(total_bytes),
        }

    def import_prefix_pages(self, bundle) -> int:
        """Install a peer's :meth:`export_prefix_pages` bundle into the
        published-prefix index so the NEXT admission maps the pages as
        ordinary shared-prefix hits instead of re-prefilling. The whole
        bundle is verified before any page lands: format, cache
        geometry, the full publication hash chain from the root (each
        page's key must equal ``chain(parent, tokens)`` — a bundle
        claiming pages it cannot prove is refused), and every shard's
        sha256. Pages land all-or-nothing into idle free pages only
        (never evicting), through the warmed ``("page_write",)``
        signature. Returns pages installed (0 when everything was
        already held — not an error)."""
        if bundle is None or not self.cache.config.share_prefix:
            return 0
        if bundle.get("format") != PREFIX_BUNDLE_FORMAT:
            raise SlotMigrationError(
                f"unknown prefix bundle format {bundle.get('format')!r}")
        cfgc = self.cache.config
        tp_shards = self.tp if self.tp_spmd else 1
        mine = {"num_layers": cfgc.num_layers, "num_heads": cfgc.num_heads,
                "head_dim": cfgc.head_dim, "page_size": cfgc.page_size,
                "dtype": str(jnp.dtype(cfgc.dtype)),
                "tp": tp_shards}
        if bundle.get("geometry") != mine:
            raise SlotMigrationError(
                f"cache geometry mismatch: bundle "
                f"{bundle.get('geometry')} != engine {mine}")
        pages = bundle.get("pages") or []
        prev = _ROOT_KEY
        for page in pages:
            tokens = np.asarray(page["tokens"], np.int32).reshape(-1)
            if tokens.shape[0] != cfgc.page_size:
                raise SlotMigrationError(
                    f"prefix page carries {tokens.shape[0]} tokens "
                    f"(page_size {cfgc.page_size}) — refusing")
            key = int(page["key"])
            if _chain(prev, tokens) != key:
                raise SlotMigrationError(
                    "prefix bundle breaks the publication hash chain "
                    "— refusing to install unprovable pages")
            prev = key
            shards, manifest = page["shards"], page["manifest"]
            if len(shards) != tp_shards or len(manifest) != tp_shards:
                raise SlotMigrationError(
                    f"{len(shards)} shards for a {tp_shards}-shard "
                    "page — refusing")
            for shard, rec in zip(shards, manifest):
                digest = self._shard_digest(shard)
                if digest != rec["sha256"]:
                    raise SlotMigrationError(
                        f"prefix shard sha256 mismatch ({digest[:12]}… "
                        f"!= {rec['sha256'][:12]}…) — refusing to "
                        "install a corrupt page")
        held = self.cache.advertised_digests()
        install = [p for p in pages if int(p["key"]) not in held]
        if not install:
            return 0
        if len(install) > self.cache.idle_free_pages:
            # all-or-nothing, and never by eviction: installing a
            # remote prefix must not destroy local published pages
            raise SlotMigrationError(
                f"no idle page capacity for {len(install)} fetched "
                "prefix pages")
        nbytes = 0
        for page in install:
            pid = self.cache.adopt_published_page(
                int(page["key"]), page["tokens"])
            chunks = page["shards"]
            if self.quantized:
                kv = np.concatenate([np.asarray(c[0]) for c in chunks],
                                    axis=3)
                sc = chunks[0][1]
                self.cache.pages = self.write_page_step(
                    self.cache.pages, jnp.asarray(pid, jnp.int32),
                    jnp.asarray(kv), jnp.asarray(sc))
            else:
                kv = np.concatenate([np.asarray(c) for c in chunks],
                                    axis=3)
                self.cache.pages = self.write_page_step(
                    self.cache.pages, jnp.asarray(pid, jnp.int32),
                    jnp.asarray(kv))
            nbytes += sum(int(r["bytes"]) for r in page["manifest"])
        self._reg.counter(
            "serving_prefix_fetched_pages_total",
            "prefix pages installed from fleet peers").inc(len(install))
        self._reg.counter(
            "serving_prefix_fetched_bytes_total",
            "bytes of prefix pages installed from fleet peers"
        ).inc(nbytes)
        self._refresh_health()
        return len(install)

    # -- tensor parallel helpers ------------------------------------------

    def _make_tp_params(self, params):
        """Head-major TP re-layout of the attention projections: fused
        qkv weight ``(D, 3D)`` -> ``(D, 3, H, Dh)`` (bias ``(3D,)`` ->
        ``(3, H, Dh)``), out_proj weight ``(D, D)`` -> ``(H, Dh, D)``.
        Sharding the RAW fused columns over tp would hand each shard a
        slice straddling the q/k/v boundaries; head-major, the "tp"
        shard boundary IS a head boundary — which is exactly what the
        per-shard page pools need. Everything else passes through
        untouched (replicated under ``serving_tp_plan``)."""
        cfg = self.model.cfg
        d, h = cfg.hidden_size, cfg.num_heads
        dh = d // h
        out = dict(params)
        blocks = {}
        for name, bp in params["blocks"].items():
            bp = dict(bp)
            qkv, op = bp["attn"]["qkv_proj"], bp["attn"]["out_proj"]
            attn = {
                "qkv_tp": {"weight": qkv["weight"].reshape(d, 3, h, dh)},
                "out_tp": {"weight": op["weight"].reshape(h, dh, d)},
            }
            if "bias" in qkv:
                attn["qkv_tp"]["bias"] = qkv["bias"].reshape(3, h, dh)
            if "bias" in op:
                attn["out_tp"]["bias"] = op["bias"]
            bp["attn"] = attn
            blocks[name] = bp
        out["blocks"] = blocks
        return out

    def _tp_shard_slice(self, tp_params, shard: int):
        """One shard's local slice of the head-major TP tree — the
        probe engine's params (what shard_map would hand shard
        ``shard``)."""
        hl = self._tp_heads
        lo = shard * hl
        out = dict(tp_params)
        blocks = {}
        for name, bp in tp_params["blocks"].items():
            bp = dict(bp)
            attn = dict(bp["attn"])
            qkv = {"weight": attn["qkv_tp"]["weight"][:, :, lo:lo + hl]}
            if "bias" in attn["qkv_tp"]:
                qkv["bias"] = attn["qkv_tp"]["bias"][:, lo:lo + hl]
            attn["qkv_tp"] = qkv
            op = {"weight": attn["out_tp"]["weight"][lo:lo + hl]}
            if "bias" in attn["out_tp"]:
                op["bias"] = attn["out_tp"]["bias"]
            attn["out_tp"] = op
            bp["attn"] = attn
            blocks[name] = bp
        out["blocks"] = blocks
        return out

    def _qkv_tp(self, ap, x):
        """``(S, C, D)`` -> per-shard q, k, v heads ``(S, H/tp, C,
        Dh)`` from the head-major projection slice (the col-parallel
        half of the Megatron split)."""
        qkv = jnp.einsum("scd,dthk->tshck", x, ap["qkv_tp"]["weight"])
        b = ap["qkv_tp"].get("bias")
        if b is not None:
            qkv = qkv + b[:, None, :, None, :]
        return qkv[0], qkv[1], qkv[2]

    def _proj_tp(self, ap, att, spmd):
        """Row-sharded output projection + THE one attention-output
        collective: local heads ``(S, H/tp, Dh)`` (decode) or ``(S, C,
        H/tp, Dh)`` (prefill) -> ``(S, C, D)`` replicated. ``spmd=False``
        (the probe engine) elides the psum — one shard's partial sum
        stands in, which is exactly one chip's share of the work."""
        wo = ap["out_tp"]["weight"]
        if att.ndim == 3:
            part = jnp.einsum("shk,hkd->sd", att, wo)[:, None, :]
        else:
            part = jnp.einsum("schk,hkd->scd", att, wo)
        if spmd:
            part = jax.lax.psum(part, "tp")
        b = ap["out_tp"].get("bias")
        return part + b if b is not None else part

    def _mlp_tp(self, block, bp, x):
        """Megatron MLP shard (prefill tier, ISSUE 19): fc1
        column-split over "tp" (the local ``(D, F/tp)`` slice produces
        local hidden activations), fc2 row-split (``(F/tp, D)`` partial
        products) closed by the layer's SECOND psum, with the fc2 bias
        added exactly once AFTER the reduce (the replicated
        ``block.mlp`` adds it inside ``Linear``, which under a row
        shard would add it ``tp`` times). Mathematically the replicated
        MLP with the hidden-dim reduction reassociated at the shard
        boundary."""
        mp = bp["mlp"]
        h = block.ln2(bp["ln2"], x)
        h = block.mlp.act(jnp.matmul(h, mp["fc1"]["weight"])
                          + mp["fc1"]["bias"])
        part = jax.lax.psum(jnp.matmul(h, mp["fc2"]["weight"]), "tp")
        return part + mp["fc2"]["bias"]

    # -- jitted step bodies ----------------------------------------------

    def _decode_loop(self, params, pages, block_tables, lengths, tokens,
                     active, n_valid=None, *, model=None, quantized=False,
                     n_steps=1, tp=1, spmd=False, mlp_sharded=False):
        """The shared greedy token loop behind the decode step AND the
        draft-proposal step: ``n_steps`` inner iterations, each entering
        every slot's current token at position ``lengths[s]``, landing
        its K/V in the slot's current page (quantized caches store the
        int8 rows + per-token scales and attend through the
        dequant-attend kernel), and attending ragged-paged over live
        pages only. ``n_valid`` (draft proposing) additionally masks
        writes of iterations ``j >= n_valid[s]`` to the null page — a
        chunk capped below ``n_steps`` must not write past the slot's
        reservation. ``tp > 1``: the body is per-shard — qkv from the
        head-major TP slice, K/V landing in the per-shard pages, the
        ragged kernel over ``H/tp`` local heads, and the row-sharded
        output projection with ONE psum per layer (``spmd=False`` is
        the probe engine: same local math, collectives elided; int8
        scales complete their abs-max with a pmax so quantization stays
        bit-identical to tp=1). The keyword-only args are static config
        (default-marked so the AST host-sync lint, which runs on THIS
        body via the graph_lint preset, seeds only the array args as
        tracers). Returns (tokens (S, n_steps), pages)."""
        cfg = model.cfg
        ps = self.cache.config.page_size
        s_tot = tokens.shape[0]
        w = block_tables.shape[1]
        slot_ids = jnp.arange(s_tot)

        def one_token(j, pages, lengths, tokens):
            pos = jnp.minimum(lengths, cfg.max_position - 1)
            x = (model.wte(params["wte"], tokens[:, None])
                 + model.wpe(params["wpe"], pos[:, None]))      # (S,1,D)
            writable = active > 0
            if n_valid is not None:
                writable = writable & (j < n_valid)
            page_idx = jnp.where(
                writable,
                block_tables[slot_ids, jnp.minimum(lengths // ps, w - 1)],
                0)
            off = lengths % ps
            new_pages = []
            for i, block in enumerate(model.blocks):
                bp = params["blocks"][str(i)]
                h = block.ln1(bp["ln1"], x)
                if tp > 1:
                    q, k, v = self._qkv_tp(bp["attn"], h)  # (S,Hl,1,Dh)
                else:
                    q, k, v = block.attn.qkv_heads(bp["attn"],
                                                   h)      # (S,H,1,Dh)
                if quantized:
                    kp, vp, ksc, vsc = pages[i]
                    psa = "tp" if (tp > 1 and spmd) else None
                    kq, k_s = quantize_kv(k[:, :, 0, :], (1, 2),
                                          psum_axis=psa)
                    vq, v_s = quantize_kv(v[:, :, 0, :], (1, 2),
                                          psum_axis=psa)
                    kp = kp.at[page_idx, off].set(kq)
                    vp = vp.at[page_idx, off].set(vq)
                    ksc = ksc.at[page_idx, off].set(k_s)
                    vsc = vsc.at[page_idx, off].set(v_s)
                    att = DA.ragged_paged_decode_int8_attention(
                        q[:, :, 0, :], kp, vp, ksc, vsc, block_tables,
                        lengths + 1, impl=self.attn_impl)       # (S,H,Dh)
                    new_pages.append((kp, vp, ksc, vsc))
                else:
                    kp, vp = pages[i]
                    kp = kp.at[page_idx, off].set(
                        k[:, :, 0, :].astype(kp.dtype))
                    vp = vp.at[page_idx, off].set(
                        v[:, :, 0, :].astype(vp.dtype))
                    att = DA.ragged_paged_decode_attention(
                        q[:, :, 0, :], kp, vp, block_tables, lengths + 1,
                        impl=self.attn_impl)                    # (S,H,Dh)
                    new_pages.append((kp, vp))
                if tp > 1:
                    x = x + self._proj_tp(bp["attn"], att, spmd)
                else:
                    x = x + block.attn.proj_out(bp["attn"],
                                                att[:, :, None, :])
                if mlp_sharded:
                    x = x + self._mlp_tp(block, bp, x)
                else:
                    x = x + block.mlp(bp["mlp"], block.ln2(bp["ln2"], x))
            x = model.ln_f(params["ln_f"], x)
            logits = jnp.einsum("bd,vd->bv", x[:, 0],
                                params["wte"]["weight"])
            return new_pages, jnp.argmax(logits, -1).astype(jnp.int32)

        out = jnp.zeros((s_tot, n_steps), jnp.int32)

        def body(j, carry):
            pages, lengths, tokens, out = carry
            pages, nxt = one_token(j, pages, lengths, tokens)
            return pages, lengths + 1, nxt, out.at[:, j].set(nxt)

        pages, _, _, out = jax.lax.fori_loop(
            0, n_steps, body, (pages, lengths, tokens, out))
        return out, pages

    def _decode_step_impl(self, params, pages, block_tables, lengths,
                          tokens, active):
        """Fixed-shape batched decode of ONE BLOCK of ``decode_block``
        tokens per slot — one host round-trip per block instead of per
        token. Non-decoding lanes (``active == 0``: free slots AND
        slots still mid-prefill, which own live pages the block must
        not corrupt) write to the null page; post-EOS/post-cap lanes
        write past their reservation into the null page and produce
        discarded garbage (the host keeps only in-budget, pre-EOS
        tokens). Returns (tokens (S, decode_block), pages)."""
        return self._decode_loop(params, pages, block_tables, lengths,
                                 tokens, active, model=self.model,
                                 quantized=self.quantized,
                                 n_steps=self.decode_block,
                                 tp=self.tp, spmd=self.tp_spmd,
                                 mlp_sharded=self._mlp_sharded)

    def _make_probe_pool(self):
        """Zero page pool for the collective probe: the real pool's
        geometry with ONE shard's head slice (``H/tp``) on a single
        device — what shard_map hands each shard, minus the psum. Page
        content does not matter for timing (shapes are fixed); a zero
        pool keeps the probe from ever touching live KV."""
        c = self.cache.config
        shape = (c.num_pages, c.page_size, self._tp_heads, c.head_dim)
        pool = []
        for _ in range(c.num_layers):
            if self.quantized:
                sc = jnp.zeros((c.num_pages, c.page_size), jnp.float32)
                pool.append((jnp.zeros(shape, jnp.int8),
                             jnp.zeros(shape, jnp.int8), sc, sc))
            else:
                pool.append((jnp.zeros(shape, c.dtype),
                             jnp.zeros(shape, c.dtype)))
        return pool

    def _decode_probe_step_impl(self, params, pages, block_tables,
                                lengths, tokens, active):
        """The decode step's collectives-elided twin (ISSUE 16): one
        shard's local computation with ``spmd=False`` — identical
        shapes and math minus the per-layer psum, so ``real - probe``
        wall time is the step's exposed collective cost."""
        return self._decode_loop(params, pages, block_tables, lengths,
                                 tokens, active, model=self.model,
                                 quantized=self.quantized,
                                 n_steps=self.decode_block,
                                 tp=self.tp, spmd=False)

    def _draft_propose_step_impl(self, params, pages, block_tables,
                                 lengths, tokens, active, n_valid):
        """Fixed-shape draft proposal: ``spec_k`` greedy draft tokens
        per slot on the DRAFT cache (the first ``spec_k - 1`` become
        the verify chunk's candidates). Iterations at/after
        ``n_valid[s]`` write to the null page — their outputs are
        discarded lanes. Returns (proposals (S, spec_k), pages)."""
        return self._decode_loop(params, pages, block_tables, lengths,
                                 tokens, active, n_valid,
                                 model=self.draft_model,
                                 quantized=self._draft_quantized,
                                 n_steps=self.spec_k)

    def _prefill_loop(self, params, pages, block_tables, starts, tokens,
                      n_valid, *, model=None, quantized=False,
                      all_positions=False, tp=1, spmd=False,
                      mlp_sharded=False):
        """The shared chunk-forward behind the batched prefill step, the
        draft prefill step, and the speculative VERIFY step: ``tokens``
        (S, C) enter at absolute positions ``starts[s]..starts[s]+C-1``
        (first ``n_valid[s]`` real, rest pad to the null page), K/V land
        in each slot's pages (quantized: int8 + scale rows), and every
        live lane attends causally over everything cached.
        ``all_positions=False`` returns the greedy next token after each
        slot's LAST valid position (prefill's first generated token);
        ``all_positions=True`` returns the greedy argmax after EVERY
        chunk position (S, C) — the speculative verifier's per-candidate
        target tokens. ``tp``/``spmd`` shard the body per head group
        exactly as in :meth:`_decode_loop`. Keyword-only args are static
        config (the AST host-sync lint runs on this body — see
        :meth:`_decode_loop`). Returns (tokens, pages)."""
        cfg = model.cfg
        ps = self.cache.config.page_size
        s_tot, c = tokens.shape
        w = block_tables.shape[1]
        positions = starts[:, None] + jnp.arange(c, dtype=jnp.int32)
        pos_e = jnp.minimum(positions, cfg.max_position - 1)
        x = (model.wte(params["wte"], tokens)
             + model.wpe(params["wpe"], pos_e))                 # (S,C,D)
        valid = jnp.arange(c)[None, :] < n_valid[:, None]
        slot_ids = jnp.arange(s_tot)[:, None]
        page_idx = jnp.where(
            valid,
            block_tables[slot_ids, jnp.minimum(positions // ps, w - 1)],
            0)
        off = positions % ps
        new_pages = []
        for i, block in enumerate(model.blocks):
            bp = params["blocks"][str(i)]
            h = block.ln1(bp["ln1"], x)
            if tp > 1:
                q, k, v = self._qkv_tp(bp["attn"], h)           # (S,Hl,C,Dh)
            else:
                q, k, v = block.attn.qkv_heads(bp["attn"],
                                               h)               # (S,H,C,Dh)
            k_tok = k.transpose(0, 2, 1, 3)                     # (S,C,H,Dh)
            v_tok = v.transpose(0, 2, 1, 3)
            if quantized:
                kp, vp, ksc, vsc = pages[i]
                psa = "tp" if (tp > 1 and spmd) else None
                kq, k_s = quantize_kv(k_tok, (2, 3),
                                      psum_axis=psa)            # (S,C)
                vq, v_s = quantize_kv(v_tok, (2, 3),
                                      psum_axis=psa)
                kp = kp.at[page_idx, off].set(kq)
                vp = vp.at[page_idx, off].set(vq)
                ksc = ksc.at[page_idx, off].set(k_s)
                vsc = vsc.at[page_idx, off].set(v_s)
                att = DA.ragged_paged_prefill_int8_attention(
                    q.transpose(0, 2, 1, 3), kp, vp, ksc, vsc,
                    block_tables, starts, n_valid,
                    impl=self.attn_impl)                        # (S,C,H,Dh)
                new_pages.append((kp, vp, ksc, vsc))
            else:
                kp, vp = pages[i]
                kp = kp.at[page_idx, off].set(k_tok.astype(kp.dtype))
                vp = vp.at[page_idx, off].set(v_tok.astype(vp.dtype))
                att = DA.ragged_paged_prefill_attention(
                    q.transpose(0, 2, 1, 3), kp, vp, block_tables,
                    starts, n_valid, impl=self.attn_impl)       # (S,C,H,Dh)
                new_pages.append((kp, vp))
            if tp > 1:
                x = x + self._proj_tp(bp["attn"], att, spmd)
            else:
                x = x + block.attn.proj_out(bp["attn"],
                                            att.transpose(0, 2, 1, 3))
            if mlp_sharded:
                x = x + self._mlp_tp(block, bp, x)
            else:
                x = x + block.mlp(bp["mlp"], block.ln2(bp["ln2"], x))
        x = model.ln_f(params["ln_f"], x)
        if all_positions:
            logits = jnp.einsum("scd,vd->scv", x,
                                params["wte"]["weight"])        # (S,C,V)
            return jnp.argmax(logits, -1).astype(jnp.int32), new_pages
        last = jnp.take_along_axis(
            x, jnp.maximum(n_valid - 1, 0)[:, None, None], axis=1)[:, 0]
        logits = last @ params["wte"]["weight"].T               # (S, V)
        return jnp.argmax(logits, -1).astype(jnp.int32), new_pages

    def _prefill_step_impl(self, params, pages, block_tables, starts,
                           tokens, n_valid):
        """Fixed-shape BATCHED chunked prefill: one call advances EVERY
        admitted request's next prompt chunk (see
        :meth:`_prefill_loop`). Returns (greedy next token after each
        slot's last valid position (S,), pages)."""
        return self._prefill_loop(params, pages, block_tables, starts,
                                  tokens, n_valid, model=self.model,
                                  quantized=self.quantized,
                                  tp=self.tp, spmd=self.tp_spmd,
                                  mlp_sharded=self._mlp_sharded)

    def _draft_prefill_step_impl(self, params, pages, block_tables,
                                 starts, tokens, n_valid):
        """The draft model's prefill twin: same chunks, its own cache —
        keeps the draft's committed prefix in lockstep with the
        target's so proposals condition on identical context."""
        return self._prefill_loop(params, pages, block_tables, starts,
                                  tokens, n_valid,
                                  model=self.draft_model,
                                  quantized=self._draft_quantized)

    def _verify_step_impl(self, params, pages, block_tables, starts,
                          tokens, props, n_valid):
        """The speculative VERIFY step: the batched-prefill shape is
        exactly right for k-token verification — assemble the chunk
        ``[pending, d_1 .. d_{k-1}]`` from each slot's pending token
        (S,) and the draft's proposals (S, spec_k) IN-GRAPH (so the
        step dispatches on the un-materialized draft output, no host
        round-trip between draft and verify), enter it at the slot's
        live positions, commit its K/V, and return the target's greedy
        argmax after EVERY position (S, spec_k) so the host can accept
        the longest agreeing prefix. ONE fixed-shape call verifies all
        k candidates of every slot."""
        chunk = jnp.concatenate(
            [tokens[:, None], props[:, :self.spec_k - 1]], axis=1)
        return self._prefill_loop(params, pages, block_tables, starts,
                                  chunk, n_valid, model=self.model,
                                  quantized=self.quantized,
                                  all_positions=True)

    def _copy_page_impl(self, pages, src, dst):
        """Device-side page copy (CoW of a borrowed shared tail page):
        every layer's K and V page ``src`` duplicated into ``dst`` —
        including the scale rows of a quantized pool, which travel with
        their page. Fixed shape — src/dst are traced scalars, so one
        compile covers every copy."""
        out = []
        for ent in pages:
            out.append(tuple(a.at[dst].set(a[src]) for a in ent))
        return out

    def _read_page_impl(self, pages, src):
        """One page's K/V across every layer, stacked (2, L, page_size,
        H, Dh) — the migration shard unit; a quantized pool also
        returns the page's scale rows (2, L, page_size), carried in the
        same shard. ``src`` is a traced scalar: one compile covers
        every page ever snapshotted."""
        ks = jnp.stack([ent[0][src] for ent in pages])
        vs = jnp.stack([ent[1][src] for ent in pages])
        kv = jnp.stack([ks, vs])
        if self.quantized:
            ksc = jnp.stack([ent[2][src] for ent in pages])
            vsc = jnp.stack([ent[3][src] for ent in pages])
            return kv, jnp.stack([ksc, vsc])
        return kv

    def _write_page_impl(self, pages, dst, kv, sc=None):
        """Install one migration shard (the :meth:`_read_page_impl`
        layout) into page ``dst`` of every layer — quantized shards
        carry ``sc`` and restore the scale rows alongside the int8
        page; pages donated, dst a traced scalar — one compile covers
        every restore."""
        out = []
        for i, ent in enumerate(pages):
            if self.quantized:
                kp, vp, ksc, vsc = ent
                out.append((kp.at[dst].set(kv[0, i].astype(kp.dtype)),
                            vp.at[dst].set(kv[1, i].astype(vp.dtype)),
                            ksc.at[dst].set(sc[0, i]),
                            vsc.at[dst].set(sc[1, i])))
            else:
                kp, vp = ent
                out.append((kp.at[dst].set(kv[0, i].astype(kp.dtype)),
                            vp.at[dst].set(kv[1, i].astype(vp.dtype))))
        return out
