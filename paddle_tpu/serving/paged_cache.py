"""Paged KV cache: fixed-size pages + per-slot block tables.

The dense serving cache (``GPT.init_cache``) allocates
``B × H × max_len × Dh`` per layer — every request pays for the longest
request's horizon. Here K/V live in fixed-size *pages* shared by all
slots; a host-side allocator hands pages to slots as their sequences
grow and reclaims them the step a sequence finishes, so HBM scales with
**live tokens** (plus one page of rounding per slot).

Device state (threaded through the jitted step, donated):
  pages[layer] = (k_pages, v_pages), each (num_pages, page_size, H, Dh)

Host state (plain numpy, mutated by the allocator):
  block_tables (num_slots, max_pages_per_slot) int32 — page ids, row-
    filled in sequence order; unused entries hold 0 (the null page)
  lengths      (num_slots,) int32 — live tokens per slot

Page 0 is reserved as the **null page**: never allocated, the write
target for masked/inactive lanes inside the fixed-shape step, and the
harmless gather target for unused block-table entries.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class PagedCacheConfig:
    num_layers: int
    num_heads: int
    head_dim: int
    num_slots: int
    page_size: int = 16
    num_pages: int = 256
    max_pages_per_slot: int = 16
    dtype: object = jnp.float32

    def __post_init__(self):
        if self.page_size < 1 or self.num_pages < 2:
            raise ValueError("need page_size >= 1 and num_pages >= 2 "
                             "(page 0 is the reserved null page)")
        if self.max_pages_per_slot < 1:
            raise ValueError("max_pages_per_slot must be >= 1")

    @property
    def max_tokens_per_slot(self) -> int:
        return self.max_pages_per_slot * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)


class PageOverflowError(RuntimeError):
    """No free pages (or slot capacity exceeded) for a reservation."""


class PagedKVCache:
    """Device pages + host-side page allocator and block tables."""

    def __init__(self, config: PagedCacheConfig):
        self.config = config
        c = config
        shape = (c.num_pages, c.page_size, c.num_heads, c.head_dim)
        self.pages: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
            (jnp.zeros(shape, c.dtype), jnp.zeros(shape, c.dtype))
            for _ in range(c.num_layers)]
        self.block_tables = np.zeros((c.num_slots, c.max_pages_per_slot),
                                     np.int32)
        self.lengths = np.zeros((c.num_slots,), np.int32)
        # page 0 reserved: null page
        self._free = list(range(c.num_pages - 1, 0, -1))
        self._slot_pages: List[List[int]] = [[] for _ in range(c.num_slots)]

    # -- allocator --------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.config.num_pages - 1) - len(self._free)

    def utilization(self) -> float:
        """Live-token fraction of the allocatable page pool."""
        cap = (self.config.num_pages - 1) * self.config.page_size
        return float(self.lengths.sum()) / cap if cap else 0.0

    def can_reserve(self, n_tokens: int) -> bool:
        need = self.config.pages_for(n_tokens)
        return (need <= len(self._free)
                and need <= self.config.max_pages_per_slot)

    def reserve(self, slot: int, n_tokens: int):
        """Pre-allocate every page ``slot`` will need for ``n_tokens``
        total tokens (prompt + generation horizon). All-or-nothing, so
        an admitted request can never OOM mid-decode."""
        if self._slot_pages[slot]:
            raise PageOverflowError(f"slot {slot} already holds pages")
        need = self.config.pages_for(n_tokens)
        if need > self.config.max_pages_per_slot:
            raise PageOverflowError(
                f"{n_tokens} tokens needs {need} pages > max_pages_per_slot"
                f"={self.config.max_pages_per_slot}")
        if need > len(self._free):
            raise PageOverflowError(
                f"{need} pages needed, {len(self._free)} free")
        got = [self._free.pop() for _ in range(need)]
        self._slot_pages[slot] = got
        self.block_tables[slot, :] = 0
        self.block_tables[slot, :need] = got
        self.lengths[slot] = 0

    def free_slot(self, slot: int):
        """Return the slot's pages to the pool (the step a request
        finishes — continuous batching's whole point)."""
        self._free.extend(reversed(self._slot_pages[slot]))
        self._slot_pages[slot] = []
        self.block_tables[slot, :] = 0
        self.lengths[slot] = 0

    def slot_pages(self, slot: int) -> List[int]:
        return list(self._slot_pages[slot])

    # -- device views -----------------------------------------------------

    def device_tables(self):
        """(block_tables, lengths) as device arrays for the jitted step."""
        return jnp.asarray(self.block_tables), jnp.asarray(self.lengths)

    def check_invariants(self):
        """Allocator self-check (tests): no page is double-owned, free +
        owned + null == num_pages."""
        owned = [p for sp in self._slot_pages for p in sp]
        assert 0 not in owned, "null page allocated"
        assert 0 not in self._free, "null page in free list"
        all_pages = owned + self._free
        assert len(set(all_pages)) == len(all_pages), "page double-owned"
        assert len(all_pages) == self.config.num_pages - 1
