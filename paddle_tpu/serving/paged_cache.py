"""Paged KV cache: fixed-size pages, block tables, and prefix sharing.

The dense serving cache (``GPT.init_cache``) allocates
``B × H × max_len × Dh`` per layer — every request pays for the longest
request's horizon. Here K/V live in fixed-size *pages* shared by all
slots; a host-side allocator hands pages to slots as their sequences
grow and reclaims them the step a sequence finishes, so HBM scales with
**live tokens** (plus one page of rounding per slot).

Device state (threaded through the jitted step, donated):
  pages[layer] = (k_pages, v_pages), each (num_pages, page_size, H, Dh)

Host state (plain numpy, mutated by the allocator):
  block_tables (num_slots, max_pages_per_slot) int32 — page ids, row-
    filled in sequence order; unused entries hold 0 (the null page)
  lengths      (num_slots,) int32 — live tokens per slot

Page 0 is reserved as the **null page**: never allocated, the write
target for masked/inactive lanes inside the fixed-shape step, and the
harmless gather target for unused block-table entries.

Prefix sharing (ISSUE 6): pages are **refcounted**, and prompt prefixes
are published to a hash-chained index at *page* granularity once their
content has actually been prefilled. A new request whose prompt matches
a published chain maps those pages straight into its block table
(refcount bump — the shared system-prompt case: prefilled once, mapped
by every follower) and skips prefilling them. Rules that keep it exact:

- Only the *owner* (the slot that allocated a page) ever writes it; a
  borrowed page is read-only for the borrower.
- Matching is verified against the **stored tokens**, never the hash
  alone — a hash collision can cost a copy, never correctness.
- A *tail* page (partially filled) can be borrowed too, but the
  borrower will append into it, so ``reserve`` maps a fresh
  **copy-on-write** page in its place and records a pending device copy
  (src → dst) the engine performs before the slot's first prefill.
  Allocating the CoW page at reservation time preserves the
  all-or-nothing guarantee: an admitted request can never OOM later.
- At most ``len(prompt) - 1`` tokens are ever shared, so every request
  prefills at least one token — the one that produces its first output.
- A page whose refcount drops to zero while still published parks in an
  LRU **cached** pool: reusable by future matches, evicted (and
  unpublished) only when the allocator runs dry.

Int8 pages (ISSUE 13): ``dtype=jnp.int8`` stores K/V pages quantized —
HBM per live token roughly halves, so the same pool hosts ~2x the
slots. Each layer entry becomes ``(k_pages, v_pages, k_scales,
v_scales)`` with the scales fp32 ``(num_pages, page_size)`` — one
symmetric abs-max scale per *token row* of each page
(:func:`quantize_kv`), stored page-major so scales always travel WITH
their pages: publication, copy-on-write, the LRU cached pool, and the
fleet migration shards all move page and scale rows together under one
page id (a finer grain than one scalar per page, same page-granular
management — incremental token writes then never requantize already-
stored rows, so stored content is append-stable and prefix sharing
stays exact). Dequantization happens INSIDE the dequant-attend kernels
(:mod:`~paddle_tpu.serving.decode_attention`), fused into the QK and
PV products — no fp page is ever materialized.

Tensor parallel (ISSUE 15): pass ``mesh=`` (a mesh with a ``tp`` axis
of size > 1) and the page pool becomes **per-shard**: the K/V page
arrays are placed sharded over ``tp`` on the HEAD axis (each mesh shard
holds every page's slice of its own ``H/tp`` heads), while the block
tables, lengths, allocator books, and — for int8 pools — the per-token
scale rows stay replicated (a token's quantization scale is computed
over ALL heads, so it is shard-independent; see
:func:`quantize_kv`'s ``psum_axis``). The host-side allocator and the
prefix-sharing index are untouched: page identity is global, only the
page *contents* are sharded.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.analysis.concurrency import guarded_by


@dataclasses.dataclass
class PagedCacheConfig:
    num_layers: int
    num_heads: int
    head_dim: int
    num_slots: int
    page_size: int = 16
    num_pages: int = 256
    max_pages_per_slot: int = 16
    dtype: object = jnp.float32
    share_prefix: bool = True

    def __post_init__(self):
        if self.page_size < 1 or self.num_pages < 2:
            raise ValueError("need page_size >= 1 and num_pages >= 2 "
                             "(page 0 is the reserved null page)")
        if self.max_pages_per_slot < 1:
            raise ValueError("max_pages_per_slot must be >= 1")

    @property
    def max_tokens_per_slot(self) -> int:
        return self.max_pages_per_slot * self.page_size

    @property
    def quantized(self) -> bool:
        """Int8 page storage with per-token-row fp32 scales."""
        return jnp.dtype(self.dtype) == jnp.dtype(jnp.int8)

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)


class PageOverflowError(RuntimeError):
    """No free pages (or slot capacity exceeded) for a reservation."""


#: abs-max floor so an all-zero token row gets a harmless tiny scale
#: instead of a division by zero (dequant of its zero int8 row is 0)
KV_SCALE_FLOOR = 1e-8


def quantize_kv(x, reduce_axes: Tuple[int, ...], psum_axis=None):
    """Symmetric per-token int8 quantization of a K/V slab.

    ``x`` carries one K (or V) vector per token over its TRAILING
    ``reduce_axes`` (decode writes ``(S, H, Dh)`` with axes ``(1, 2)``;
    prefill writes ``(S, C, H, Dh)`` with axes ``(2, 3)``). Returns
    ``(q int8, scale f32)`` with ``scale = max(|x|) / 127`` per token —
    the row the page pool stores next to the page so dequantization is
    ``q * scale`` inside the attend kernel. Per-token granularity keeps
    incremental page writes append-stable: a new token never forces a
    requantization of rows already stored (a single per-page scalar
    would), which is what lets shared/published int8 pages stay
    bit-stable under prefix sharing and CoW.

    ``psum_axis`` (tensor parallel): inside ``shard_map`` each shard
    holds only its own ``H/tp`` heads of ``x``, so the per-token abs-max
    is completed with a ``pmax`` over the named mesh axis BEFORE the
    scale divides — every shard then quantizes its head slice with the
    all-head scale the tp=1 engine computes (max is exact, so for
    bit-identical inputs the quantization is bit-identical; in the
    sharded engine deeper layers' inputs carry the psum's last-ulp
    accumulation noise, which the rounding absorbs — greedy parity is
    pinned at the token level)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=reduce_axes)
    if psum_axis is not None:
        amax = jax.lax.pmax(amax, psum_axis)
    scale = jnp.maximum(amax, KV_SCALE_FLOOR) / 127.0
    exp = scale.reshape(scale.shape + (1,) * len(reduce_axes))
    q = jnp.clip(jnp.round(xf / exp), -127, 127).astype(jnp.int8)
    return q, scale


_ROOT_KEY = hash("paddle_tpu.serving.prefix_root")


def _chain(parent_key: int, chunk: np.ndarray) -> int:
    return hash((parent_key, chunk.tobytes()))


def _chain_walk(prompt, page_size: int, upto: int,
                key: int = _ROOT_KEY, start_page: int = 0):
    """Yield ``(page_index, chain_key, chunk)`` for each FULL page of
    ``prompt[:upto]`` starting at ``start_page``, chaining from
    ``key``. The ONE page-chain loop behind prefix matching, prefix
    publication, AND the router's :func:`prompt_prefix_digests` — the
    three must agree bit-for-bit or affinity prediction silently
    diverges from what ``publish_prefix`` commits."""
    k = key
    p = start_page
    while (p + 1) * page_size <= upto:
        chunk = np.asarray(prompt[p * page_size:(p + 1) * page_size],
                           np.int32)
        k = _chain(k, chunk)
        yield p, k, chunk
        p += 1


def prompt_prefix_digests(prompt, page_size: int) -> List[int]:
    """The hash-chain keys of ``prompt``'s page-aligned full prefix
    pages — digest ``k`` covers tokens ``[0, (k+1)*page_size)``. These
    are EXACTLY the keys :meth:`PagedKVCache.publish_prefix` commits to
    the full-page index, so intersecting them with a cache's
    :meth:`~PagedKVCache.published_digests` predicts how many prefix
    pages a new request would map instead of prefill — the fleet
    router's cache-locality signal. Capped at ``len(prompt) - 1``
    tokens, mirroring the at-least-one-token-prefills rule. In-process
    only (python ``hash`` is seed-randomized per interpreter); a
    cross-process transport must re-digest with a stable hash."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    limit = int(prompt.shape[0]) - 1
    return [key for _p, key, _c in _chain_walk(prompt, page_size, limit)]


def payload_digest(payload: Tuple[np.ndarray, ...]) -> str:
    """sha256 over a spilled page's host arrays — the int8 KV and its
    fp32 scale rows hash as ONE digest (a scale-only corruption must be
    refused exactly like a KV corruption)."""
    h = hashlib.sha256()
    for a in payload:
        h.update(np.asarray(a).tobytes())
    return h.hexdigest()


@dataclasses.dataclass
class SpilledPage:
    """One published full page parked in host memory: its hash-chain
    key, the stored token content (match verification stays
    content-checked, never hash-only), the host copies of the page's
    device arrays (``(kv,)`` fp, ``(kv, scales)`` int8 — scale rows
    always travel WITH their page), and the sha256 stamped at spill
    time that restore/export re-verify."""

    key: int
    tokens: np.ndarray
    payload: Tuple[np.ndarray, ...]
    sha256: str
    nbytes: int


@guarded_by("_lock", "_entries")
class HostPagePool:
    """Host-memory LRU tier for spilled KV pages (ISSUE 20).

    When the device cached pool would evict (and destroy) a published
    page under allocator pressure, the page's bytes land here instead,
    keyed by its prefix-chain digest; the next prefix hit restores it
    with an async ``device_put`` that overlaps admission, and a fleet
    peer fetch can export straight from here without touching HBM.
    Bounded in pages — over ``capacity`` the LRU entry is dropped (the
    only path that truly destroys a published page's content now).

    ``gen`` bumps on EVERY mutation (spill, restore, drop, discard):
    together with the device index's ``_index_gen`` it forms
    :attr:`PagedKVCache.prefix_gen`, the generation the fleet's
    affinity snapshots key on — a silently-dropped prefix must change
    the advertised digest set, never linger in a stale memo.

    Thread-safe (one ``threading.Lock``, a leaf in the committed lock
    order): the engine mutates it from the step thread while a fleet
    router thread reads ``keys()``/``len()`` through
    ``advertised_digests``/``health``.
    """

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError("HostPagePool needs capacity >= 1")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[int, SpilledPage]" = OrderedDict()
        self.gen = 0
        self.spilled_total = 0
        self.restored_total = 0
        self.dropped_total = 0
        self.spilled_bytes_total = 0
        self.restored_bytes_total = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys(self) -> frozenset:
        with self._lock:
            return frozenset(self._entries)

    def entries(self) -> List[SpilledPage]:
        with self._lock:
            return list(self._entries.values())

    def spilled_bytes(self) -> int:
        """Host bytes resident right now."""
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def put(self, entry: SpilledPage):
        """Admit one spilled page (newest = most recently used); LRU
        entries past capacity are dropped and counted."""
        with self._lock:
            self._entries[entry.key] = entry
            self._entries.move_to_end(entry.key)
            self.gen += 1
            self.spilled_total += 1
            self.spilled_bytes_total += entry.nbytes
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.dropped_total += 1
                self.gen += 1

    def get(self, key: int) -> Optional[SpilledPage]:
        """Peek (and LRU-touch) without removing."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
            return ent

    def pop(self, key: int) -> Optional[SpilledPage]:
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self.gen += 1
            return ent

    def discard(self, key: int):
        """Drop an entry that became device-resident again (restore,
        peer fetch, or a fresh local publication of the same chain) —
        the pool holds COLD pages only, never a device duplicate."""
        self.pop(key)

    def note_restored(self, pages: int, nbytes: int):
        with self._lock:
            self.restored_total += pages
            self.restored_bytes_total += nbytes


class PagedKVCache:
    """Device pages + host-side page allocator, block tables, and the
    refcounted prefix-sharing index.

    ``mesh=`` (tp > 1): the K/V page arrays are placed sharded over the
    mesh's ``tp`` axis on the head dimension — per-shard page pools —
    while int8 scale rows stay replicated (per-token scales are
    head-global). Allocator/index state is host-side and unaffected."""

    def __init__(self, config: PagedCacheConfig, mesh=None,
                 host_spill_pages: int = 0):
        self.config = config
        self.mesh = mesh if (mesh is not None
                             and int(mesh.shape.get("tp", 1)) > 1) else None
        c = config
        if self.mesh is not None and c.num_heads % int(mesh.shape["tp"]):
            raise ValueError(
                f"tp={mesh.shape['tp']} must divide num_heads={c.num_heads}")
        shape = (c.num_pages, c.page_size, c.num_heads, c.head_dim)
        if c.quantized:
            # int8 pages + fp32 per-token-row scales, one (k, v, ks, vs)
            # tuple per layer so scales thread/donate with their pages
            # through every jitted step as ONE pytree
            sshape = (c.num_pages, c.page_size)
            self.pages = [
                (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                 jnp.zeros(sshape, jnp.float32),
                 jnp.zeros(sshape, jnp.float32))
                for _ in range(c.num_layers)]
        else:
            self.pages: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
                (jnp.zeros(shape, c.dtype), jnp.zeros(shape, c.dtype))
                for _ in range(c.num_layers)]
        if self.mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            kv_s = NamedSharding(self.mesh, P(None, None, "tp", None))
            rep = NamedSharding(self.mesh, P())
            self.pages = [
                tuple(jax.device_put(a, kv_s if i < 2 else rep)
                      for i, a in enumerate(ent))
                for ent in self.pages]
        self.block_tables = np.zeros((c.num_slots, c.max_pages_per_slot),
                                     np.int32)
        self.lengths = np.zeros((c.num_slots,), np.int32)
        # page 0 reserved: null page
        self._free = list(range(c.num_pages - 1, 0, -1))
        self._slot_pages: List[List[int]] = [[] for _ in range(c.num_slots)]
        # -- sharing state --
        self._ref = np.zeros((c.num_pages,), np.int32)   # mappers per page
        self._owned: List[set] = [set() for _ in range(c.num_slots)]
        self._cached: "OrderedDict[int, bool]" = OrderedDict()  # LRU, ref 0
        self._full_index: Dict[int, int] = {}    # chain key -> page id
        self._tail_index: Dict[int, int] = {}    # chain key -> tail page id
        self._page_pub: Dict[int, Tuple[str, int]] = {}  # pid -> (kind, key)
        self._page_tokens: Dict[int, np.ndarray] = {}    # published content
        self._published_upto: List[int] = [0] * c.num_slots
        # per-slot publish cursor: hash-chain key covering the first
        # _published_upto // page_size pages, so each publish_prefix
        # call hashes only NEW pages (not the whole prompt again)
        self._pub_chain: List[int] = [_ROOT_KEY] * c.num_slots
        # slot -> (src, dst): device copy the engine owes before writing
        self._pending_copy: Dict[int, Tuple[int, int]] = {}
        # admission calls can_reserve once per queued candidate per wave
        # and reserve() repeats the match — memoize on (prompt identity,
        # index generation) so each prompt is matched once per index
        # change, not once per scheduler pass; entries pin the array
        self._index_gen = 0
        self._match_cache: "OrderedDict[Tuple[int, int], tuple]" = \
            OrderedDict()
        # published_digests() memo: the router reads it per candidate
        # per submit; rebuild only when the index actually changed
        self._digests = frozenset()
        self._digests_gen = -1
        self.shared_tokens_total = 0     # prefill tokens skipped via sharing
        self.cow_copies_total = 0
        # HBM -> host spill tier (ISSUE 20), off by default (0 pages):
        # _alloc_page pages evicted published pages into the host pool
        # instead of destroying them, via the engine-installed reader
        # (attach_spill_io) so page bytes leave the device through the
        # warmed ("page_read",) signature
        self.spill_pool: Optional[HostPagePool] = (
            HostPagePool(host_spill_pages) if host_spill_pages > 0
            else None)
        self._spill_reader: Optional[Callable] = None
        # advertised_digests() memo: device index keys + spilled keys,
        # keyed on prefix_gen (either tier changing invalidates it)
        self._adv_digests = frozenset()
        self._adv_gen = -1

    # -- allocator --------------------------------------------------------

    @property
    def free_pages(self) -> int:
        """Pages immediately allocatable (free + evictable cached)."""
        return len(self._free) + len(self._cached)

    @property
    def pages_in_use(self) -> int:
        return int((self._ref[1:] > 0).sum())

    def utilization(self) -> float:
        """Live-token fraction of the allocatable page pool."""
        cap = (self.config.num_pages - 1) * self.config.page_size
        return float(self.lengths.sum()) / cap if cap else 0.0

    def bytes_per_page(self) -> int:
        """HBM bytes one page row commits across every layer's K + V
        pools (+ scale rows when quantized) — global bytes under tp
        sharding (each shard holds its head slice of the same page)."""
        total = 0
        for layer in self.pages:
            for arr in layer:
                total += arr.nbytes
        return total // self.config.num_pages

    def capacity_bytes(self) -> int:
        """HBM bytes of the allocatable pool (null page excluded)."""
        return self.bytes_per_page() * (self.config.num_pages - 1)

    def live_bytes(self) -> int:
        """HBM bytes committed to allocated pages right now (page
        granularity — reservations count the moment they are made,
        which is what admission headroom must see)."""
        return self.bytes_per_page() * self.pages_in_use

    def _alloc_page(self) -> int:
        if self._free:
            return self._free.pop()
        if self._cached:     # evict the LRU published-but-idle page
            pid, _ = self._cached.popitem(last=False)
            self._spill_page(pid)
            self._unpublish(pid)
            return pid
        raise PageOverflowError("page pool exhausted")

    def attach_spill_io(self, reader: Callable):
        """Install the engine's page reader (``pid -> tuple of host
        arrays``, the full stacked page the jitted ``read_page_step``
        returns). Spilling stays a no-op until both a pool AND a reader
        exist, so a bare cache (unit tests, draft caches) never tries
        device IO."""
        self._spill_reader = reader

    def _spill_page(self, pid: int):
        """Page an evicted published FULL page out to the host pool
        (kv + scale rows together, sha256-stamped) instead of letting
        ``_unpublish`` destroy its content. Tail pages are not spilled:
        they are at most ``page_size - 1`` tokens of recompute and do
        not participate in fleet digests."""
        if self.spill_pool is None or self._spill_reader is None:
            return
        pub = self._page_pub.get(pid)
        if pub is None or pub[0] != "full":
            return
        payload = tuple(np.asarray(a) for a in self._spill_reader(pid))
        self.spill_pool.put(SpilledPage(
            key=pub[1], tokens=self._page_tokens[pid].copy(),
            payload=payload, sha256=payload_digest(payload),
            nbytes=sum(int(a.nbytes) for a in payload)))

    def _acquire(self, pid: int):
        """Take a reference on a published page (reviving it from the
        cached pool if idle)."""
        if pid in self._cached:
            del self._cached[pid]
        self._ref[pid] += 1

    def _release(self, pid: int):
        self._ref[pid] -= 1
        assert self._ref[pid] >= 0, f"page {pid} over-released"
        if self._ref[pid] == 0:
            if pid in self._page_pub:
                self._cached[pid] = True     # reusable via the index
            else:
                self._free.append(pid)

    def _unpublish(self, pid: int):
        kind, key = self._page_pub.pop(pid)
        index = self._full_index if kind == "full" else self._tail_index
        if index.get(key) == pid:
            del index[key]
        self._page_tokens.pop(pid, None)
        self._index_gen += 1

    # -- prefix matching --------------------------------------------------

    def _match_prefix(self, prompt: Optional[np.ndarray]):
        """Longest published, content-verified prefix of ``prompt``.
        Returns (full_page_ids, tail_src_page_or_None, shared_tokens);
        caps sharing at ``len(prompt) - 1`` so at least one token always
        prefills (producing the request's first output token). Also
        returns ``key_after_full``, the hash-chain key covering the
        matched full pages — ``reserve`` seeds the slot's publish cursor
        with it so ``publish_prefix`` never rehashes them. Memoized
        per (prompt identity, index generation): the result only depends
        on the publication indices, which bump ``_index_gen`` on every
        change, never on page refcount/cached state. Keying on
        ``id(prompt)`` keeps the hot path free of whole-prompt copies or
        hashing — admission probes the same queued Request's array every
        wave — and the entry pins the array, so its id cannot be reused
        while the entry lives (prompts are never mutated after submit)."""
        if prompt is None or not self.config.share_prefix:
            return [], None, 0, _ROOT_KEY
        mkey = (id(prompt), self._index_gen)
        hit = self._match_cache.get(mkey)
        if hit is not None and hit[0] is prompt:
            return hit[1]
        res = self._match_prefix_uncached(prompt)
        self._match_cache[mkey] = (prompt, res)
        while len(self._match_cache) > 512:
            self._match_cache.popitem(last=False)
        return res

    def _match_prefix_uncached(self, prompt: np.ndarray):
        ps = self.config.page_size
        limit = int(prompt.shape[0]) - 1
        key, k, full = _ROOT_KEY, 0, []
        for p, key2, chunk in _chain_walk(prompt, ps, limit):
            pid = self._full_index.get(key2)
            if pid is None or not np.array_equal(
                    self._page_tokens[pid], chunk):
                break
            full.append(pid)
            key, k = key2, p + 1
        shared = k * ps
        tail_pid = self._tail_index.get(key)
        if tail_pid is not None:
            stored = self._page_tokens[tail_pid]
            rem = np.asarray(prompt[shared:limit], np.int32)
            n = 0
            m = min(len(stored), len(rem))
            while n < m and stored[n] == rem[n]:
                n += 1
            if n > 0:
                return full, (tail_pid, n), shared + n, key
            return full, None, shared, key
        return full, None, shared, key

    def can_reserve(self, n_tokens: int,
                    prompt: Optional[np.ndarray] = None) -> bool:
        need = self.config.pages_for(n_tokens)
        if need > self.config.max_pages_per_slot:
            return False
        full, _tail, _shared, _key = self._match_prefix(prompt)
        borrowed_cached = sum(1 for p in full if p in self._cached)
        fresh = need - len(full)
        # tail sharing is dropped by reserve() when pinning the CoW src
        # would not fit, so feasibility only needs the full-page math
        return fresh <= len(self._free) + len(self._cached) - borrowed_cached

    def reserve(self, slot: int, n_tokens: int,
                prompt: Optional[np.ndarray] = None) -> int:
        """Pre-allocate every page ``slot`` will need for ``n_tokens``
        total tokens (prompt + generation horizon). All-or-nothing, so
        an admitted request can never OOM mid-decode. With ``prompt``
        given and sharing enabled, published prefix pages are mapped
        instead of allocated; returns the number of prompt tokens
        already covered by shared pages (the engine starts prefill after
        them and sets ``lengths[slot]`` accordingly — done here)."""
        if self._slot_pages[slot]:
            raise PageOverflowError(f"slot {slot} already holds pages")
        need = self.config.pages_for(n_tokens)
        if need > self.config.max_pages_per_slot:
            raise PageOverflowError(
                f"{n_tokens} tokens needs {need} pages > max_pages_per_slot"
                f"={self.config.max_pages_per_slot}")
        full, tail, shared, chain_key = self._match_prefix(prompt)
        borrowed_cached = sum(1 for p in full if p in self._cached)
        fresh = need - len(full)
        if (tail is not None
                and fresh > len(self._free) + len(self._cached)
                - borrowed_cached
                - (1 if tail[0] in self._cached else 0)):
            # pinning the CoW src would leave too few evictable pages:
            # degrade to sharing the full pages only (the tail tokens
            # just get recomputed) rather than refusing the request
            tail, shared = None, len(full) * self.config.page_size
        if fresh > len(self._free) + len(self._cached) - borrowed_cached:
            raise PageOverflowError(
                f"{fresh} pages needed, {len(self._free)} free "
                f"+ {len(self._cached)} cached")
        mapped: List[int] = []
        owned = set()
        for pid in full:
            self._acquire(pid)
            mapped.append(pid)
        if tail is not None:
            # pin the CoW src BEFORE allocating fresh pages: _alloc_page
            # evicts from the cached pool when free runs dry, and the
            # idle published tail is exactly the kind of page it would
            # recycle — after which the pending copy would read garbage
            self._acquire(tail[0])
        for _ in range(fresh):
            pid = self._alloc_page()
            self._ref[pid] = 1
            owned.add(pid)
            mapped.append(pid)
        if tail is not None:
            src, _n = tail
            # the borrower appends into this page: map a fresh CoW page
            # in its place (already counted in ``fresh`` — it replaces
            # the tail slot position) and owe a device copy
            self._pending_copy[slot] = (src, mapped[len(full)])
            self.cow_copies_total += 1
        self._slot_pages[slot] = mapped
        self._owned[slot] = owned
        self._published_upto[slot] = shared
        self._pub_chain[slot] = chain_key
        self.block_tables[slot, :] = 0
        self.block_tables[slot, :need] = mapped
        self.lengths[slot] = shared
        self.shared_tokens_total += shared
        return shared

    def pending_copy(self, slot: int) -> Optional[Tuple[int, int]]:
        """(src, dst) device page copy the engine must perform before
        the slot's first write (CoW of a borrowed tail page)."""
        return self._pending_copy.get(slot)

    def copy_done(self, slot: int):
        src, _dst = self._pending_copy.pop(slot)
        self._release(src)

    def publish_prefix(self, slot: int, prompt: np.ndarray, upto: int):
        """Publish the slot's OWN prompt pages whose content has been
        prefilled through token ``upto``: full pages always; the partial
        tail page once the whole prompt is in (``upto >= len(prompt)``).
        Borrowed pages are already published; first publisher wins."""
        if not self.config.share_prefix:
            return
        ps = self.config.page_size
        upto = min(int(upto), int(prompt.shape[0]))
        if upto <= self._published_upto[slot]:
            return
        # resume from the publish cursor: pages before it are already
        # published (or borrowed) and their chain key is saved
        key = self._pub_chain[slot]
        k = self._published_upto[slot] // ps
        for p, key2, chunk in _chain_walk(prompt, ps, upto,
                                          key=key, start_page=k):
            pid = self._slot_pages[slot][p]
            if (key2 not in self._full_index and pid in self._owned[slot]
                    and pid not in self._page_pub):
                self._full_index[key2] = pid
                self._page_pub[pid] = ("full", key2)
                self._page_tokens[pid] = chunk.copy()
                self._index_gen += 1
                if self.spill_pool is not None:
                    # a fresh local prefill re-committed this chain key
                    # device-side: the cold host copy is now redundant
                    # (the pool never shadows a device-resident page)
                    self.spill_pool.discard(key2)
            key, k = key2, p + 1
        self._pub_chain[slot] = key
        if upto >= int(prompt.shape[0]) and upto % ps:
            tail = np.asarray(prompt[k * ps:upto], np.int32)
            pid = self._slot_pages[slot][k]
            if (key not in self._tail_index and pid in self._owned[slot]
                    and pid not in self._page_pub):
                self._tail_index[key] = pid
                self._page_pub[pid] = ("tail", key)
                self._page_tokens[pid] = tail.copy()
                self._index_gen += 1
        self._published_upto[slot] = upto

    def writable(self, slot: int, page_index: int) -> bool:
        """True when the slot may write the page at this block-table
        position (it allocated it — borrowed pages are read-only)."""
        return self._slot_pages[slot][page_index] in self._owned[slot]

    def free_slot(self, slot: int):
        """Drop the slot's references; pages hit the free pool (or the
        cached pool, when published) only at refcount zero — continuous
        batching's whole point, minus whatever prefix sharers still
        hold."""
        if slot in self._pending_copy:
            self.copy_done(slot)     # never materialized; release the src
        for pid in self._slot_pages[slot]:
            self._release(pid)
        self._slot_pages[slot] = []
        self._owned[slot] = set()
        self._published_upto[slot] = 0
        self._pub_chain[slot] = _ROOT_KEY
        self.block_tables[slot, :] = 0
        self.lengths[slot] = 0

    def slot_pages(self, slot: int) -> List[int]:
        return list(self._slot_pages[slot])

    def published_digests(self) -> frozenset:
        """The full-page prefix digests currently resolvable through the
        index (live or parked in the cached pool) — the set a replica
        advertises to the fleet router; compare against
        :func:`prompt_prefix_digests` of a candidate prompt. Memoized
        on ``_index_gen`` (the same discipline as ``_match_prefix``):
        the router polls this on every submit, the index changes only
        on publish/unpublish."""
        if self._digests_gen != self._index_gen:
            self._digests = frozenset(self._full_index)
            self._digests_gen = self._index_gen
        return self._digests

    # -- HBM -> host spill tier (ISSUE 20) --------------------------------

    @property
    def prefix_gen(self) -> int:
        """Monotonic generation over BOTH publication tiers: bumps when
        the device index changes (publish/unpublish/adopt) AND when the
        host spill pool changes (spill/restore/drop). A replica
        publishes this through ``health()`` so fleet affinity snapshots
        can never keep routing to a replica that silently dropped a
        prefix — eviction of a published page is a generation change,
        not a private event."""
        return self._index_gen + (self.spill_pool.gen
                                  if self.spill_pool is not None else 0)

    @property
    def idle_free_pages(self) -> int:
        """Pages allocatable WITHOUT evicting a published cached page —
        the budget spill restores and peer-fetch installs spend (taking
        more would evict-and-respill other cold pages: churn, not
        progress)."""
        return len(self._free)

    def advertised_digests(self) -> frozenset:
        """What this replica advertises fleet-wide: device-published
        digests plus host-spilled ones — a spilled page is still
        servable (restored on the next local prefix hit, exported on a
        peer fetch), so affinity must keep counting it. Memoized on
        :attr:`prefix_gen`, same discipline as ``published_digests``."""
        if self.spill_pool is None:
            return self.published_digests()
        g = self.prefix_gen
        if self._adv_gen != g:
            self._adv_digests = (self.published_digests()
                                 | self.spill_pool.keys())
            self._adv_gen = g
        return self._adv_digests

    def spill_restore_plan(self, prompt) -> List[SpilledPage]:
        """The spilled full pages that would extend ``prompt``'s
        device-resident published chain if restored — in chain order,
        content-verified against the stored tokens like every other
        match. Walks the same hash chain as ``_match_prefix``; stops at
        the first page held by NEITHER tier (later pages cannot map —
        prefix pages only chain onto a present parent). Capped at
        :attr:`idle_free_pages` so restoring never evicts."""
        if (self.spill_pool is None or len(self.spill_pool) == 0
                or prompt is None or not self.config.share_prefix):
            return []
        ps = self.config.page_size
        limit = int(np.asarray(prompt).reshape(-1).shape[0]) - 1
        plan: List[SpilledPage] = []
        for _p, key, chunk in _chain_walk(prompt, ps, limit):
            pid = self._full_index.get(key)
            if pid is not None:
                if np.array_equal(self._page_tokens[pid], chunk):
                    continue
                break
            ent = self.spill_pool.get(key)
            if ent is None or not np.array_equal(ent.tokens, chunk):
                break
            plan.append(ent)
            if len(plan) >= len(self._free):
                break
        return plan

    def adopt_published_page(self, key: int, tokens) -> int:
        """Publish an externally-written page (spill restore or fleet
        peer fetch): allocate a page, commit it to the full-page index
        parked in the cached pool (refcount 0 — the next match borrows
        it exactly like a locally-published page), and drop any host
        copy of the same key. Returns the page id; the caller owes the
        device write immediately after (nothing can read the page
        before the caller's own next cache operation). New adoptions
        enter the LRU at the hot end, so a same-wave ``_alloc_page``
        eviction cannot immediately recycle them."""
        pid = self._alloc_page()
        self._full_index[key] = pid
        self._page_pub[pid] = ("full", key)
        self._page_tokens[pid] = np.asarray(tokens, np.int32).copy()
        self._cached[pid] = True
        self._index_gen += 1
        if self.spill_pool is not None:
            self.spill_pool.discard(key)
        return pid

    def lookup_prefix_page(self, key: int):
        """Resolve one advertised digest for the engine's peer-export
        path: ``("device", pid, tokens)`` when the page is resident,
        ``("host", SpilledPage)`` when spilled, None when this cache
        no longer holds it (dropped under host-pool pressure)."""
        pid = self._full_index.get(key)
        if pid is not None:
            return ("device", pid, self._page_tokens[pid])
        if self.spill_pool is not None:
            ent = self.spill_pool.get(key)
            if ent is not None:
                return ("host", ent)
        return None

    # -- device views -----------------------------------------------------

    def device_tables(self):
        """(block_tables, lengths) as device arrays for the jitted step."""
        return jnp.asarray(self.block_tables), jnp.asarray(self.lengths)

    def check_invariants(self):
        """Allocator self-check (tests): per-page refcount equals the
        number of mappings holding it, free/cached/live partition the
        pool, the null page is never owned, published entries resolve."""
        c = self.config
        expect = np.zeros((c.num_pages,), np.int32)
        for sp in self._slot_pages:
            for p in sp:
                expect[p] += 1
        for (src, _dst) in self._pending_copy.values():
            expect[src] += 1
        assert expect[0] == 0, "null page mapped"
        assert (expect == self._ref).all(), (
            f"refcount drift: {np.nonzero(expect != self._ref)[0]}")
        free_s, cached_s = set(self._free), set(self._cached)
        assert len(free_s) == len(self._free), "page double-freed"
        assert not (free_s & cached_s), "page both free and cached"
        assert 0 not in free_s and 0 not in cached_s, "null page pooled"
        live = {int(p) for p in np.nonzero(self._ref)[0]}
        assert not (live & (free_s | cached_s)), "live page in a pool"
        assert free_s | cached_s | live == set(range(1, c.num_pages)), \
            "page leaked"
        for pid, (kind, key) in self._page_pub.items():
            index = self._full_index if kind == "full" else self._tail_index
            assert index.get(key) == pid, "publication index drift"
            assert pid in self._page_tokens, "published page lost tokens"
        for owned, sp in zip(self._owned, self._slot_pages):
            assert owned <= set(sp), "owned page not mapped"
        if self.spill_pool is not None:
            spilled = self.spill_pool.keys()
            assert len(self.spill_pool) <= self.spill_pool.capacity, \
                "host spill pool over capacity"
            assert not (spilled & set(self._full_index)), \
                "page both device-published and host-spilled"
            for ent in self.spill_pool.entries():
                assert payload_digest(ent.payload) == ent.sha256, \
                    "spilled page payload corrupted in host pool"
