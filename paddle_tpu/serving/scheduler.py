"""Continuous-batching scheduler: keep every decode slot full.

Lock-step batch decoding finishes when the *longest* request finishes;
every early-EOS sequence wastes its slot as padding until then. Here a
fixed number of decode slots run one fixed-shape step together, and the
scheduler (pure host logic — no jax, unit-testable with randomized
arrivals):

  - admits queued requests into free slots the moment slots + pages are
    available (admission order is FIFO; a too-big-for-now request blocks
    the queue rather than starving — no head-of-line reordering, so
    completion is guaranteed);
  - evicts a sequence the step it finishes (EOS or its own length cap),
    releasing its slot and pages for the next admission;
  - tracks queue-wait / first-token timestamps for the engine's metrics.

The scheduler never touches device state: the engine owns the jitted
step and the paged cache; this class only decides *which request sits
in which slot when*.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray              # (S0,) int32
    max_new_tokens: int
    eos_id: Optional[int] = None
    submitted_at: float = 0.0

    @property
    def total_tokens(self) -> int:
        return int(self.prompt.shape[0]) + self.max_new_tokens


@dataclasses.dataclass
class SlotState:
    request: Request
    generated: List[int] = dataclasses.field(default_factory=list)
    prefilled: int = 0              # prompt tokens already in the cache
    admitted_at: float = 0.0
    first_token_at: Optional[float] = None

    @property
    def prefill_done(self) -> bool:
        return self.prefilled >= int(self.request.prompt.shape[0])

    def finished(self) -> bool:
        r = self.request
        if len(self.generated) >= r.max_new_tokens:
            return True
        return (r.eos_id is not None and self.generated
                and self.generated[-1] == r.eos_id)


class ContinuousBatchingScheduler:
    """FIFO queue + slot table. ``can_admit(request)`` is injected by the
    engine (page availability lives in the cache, not here)."""

    def __init__(self, num_slots: int,
                 can_admit: Optional[Callable[[Request], bool]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.num_slots = num_slots
        self.slots: List[Optional[SlotState]] = [None] * num_slots
        self.queue: Deque[Request] = deque()
        self._can_admit = can_admit or (lambda r: True)
        self._clock = clock
        self._ids = itertools.count()

    # -- queue ------------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               eos_id: Optional[int] = None) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        req = Request(next(self._ids), prompt, max_new_tokens, eos_id,
                      submitted_at=self._clock())
        self.queue.append(req)
        return req.rid

    # -- slot bookkeeping -------------------------------------------------

    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def active_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is not None]

    def decode_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots)
                if s is not None and s.prefill_done]

    def occupancy(self) -> float:
        return len(self.active_slots()) / self.num_slots

    def admit(self, on_admit=None) -> List[int]:
        """Move queued requests into free slots (FIFO, head-blocking).
        Returns the slot indices admitted this call; the engine then
        prefills them. Stops at the first request the cache cannot hold
        yet — its pages free up as running sequences finish.

        ``on_admit(slot, request)`` fires immediately per admission,
        BEFORE the next request's ``can_admit`` check — the engine
        reserves pages there, so one call admitting several requests
        can never over-commit the pool against a stale free count."""
        admitted = []
        for slot in self.free_slots():
            if not self.queue:
                break
            if not self._can_admit(self.queue[0]):
                break
            req = self.queue.popleft()
            self.slots[slot] = SlotState(req, admitted_at=self._clock())
            if on_admit is not None:
                on_admit(slot, req)
            admitted.append(slot)
        return admitted

    def evict_finished(self) -> Dict[int, SlotState]:
        """Pop every finished slot; returns {slot: final state}."""
        done = {}
        for i, st in enumerate(self.slots):
            if st is not None and st.finished():
                done[i] = st
                self.slots[i] = None
        return done

    def idle(self) -> bool:
        return not self.queue and not self.active_slots()
