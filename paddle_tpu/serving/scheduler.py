"""Serving schedulers: keep every decode slot full, and meet SLOs.

Lock-step batch decoding finishes when the *longest* request finishes;
every early-EOS sequence wastes its slot as padding until then. Here a
fixed number of decode slots run one fixed-shape step together, and the
scheduler (pure host logic — no jax, unit-testable with randomized
arrivals):

  - admits queued requests into free slots the moment slots + pages are
    available;
  - evicts a sequence the step it finishes (EOS or its own length cap),
    releasing its slot and pages for the next admission;
  - tracks queue-wait / first-token timestamps for the engine's metrics.

Two policies (ISSUE 6):

``ContinuousBatchingScheduler`` — plain FIFO with head blocking: a
too-big-for-now request blocks the queue rather than starving. Simple,
starvation-free, but one huge request at the head stalls every
interactive request behind it.

``SLOScheduler`` — priority lanes (ordered, e.g. ``interactive`` before
``batch``), per-request TTFT deadlines with earliest-deadline-first
boosting of at-risk requests, admission that *skips* requests that do
not fit yet (no head-of-line blocking) with a bounded-skip
anti-starvation rule (a request passed over ``starvation_skips`` times
becomes blocking until it fits), and load shedding: rather than
queueing forever, ``submit`` raises a structured
:class:`LoadShedError` when the queue is full or the estimated TTFT
already blows the request's deadline.

The scheduler never touches device state: the engine owns the jitted
steps and the paged cache; this class only decides *which request sits
in which slot when*.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray              # (S0,) int32
    max_new_tokens: int
    eos_id: Optional[int] = None
    submitted_at: float = 0.0
    lane: str = "default"
    ttft_deadline_s: Optional[float] = None
    skips: int = 0                  # admission passes that skipped it
    boosted: bool = False           # already EDF-boosted (one trace event)

    @property
    def total_tokens(self) -> int:
        return int(self.prompt.shape[0]) + self.max_new_tokens

    def deadline_at(self) -> Optional[float]:
        if self.ttft_deadline_s is None:
            return None
        return self.submitted_at + self.ttft_deadline_s


#: the Reject.reason vocabulary — the ONE source of truth. The wire
#: protocol validates decoded rejects against it, the parametrized wire
#: tests enumerate it, and ``analysis.conformance.lint_reject_vocab``
#: statically checks that every constructed literal is registered and
#: every entry is constructed somewhere.
REJECT_REASONS = (
    "queue_full",            # submit: bounded queue at capacity
    "deadline_infeasible",   # submit: est TTFT already past the deadline
    "deadline_expired",      # queued past its TTFT deadline (engine reap
                             # or router pre-redrive check)
    "redrive_budget",        # router: per-request redrive budget spent
    "no_replica",            # router: no live replica can accept it
    "requeue_shed",          # router: drain-requeue landed nowhere
    "slow_reader",           # front door: client stream backpressure
)


@dataclasses.dataclass
class Reject:
    """Structured load-shed verdict (the body of :class:`LoadShedError`):
    everything a client needs to back off sensibly instead of the
    request silently queueing forever. ``reason`` is one of
    :data:`REJECT_REASONS`."""
    reason: str
    lane: str
    queue_depth: int
    est_ttft_s: float
    retry_after_s: float


class LoadShedError(RuntimeError):
    """Raised by ``SLOScheduler.submit`` instead of queueing a request
    the server cannot serve within its SLO; carries a :class:`Reject`."""

    def __init__(self, reject: Reject):
        super().__init__(
            f"load shed ({reject.reason}): lane={reject.lane} "
            f"queue_depth={reject.queue_depth} "
            f"est_ttft={reject.est_ttft_s:.3f}s "
            f"retry_after={reject.retry_after_s:.3f}s")
        self.reject = reject


@dataclasses.dataclass
class SlotState:
    request: Request
    generated: List[int] = dataclasses.field(default_factory=list)
    prefilled: int = 0              # prompt tokens already in the cache
    admitted_at: float = 0.0
    first_token_at: Optional[float] = None

    @property
    def prefill_done(self) -> bool:
        return self.prefilled >= int(self.request.prompt.shape[0])

    def finished(self) -> bool:
        r = self.request
        if len(self.generated) >= r.max_new_tokens:
            return True
        return (r.eos_id is not None and self.generated
                and self.generated[-1] == r.eos_id)


class ContinuousBatchingScheduler:
    """FIFO queue + slot table. ``can_admit(request)`` is injected by the
    engine (page availability lives in the cache, not here)."""

    def __init__(self, num_slots: int,
                 can_admit: Optional[Callable[[Request], bool]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.num_slots = num_slots
        self.slots: List[Optional[SlotState]] = [None] * num_slots
        self.queue: Deque[Request] = deque()
        self._can_admit = can_admit or (lambda r: True)
        self._clock = clock
        self._ids = itertools.count()
        # decision-event sink: event_cb(rid, name, **attrs). The engine
        # wires this to each request's trace span, so skip/boost/shed
        # verdicts land on the request timeline with their reasons.
        self.event_cb: Optional[Callable] = None

    def _event(self, rid: int, name: str, **attrs):
        if self.event_cb is not None:
            self.event_cb(rid, name, **attrs)

    # -- queue ------------------------------------------------------------

    def _make_request(self, prompt, max_new_tokens, eos_id, lane,
                      ttft_deadline_s) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        return Request(next(self._ids), prompt, max_new_tokens, eos_id,
                       submitted_at=self._clock(), lane=lane,
                       ttft_deadline_s=ttft_deadline_s)

    def submit(self, prompt, max_new_tokens: int,
               eos_id: Optional[int] = None, *, lane: str = "default",
               ttft_deadline_s: Optional[float] = None) -> int:
        req = self._make_request(prompt, max_new_tokens, eos_id, lane,
                                 ttft_deadline_s)
        self.queue.append(req)
        return req.rid

    def queue_depth(self) -> int:
        return len(self.queue)

    def note_ttft(self, seconds: float):
        """Engine feedback hook (TTFT estimator); FIFO ignores it."""

    # -- slot bookkeeping -------------------------------------------------

    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def active_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is not None]

    def decode_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots)
                if s is not None and s.prefill_done]

    def occupancy(self) -> float:
        return len(self.active_slots()) / self.num_slots

    def admit(self, on_admit=None) -> List[int]:
        """Move queued requests into free slots (FIFO, head-blocking).
        Returns the slot indices admitted this call; the engine then
        prefills them. Stops at the first request the cache cannot hold
        yet — its pages free up as running sequences finish.

        ``on_admit(slot, request)`` fires immediately per admission,
        BEFORE the next request's ``can_admit`` check — the engine
        reserves pages there, so one call admitting several requests
        can never over-commit the pool against a stale free count."""
        admitted = []
        for slot in self.free_slots():
            if not self.queue:
                break
            if not self._can_admit(self.queue[0]):
                break
            req = self.queue.popleft()
            self.slots[slot] = SlotState(req, admitted_at=self._clock())
            if on_admit is not None:
                on_admit(slot, req)
            admitted.append(slot)
        return admitted

    def evict_finished(self) -> Dict[int, SlotState]:
        """Pop every finished slot; returns {slot: final state}."""
        done = {}
        for i, st in enumerate(self.slots):
            if st is not None and st.finished():
                done[i] = st
                self.slots[i] = None
        return done

    def idle(self) -> bool:
        return not self.queue and not self.active_slots()


class SLOScheduler(ContinuousBatchingScheduler):
    """SLO-aware admission: priority lanes + TTFT deadlines + bounded
    skipping + load shedding. Slot bookkeeping (eviction, decode-slot
    tracking) is shared with the FIFO base; only *who gets in when* and
    *who is turned away* differ.

    Admission order each call:

    1. Requests whose TTFT deadline is **at risk** (now + the EWMA
       TTFT estimate crosses the deadline), earliest deadline first —
       they jump every lane.
    2. Everything else by lane priority (``lanes`` order), FIFO within
       a lane.

    A candidate that does not fit (``can_admit`` false — typically no
    pages yet) is *skipped*, not blocking the line, and its skip count
    increments; once a request has been skipped ``starvation_skips``
    times, admission stops behind it until it fits (the FIFO
    head-blocking guarantee, applied only where starvation is real).

    ``submit`` sheds load instead of queueing forever: with the queue at
    ``max_queue_depth``, or with a requested deadline the EWMA TTFT
    estimate says is infeasible, it raises :class:`LoadShedError`
    carrying a structured :class:`Reject`. Deadline shedding only
    applies once the queue is *saturated* (``shed_saturation_waves``
    full admission waves deep) — below saturation the EDF boost can
    still rescue an at-risk request, so it is admitted and, if it
    misses anyway, reaped by :meth:`shed_expired`.
    """

    def __init__(self, num_slots: int,
                 can_admit: Optional[Callable[[Request], bool]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 lanes: Sequence[str] = ("interactive", "default", "batch"),
                 max_queue_depth: Optional[int] = None,
                 starvation_skips: int = 64,
                 deadline_slack_s: float = 0.0,
                 shed_saturation_waves: float = 2.0):
        super().__init__(num_slots, can_admit=can_admit, clock=clock)
        self.lane_order = {name: i for i, name in enumerate(lanes)}
        self.max_queue_depth = max_queue_depth
        self.starvation_skips = starvation_skips
        self.deadline_slack_s = deadline_slack_s
        self.shed_saturation_waves = shed_saturation_waves
        self._ttft_ewma = 0.0       # engine-fed; 0 = no estimate yet
        self.shed_total = 0

    # -- TTFT estimator ---------------------------------------------------

    def note_ttft(self, seconds: float):
        """Engine feedback: observed TTFT of a completed admission,
        folded into the EWMA the shedding/at-risk decisions use."""
        a = 0.3
        self._ttft_ewma = (seconds if self._ttft_ewma == 0.0
                           else a * seconds + (1 - a) * self._ttft_ewma)

    def est_ttft_s(self) -> float:
        """Crude queue-aware TTFT estimate: the EWMA of served requests
        scaled by how many queue waves sit ahead of a new arrival."""
        waves = 1.0 + len(self.queue) / max(self.num_slots, 1)
        return self._ttft_ewma * waves

    # -- submission + shedding --------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               eos_id: Optional[int] = None, *, lane: str = "default",
               ttft_deadline_s: Optional[float] = None) -> int:
        if lane not in self.lane_order:
            raise ValueError(f"unknown lane {lane!r} "
                             f"(have {sorted(self.lane_order)})")
        est = self.est_ttft_s()
        if (self.max_queue_depth is not None
                and len(self.queue) >= self.max_queue_depth):
            self.shed_total += 1
            raise LoadShedError(Reject(
                "queue_full", lane, len(self.queue), est,
                retry_after_s=max(self._ttft_ewma, 0.001)))
        saturated = (len(self.queue)
                     >= self.shed_saturation_waves * self.num_slots)
        if (saturated and ttft_deadline_s is not None
                and est > ttft_deadline_s > 0):
            self.shed_total += 1
            raise LoadShedError(Reject(
                "deadline_infeasible", lane, len(self.queue), est,
                retry_after_s=max(est - ttft_deadline_s, 0.001)))
        return super().submit(prompt, max_new_tokens, eos_id, lane=lane,
                              ttft_deadline_s=ttft_deadline_s)

    # -- admission --------------------------------------------------------

    def _admission_order(self) -> List[Request]:
        now = self._clock()
        at_risk: List[Tuple[float, int, Request]] = []
        rest: List[Tuple[int, float, int, Request]] = []
        for i, req in enumerate(self.queue):
            dl = req.deadline_at()
            if (dl is not None and self._ttft_ewma > 0.0
                    and now + self._ttft_ewma + self.deadline_slack_s >= dl):
                if not req.boosted:     # one boost event per request
                    req.boosted = True
                    self._event(req.rid, "sched_boost",
                                deadline_in_s=round(dl - now, 6),
                                est_ttft_s=round(self._ttft_ewma, 6))
                at_risk.append((dl, i, req))
            else:
                rest.append((self.lane_order.get(req.lane, 0),
                             req.submitted_at, i, req))
        at_risk.sort(key=lambda t: t[:2])       # earliest deadline first
        rest.sort(key=lambda t: t[:3])          # lane, then FIFO
        return [t[-1] for t in at_risk] + [t[-1] for t in rest]

    def admit(self, on_admit=None) -> List[int]:
        """Move queued requests into free slots in SLO order. A request
        that cannot fit yet is skipped (no head blocking) unless its
        skip count has crossed ``starvation_skips`` — then it blocks
        admission of everything ordered behind it until it fits."""
        admitted: List[int] = []
        free = self.free_slots()
        if not free or not self.queue:
            return admitted     # saturated: skip the whole-queue sort
        for req in self._admission_order():
            if not free:
                break
            if not self._can_admit(req):
                req.skips += 1
                if req.skips > self.starvation_skips:
                    if req.skips == self.starvation_skips + 1:
                        # once per request: admit() runs every step, and
                        # a head-blocked request can stay blocked for
                        # hours — per-pass events would grow its live
                        # span without bound
                        self._event(req.rid, "sched_block",
                                    skips=req.skips)
                    break           # anti-starvation: now it head-blocks
                self._event(req.rid, "sched_skip", skips=req.skips,
                            reason="no_capacity")
                continue
            slot = free.pop(0)
            self.queue.remove(req)
            self.slots[slot] = SlotState(req, admitted_at=self._clock())
            if on_admit is not None:
                on_admit(slot, req)
            admitted.append(slot)
        return admitted

    def shed_expired(self) -> List[Request]:
        """Pop queued requests whose TTFT deadline has already passed —
        serving them late helps nobody and burns pages interactive
        traffic needs. The engine reports them as structured rejects."""
        now = self._clock()
        dead = [r for r in self.queue
                if r.deadline_at() is not None and now > r.deadline_at()]
        for r in dead:
            self.queue.remove(r)
            self.shed_total += 1
        return dead
