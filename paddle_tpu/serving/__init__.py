"""Paged KV-cache serving engine: continuous batching + ragged decode.

The serving-throughput subsystem (ISSUE 4). Four parts:

1. **Paged KV cache** (`paged_cache.py`): K/V in fixed-size pages with
   per-slot block tables and a host-side allocator — HBM scales with
   live tokens, not ``batch × max_len``.
2. **Ragged paged decode attention** (`decode_attention.py`): one
   fixed-shape kernel call attends every slot's query over only its own
   live pages (Pallas with block-table scalar prefetch; lax fallback and
   an ``interpret=True`` path so CPU tier-1 tests run the real kernel).
3. **Continuous-batching scheduler** (`scheduler.py`): fixed decode
   slots, FIFO admission into freed slots, immediate eviction on
   EOS/length cap — pure host logic.
4. **ServingEngine** (`engine.py`): ``submit``/``step``/
   ``generate_many`` driving one jit-compiled fixed-shape decode step
   with donated cache pages (zero steady-state recompiles, proven by a
   ``RecompileDetector``), wired into the observability registry.
"""

from paddle_tpu.serving.paged_cache import (PagedCacheConfig, PagedKVCache,
                                            PageOverflowError)
from paddle_tpu.serving.decode_attention import (paged_prefill_attention,
                                                 ragged_paged_decode_attention)
from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                          Request, SlotState)
from paddle_tpu.serving.engine import ServingEngine

__all__ = [
    "PagedCacheConfig", "PagedKVCache", "PageOverflowError",
    "paged_prefill_attention", "ragged_paged_decode_attention",
    "ContinuousBatchingScheduler", "Request", "SlotState",
    "ServingEngine",
]
