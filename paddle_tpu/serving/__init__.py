"""Paged KV-cache serving engine: continuous batching, batched chunked
prefill, prefix sharing, and SLO-aware scheduling.

The serving-throughput subsystem (ISSUE 4 + the ISSUE 6 prefill/SLO
rebuild). Four parts:

1. **Paged KV cache** (`paged_cache.py`): K/V in fixed-size pages with
   per-slot block tables and a host-side allocator — HBM scales with
   live tokens, not ``batch × max_len`` — plus **refcounted prefix
   sharing**: published prompt-prefix pages are mapped copy-free into
   new requests' block tables (a shared system prompt is prefilled once
   for thousands of requests), with copy-on-write for shared tail pages.
2. **Ragged paged attention kernels** (`decode_attention.py`): one
   fixed-shape call attends every slot's query token (decode) or query
   CHUNK (batched prefill) over only its own live pages (Pallas with
   block-table scalar prefetch; lax fallback and an ``interpret=True``
   path so CPU tier-1 tests run the real kernels).
3. **Schedulers** (`scheduler.py`): fixed decode slots with immediate
   EOS eviction — plain FIFO (`ContinuousBatchingScheduler`) or
   SLO-aware (`SLOScheduler`: priority lanes, TTFT deadlines, bounded-
   skip anti-starvation, structured `LoadShedError` load shedding) —
   pure host logic.
4. **ServingEngine** (`engine.py`): ``submit``/``step``/
   ``generate_many`` driving one jit-compiled fixed-shape decode step
   AND one batched chunked-prefill step with donated cache pages (zero
   steady-state recompiles, proven by a ``RecompileDetector``), prefill/
   decode interleaving under a token budget, wired into the
   observability registry with split TTFT accounting — plus slot-level
   live-migration snapshot/restore (sha256-verified per-page shards).
5. **Fleet** (`fleet/`): N engines behind one ``FleetRouter`` —
   prefix-affinity routing over the published prefix index,
   power-of-two-choices fallback, burn-rate elastic autoscaling, and
   live request migration on drain.
"""

from paddle_tpu.serving.paged_cache import (PagedCacheConfig, PagedKVCache,
                                            PageOverflowError,
                                            prompt_prefix_digests,
                                            quantize_kv)
from paddle_tpu.serving.decode_attention import (
    paged_prefill_attention, ragged_paged_decode_attention,
    ragged_paged_decode_int8_attention,
    ragged_paged_decode_int8_tp_attention,
    ragged_paged_decode_tp_attention, ragged_paged_prefill_attention,
    ragged_paged_prefill_int8_attention,
    ragged_paged_prefill_int8_tp_attention,
    ragged_paged_prefill_tp_attention)
from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                          LoadShedError, Reject, Request,
                                          SLOScheduler, SlotState)
from paddle_tpu.serving.engine import ServingEngine, SlotMigrationError
from paddle_tpu.serving import fleet

__all__ = [
    "PagedCacheConfig", "PagedKVCache", "PageOverflowError",
    "paged_prefill_attention", "ragged_paged_decode_attention",
    "ragged_paged_decode_int8_attention",
    "ragged_paged_decode_int8_tp_attention",
    "ragged_paged_decode_tp_attention",
    "ragged_paged_prefill_attention",
    "ragged_paged_prefill_int8_attention",
    "ragged_paged_prefill_int8_tp_attention",
    "ragged_paged_prefill_tp_attention", "prompt_prefix_digests",
    "quantize_kv",
    "ContinuousBatchingScheduler", "SLOScheduler", "LoadShedError",
    "Reject", "Request", "SlotState",
    "ServingEngine", "SlotMigrationError", "fleet",
]
