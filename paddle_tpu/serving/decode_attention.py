"""Ragged paged attention kernels: the serving engine's hot path.

One fixed-shape call attends every slot's query token(s) over only that
slot's *live* KV pages — the "Ragged Paged Attention" TPU serving
pattern (PAPERS.md): sequences of wildly different lengths batch into
one step, and work/HBM traffic scale with live tokens, not with
``batch × max_len`` padding. Two kernels share the layout and the
online-softmax structure (the reusable-kernel argument of Tensor
Processing Primitives — prefill is a chunk-sized variant of decode, not
a fourth bespoke module):

``ragged_paged_decode_attention`` — one query token per slot:
  q            (S, H, Dh)        one query token per decode slot
  k/v pages    (P, ps, H, Dh)    fixed-size pages, token-major
  block_tables (S, max_pages)    page ids per slot (page 0 = null page)
  lengths      (S,)              live tokens per slot (0 = inactive slot)

``ragged_paged_prefill_attention`` — a CHUNK of C query tokens per slot
(the batched multi-request chunked-prefill step, ISSUE 6): queries sit
at absolute positions ``chunk_starts[s] + c`` and attend causally over
everything the slot has cached, including this chunk's own causal
prefix (whose K/V the caller writes before attending). Lanes past
``n_valid[s]`` (and whole inactive slots, ``n_valid == 0``) emit exact
zeros.

Each has two implementations with identical numerics:

- ``impl="lax"``: XLA gather + masked softmax (CPU/debug reference).
- ``impl="pallas"`` / ``"pallas_interpret"``: a Pallas kernel, grid
  ``(S, H, max_pages)``, that scalar-prefetches the block table so each
  kv block's HBM address is known before the body runs (the
  PrefetchScalarGridSpec pattern), does online-softmax accumulation over
  pages, and skips pages past the slot's live extent entirely. The
  interpret path runs the REAL kernel on CPU, so tier-1 tests exercise
  it.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU pallas backend (interpret mode still works without a TPU)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from paddle_tpu.ops.attention import NEG_INF


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:  # pragma: no cover
        return False


# ---------------------------------------------------------------------------
# lax reference path
# ---------------------------------------------------------------------------

def _paged_decode_lax(q, k_pages, v_pages, block_tables, lengths, scale):
    s_slots, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    # contract straight against the gathered 5-D (S, mp, ps, H, Dh)
    # layout — reshaping the gather to token-major would materialize a
    # full extra copy of every slot's K and V per call
    kg = k_pages[block_tables]
    vg = v_pages[block_tables]
    scores = jnp.einsum("shd,smthd->shmt", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) * scale
    scores = scores.reshape(s_slots, h, mp * ps)
    tok = jnp.arange(mp * ps, dtype=jnp.int32)
    valid = tok[None, None, :] < lengths[:, None, None]
    scores = jnp.where(valid, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    # length-0 slots: every key masked -> emit 0, not a uniform mean of v
    alive = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0).reshape(s_slots, h, mp, ps)
    out = jnp.einsum("shmt,smthd->shd", p, vg.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel: grid (S, H, max_pages), block-table scalar prefetch
# ---------------------------------------------------------------------------

def _paged_decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, page_size):
    sl = pl.program_id(0)
    pj = pl.program_id(2)
    npg = pl.num_programs(2)

    @pl.when(pj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[sl]

    def _body():
        q = q_ref[0].astype(jnp.float32)               # (1, Dh)
        k = k_ref[0, :, 0, :].astype(jnp.float32)      # (ps, Dh)
        v = v_ref[0, :, 0, :].astype(jnp.float32)      # (ps, Dh)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (1, ps)
        tok = pj * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        s = jnp.where(tok < length, s, NEG_INF)

        m_prev = m_scr[...]                            # (1, 128)
        l_prev = l_scr[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)      # (1, 1)
        m_next = jnp.maximum(m_prev, m_cur)            # lanes broadcast
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next[:, :1])                 # (1, ps)
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = m_next
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (1, Dh)
        acc_scr[...] = acc_scr[...] * alpha[:, :1] + pv

    # ragged skip: pages at/after the slot's length hold no live tokens
    pl.when(pj * page_size < length)(_body)

    @pl.when(pj == npg - 1)
    def _finish():
        denom = l_scr[...][:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        alive = m_scr[...][:, :1] > NEG_INF / 2
        o_ref[0] = jnp.where(alive, acc_scr[...] / denom, 0.0).astype(
            o_ref.dtype)


def _paged_decode_pallas(q, k_pages, v_pages, block_tables, lengths, scale,
                         interpret):
    if pltpu is None:  # pragma: no cover
        raise RuntimeError("Pallas TPU backend unavailable; use impl='lax'")
    s_slots, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    qs = (q * jnp.asarray(scale, q.dtype))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block_tables, lengths
        grid=(s_slots, h, mp),
        in_specs=[
            pl.BlockSpec((1, 1, dh), lambda s, hh, j, bt, ln: (s, hh, 0)),
            pl.BlockSpec((1, ps, 1, dh),
                         lambda s, hh, j, bt, ln: (bt[s, j], 0, hh, 0)),
            pl.BlockSpec((1, ps, 1, dh),
                         lambda s, hh, j, bt, ln: (bt[s, j], 0, hh, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, dh),
                               lambda s, hh, j, bt, ln: (s, hh, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, dh), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_decode_kernel, page_size=ps)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, h, dh), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      qs, k_pages, v_pages)
    return out


# ---------------------------------------------------------------------------
# batched chunked prefill: lax reference + Pallas kernel
# ---------------------------------------------------------------------------

def _paged_prefill_lax(q, k_pages, v_pages, block_tables, chunk_starts,
                       n_valid, scale):
    s_slots, c, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    kg = k_pages[block_tables]                     # (S, mp, ps, H, Dh)
    vg = v_pages[block_tables]
    scores = jnp.einsum("schd,smthd->shcmt", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) * scale
    scores = scores.reshape(s_slots, h, c, mp * ps)
    tok = jnp.arange(mp * ps, dtype=jnp.int32)
    pos = chunk_starts[:, None] + jnp.arange(c, dtype=jnp.int32)  # (S, C)
    causal = tok[None, None, None, :] <= pos[:, None, :, None]
    row_ok = (jnp.arange(c) < n_valid[:, None])[:, None, :, None]
    scores = jnp.where(causal & row_ok, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    # masked rows (padding lanes / inactive slots) emit exact zeros
    alive = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0).reshape(s_slots, h, c, mp, ps)
    out = jnp.einsum("shcmt,smthd->schd", p, vg.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_prefill_kernel(bt_ref, start_ref, nv_ref, q_ref, k_ref, v_ref,
                          o_ref, m_scr, l_scr, acc_scr, *, page_size):
    sl = pl.program_id(0)
    pj = pl.program_id(2)
    npg = pl.num_programs(2)

    @pl.when(pj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    start = start_ref[sl]
    nv = nv_ref[sl]

    def _body():
        q = q_ref[0, :, 0, :].astype(jnp.float32)      # (C, Dh)
        k = k_ref[0, :, 0, :].astype(jnp.float32)      # (ps, Dh)
        v = v_ref[0, :, 0, :].astype(jnp.float32)      # (ps, Dh)
        cc = q.shape[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (C, ps)
        tok = pj * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (cc, page_size), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (cc, page_size), 0)
        ok = (tok <= start + row) & (row < nv)         # causal + live lane
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[...]                            # (C, 128)
        l_prev = l_scr[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)      # (C, 1)
        m_next = jnp.maximum(m_prev, m_cur)            # lanes broadcast
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next[:, :1])                 # (C, ps)
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = m_next
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (C, Dh)
        acc_scr[...] = acc_scr[...] * alpha[:, :1] + pv

    # ragged skip: pages wholly past the chunk's live extent do nothing
    pl.when((nv > 0) & (pj * page_size < start + nv))(_body)

    @pl.when(pj == npg - 1)
    def _finish():
        denom = l_scr[...][:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        alive = m_scr[...][:, :1] > NEG_INF / 2
        o_ref[0, :, 0, :] = jnp.where(
            alive, acc_scr[...] / denom, 0.0).astype(o_ref.dtype)


def _paged_prefill_pallas(q, k_pages, v_pages, block_tables, chunk_starts,
                          n_valid, scale, interpret):
    if pltpu is None:  # pragma: no cover
        raise RuntimeError("Pallas TPU backend unavailable; use impl='lax'")
    s_slots, c, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    qs = (q * jnp.asarray(scale, q.dtype))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # block_tables, chunk_starts, n_valid
        grid=(s_slots, h, mp),
        in_specs=[
            pl.BlockSpec((1, c, 1, dh),
                         lambda s, hh, j, bt, st, nv: (s, 0, hh, 0)),
            pl.BlockSpec((1, ps, 1, dh),
                         lambda s, hh, j, bt, st, nv: (bt[s, j], 0, hh, 0)),
            pl.BlockSpec((1, ps, 1, dh),
                         lambda s, hh, j, bt, st, nv: (bt[s, j], 0, hh, 0)),
        ],
        out_specs=pl.BlockSpec((1, c, 1, dh),
                               lambda s, hh, j, bt, st, nv: (s, 0, hh, 0)),
        scratch_shapes=[
            pltpu.VMEM((c, 128), jnp.float32),
            pltpu.VMEM((c, 128), jnp.float32),
            pltpu.VMEM((c, dh), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_prefill_kernel, page_size=ps)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, c, h, dh), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), chunk_starts.astype(jnp.int32),
      n_valid.astype(jnp.int32), qs, k_pages, v_pages)
    return out


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

def ragged_paged_decode_attention(q, k_pages, v_pages, block_tables,
                                  lengths, *, scale: Optional[float] = None,
                                  impl: str = "auto"):
    """One decode step of attention for every slot at once.

    ``q`` (S, H, Dh); ``k_pages``/``v_pages`` (P, page_size, H, Dh);
    ``block_tables`` (S, max_pages) int32; ``lengths`` (S,) int32 valid
    tokens per slot. Returns (S, H, Dh). ``impl``: "auto" (pallas on
    TPU, lax elsewhere), "lax", "pallas", "pallas_interpret".
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if impl == "auto":
        impl = "pallas" if (pltpu is not None and _on_tpu()) else "lax"
    if impl == "lax":
        return _paged_decode_lax(q, k_pages, v_pages, block_tables,
                                 lengths, scale)
    if impl in ("pallas", "pallas_interpret"):
        return _paged_decode_pallas(q, k_pages, v_pages, block_tables,
                                    lengths, scale,
                                    interpret=impl == "pallas_interpret")
    raise ValueError(f"unknown impl {impl!r}")


def ragged_paged_prefill_attention(q, k_pages, v_pages, block_tables,
                                   chunk_starts, n_valid, *,
                                   scale: Optional[float] = None,
                                   impl: str = "auto"):
    """One batched chunked-prefill step of attention for every slot.

    ``q`` (S, C, H, Dh) — a chunk of C query tokens per slot, the first
    ``n_valid[s]`` real (rest padding), at absolute positions
    ``chunk_starts[s] + c``; keys/values are read from each slot's pages
    via ``block_tables`` (S, max_pages). Each live query attends
    causally to all cache positions ``<= chunk_starts[s] + c`` (earlier
    chunks, shared prefix pages, and this chunk's causal prefix — whose
    K/V the caller has already written). Padding lanes and inactive
    slots (``n_valid == 0``) emit exact zeros. Returns (S, C, H, Dh).
    ``impl``: "auto" (pallas on TPU, lax elsewhere), "lax", "pallas",
    "pallas_interpret".
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if impl == "auto":
        impl = "pallas" if (pltpu is not None and _on_tpu()) else "lax"
    if impl == "lax":
        return _paged_prefill_lax(q, k_pages, v_pages, block_tables,
                                  chunk_starts, n_valid, scale)
    if impl in ("pallas", "pallas_interpret"):
        return _paged_prefill_pallas(q, k_pages, v_pages, block_tables,
                                     chunk_starts, n_valid, scale,
                                     interpret=impl == "pallas_interpret")
    raise ValueError(f"unknown impl {impl!r}")


def paged_prefill_attention(q, k_pages, v_pages, block_table_row,
                            positions, *, scale: Optional[float] = None):
    """Chunked-prefill attention for ONE slot.

    ``q`` (C, H, Dh) — a chunk of query tokens at absolute ``positions``
    (C,) int32; keys/values are read from the slot's pages via
    ``block_table_row`` (max_pages,). Each query attends causally to all
    cache positions ``<= positions[c]`` (earlier chunks + the causal
    prefix of this chunk, whose K/V the caller has already written).
    Padded queries (positions past the chunk's valid length) produce
    garbage rows the caller discards. XLA-composed: prefill is a few
    calls per request, the per-step hot path is the decode kernel.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    mp = block_table_row.shape[0]
    ps = k_pages.shape[1]
    h, dh = q.shape[1], q.shape[2]
    k = k_pages[block_table_row].reshape(mp * ps, h, dh)
    v = v_pages[block_table_row].reshape(mp * ps, h, dh)
    scores = jnp.einsum("chd,thd->hct", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    tok = jnp.arange(mp * ps, dtype=jnp.int32)
    causal = tok[None, None, :] <= positions[None, :, None]
    scores = jnp.where(causal, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    alive = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0)
    out = jnp.einsum("hct,thd->chd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
