"""Ragged paged attention kernels: the serving engine's hot path.

One fixed-shape call attends every slot's query token(s) over only that
slot's *live* KV pages — the "Ragged Paged Attention" TPU serving
pattern (PAPERS.md): sequences of wildly different lengths batch into
one step, and work/HBM traffic scale with live tokens, not with
``batch × max_len`` padding. Two kernels share the layout and the
online-softmax structure (the reusable-kernel argument of Tensor
Processing Primitives — prefill is a chunk-sized variant of decode, not
a fourth bespoke module):

``ragged_paged_decode_attention`` — one query token per slot:
  q            (S, H, Dh)        one query token per decode slot
  k/v pages    (P, ps, H, Dh)    fixed-size pages, token-major
  block_tables (S, max_pages)    page ids per slot (page 0 = null page)
  lengths      (S,)              live tokens per slot (0 = inactive slot)

``ragged_paged_prefill_attention`` — a CHUNK of C query tokens per slot
(the batched multi-request chunked-prefill step, ISSUE 6): queries sit
at absolute positions ``chunk_starts[s] + c`` and attend causally over
everything the slot has cached, including this chunk's own causal
prefix (whose K/V the caller writes before attending). Lanes past
``n_valid[s]`` (and whole inactive slots, ``n_valid == 0``) emit exact
zeros.

Each has two implementations with identical numerics:

- ``impl="lax"``: XLA gather + masked softmax (CPU/debug reference).
- ``impl="pallas"`` / ``"pallas_interpret"``: a Pallas kernel, grid
  ``(S, H, cdiv(max_pages, pages_per_block))``, that scalar-prefetches
  the block table so each kv block's HBM address is known before the
  body runs (the PrefetchScalarGridSpec pattern), does online-softmax
  accumulation over pages, and skips pages past the slot's live extent
  entirely. The interpret path runs the REAL kernel on CPU, so tier-1
  tests exercise it.

Both kernels register with the shared kernel layer
(:mod:`paddle_tpu.kernels`): the public entry points dispatch through
the registry, the ``pages_per_block`` tunable (how many of a slot's
pages one grid step streams — bit-equal output for any setting, the
accumulation order is identical) resolves from the shared autotuner at
trace time, and the registry's parity battery + graph-lint contract
rule cover both.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU pallas backend (interpret mode still works without a TPU)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from paddle_tpu.ops.attention import NEG_INF


def _on_tpu() -> bool:
    from paddle_tpu.kernels import harness
    return harness.on_tpu()


# ---------------------------------------------------------------------------
# lax reference path
# ---------------------------------------------------------------------------

def _paged_decode_lax(q, k_pages, v_pages, block_tables, lengths, scale):
    s_slots, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    # contract straight against the gathered 5-D (S, mp, ps, H, Dh)
    # layout — reshaping the gather to token-major would materialize a
    # full extra copy of every slot's K and V per call
    kg = k_pages[block_tables]
    vg = v_pages[block_tables]
    scores = jnp.einsum("shd,smthd->shmt", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) * scale
    scores = scores.reshape(s_slots, h, mp * ps)
    tok = jnp.arange(mp * ps, dtype=jnp.int32)
    valid = tok[None, None, :] < lengths[:, None, None]
    scores = jnp.where(valid, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    # length-0 slots: every key masked -> emit 0, not a uniform mean of v
    alive = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0).reshape(s_slots, h, mp, ps)
    out = jnp.einsum("shmt,smthd->shd", p, vg.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel: grid (S, H, max_pages), block-table scalar prefetch
# ---------------------------------------------------------------------------

def _online_softmax_page_fold(q, k_ref, v_ref, mask, m_scr, l_scr,
                              acc_scr):
    """Fold ONE (ps, H-sliced) kv page into the running (m, l, acc)
    online-softmax state. ``mask`` (rows, ps) marks live score entries;
    masked entries go to NEG_INF and contribute exact zeros. Shared by
    the decode and prefill kernels — the accumulation order here IS the
    byte-parity contract, so it must not diverge between them."""
    k = k_ref[0, :, 0, :].astype(jnp.float32)          # (ps, Dh)
    v = v_ref[0, :, 0, :].astype(jnp.float32)          # (ps, Dh)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # (rows, ps)
    s = jnp.where(mask, s, NEG_INF)

    m_prev = m_scr[...]                                # (rows, 128)
    l_prev = l_scr[...]
    m_cur = jnp.max(s, axis=1, keepdims=True)          # (rows, 1)
    m_next = jnp.maximum(m_prev, m_cur)                # lanes broadcast
    alpha = jnp.exp(m_prev - m_next)
    p = jnp.exp(s - m_next[:, :1])                     # (rows, ps)
    l_scr[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    m_scr[...] = m_next
    pv = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # (rows, Dh)
    acc_scr[...] = acc_scr[...] * alpha[:, :1] + pv


def _paged_decode_kernel(bt_ref, len_ref, q_ref, *rest, page_size,
                         pages_per_block):
    """Online-softmax over a slot's pages, ``pages_per_block`` pages per
    grid step (the shared autotuner's tunable: fewer grid iterations,
    deeper DMA pipelining; the per-page accumulation ORDER is identical
    to pages_per_block=1, so outputs are bit-equal for any setting)."""
    pb = pages_per_block
    k_refs = rest[:pb]
    v_refs = rest[pb:2 * pb]
    o_ref = rest[2 * pb]
    m_scr, l_scr, acc_scr = rest[2 * pb + 1:]
    sl = pl.program_id(0)
    pj = pl.program_id(2)
    npg = pl.num_programs(2)

    @pl.when(pj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[sl]

    def _body():
        q = q_ref[0].astype(jnp.float32)               # (1, Dh)
        for t in range(pb):
            # tokens at/after the slot's length (incl. whole tail pages
            # of this block, and the clamped duplicate page when pb does
            # not divide max_pages) mask to NEG_INF -> exact-zero
            # contributions to l and acc
            tok = (pj * pb + t) * page_size + jax.lax.broadcasted_iota(
                jnp.int32, (1, page_size), 1)
            _online_softmax_page_fold(q, k_refs[t], v_refs[t],
                                      tok < length, m_scr, l_scr,
                                      acc_scr)

    # ragged skip: blocks wholly at/after the slot's length do nothing
    pl.when(pj * pb * page_size < length)(_body)

    @pl.when(pj == npg - 1)
    def _finish():
        denom = l_scr[...][:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        alive = m_scr[...][:, :1] > NEG_INF / 2
        o_ref[0] = jnp.where(alive, acc_scr[...] / denom, 0.0).astype(
            o_ref.dtype)


def _paged_kv_specs(ps, dh, mp, pb):
    """``pb`` (k, v) BlockSpec pairs per grid step: page ``j*pb + t`` of
    the slot's block table (clamped to the last page — the clamped
    duplicate is fully masked by the token test in the kernel body).
    The index maps take the scalar-prefetch refs after the grid ids;
    the block table is always the first of them."""
    def kv_spec(t):
        def index(s, hh, j, bt, *_rest):
            return (bt[s, jnp.minimum(j * pb + t, mp - 1)], 0, hh, 0)
        return pl.BlockSpec((1, ps, 1, dh), index)
    ks = [kv_spec(t) for t in range(pb)]
    vs = [kv_spec(t) for t in range(pb)]
    return ks, vs


def _paged_decode_pallas(q, k_pages, v_pages, block_tables, lengths, scale,
                         interpret, pages_per_block=1):
    if pltpu is None:  # pragma: no cover
        raise RuntimeError("Pallas TPU backend unavailable; use impl='lax'")
    s_slots, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    pb = max(1, min(int(pages_per_block), mp))
    qs = (q * jnp.asarray(scale, q.dtype))
    k_specs, v_specs = _paged_kv_specs(ps, dh, mp, pb)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block_tables, lengths
        grid=(s_slots, h, pl.cdiv(mp, pb)),
        in_specs=[
            pl.BlockSpec((1, 1, dh), lambda s, hh, j, bt, ln: (s, hh, 0)),
            *k_specs,
            *v_specs,
        ],
        out_specs=pl.BlockSpec((1, 1, dh),
                               lambda s, hh, j, bt, ln: (s, hh, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, dh), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_decode_kernel, page_size=ps,
                               pages_per_block=pb)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, h, dh), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      qs, *([k_pages] * pb), *([v_pages] * pb))
    return out


# ---------------------------------------------------------------------------
# batched chunked prefill: lax reference + Pallas kernel
# ---------------------------------------------------------------------------

def _paged_prefill_lax(q, k_pages, v_pages, block_tables, chunk_starts,
                       n_valid, scale):
    s_slots, c, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    kg = k_pages[block_tables]                     # (S, mp, ps, H, Dh)
    vg = v_pages[block_tables]
    scores = jnp.einsum("schd,smthd->shcmt", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) * scale
    scores = scores.reshape(s_slots, h, c, mp * ps)
    tok = jnp.arange(mp * ps, dtype=jnp.int32)
    pos = chunk_starts[:, None] + jnp.arange(c, dtype=jnp.int32)  # (S, C)
    causal = tok[None, None, None, :] <= pos[:, None, :, None]
    row_ok = (jnp.arange(c) < n_valid[:, None])[:, None, :, None]
    scores = jnp.where(causal & row_ok, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    # masked rows (padding lanes / inactive slots) emit exact zeros
    alive = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0).reshape(s_slots, h, c, mp, ps)
    out = jnp.einsum("shcmt,smthd->schd", p, vg.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_prefill_kernel(bt_ref, start_ref, nv_ref, q_ref, *rest,
                          page_size, pages_per_block):
    """Chunked-prefill analog of :func:`_paged_decode_kernel`: same
    ``pages_per_block`` tunable, same bit-equal accumulation order."""
    pb = pages_per_block
    k_refs = rest[:pb]
    v_refs = rest[pb:2 * pb]
    o_ref = rest[2 * pb]
    m_scr, l_scr, acc_scr = rest[2 * pb + 1:]
    sl = pl.program_id(0)
    pj = pl.program_id(2)
    npg = pl.num_programs(2)

    @pl.when(pj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    start = start_ref[sl]
    nv = nv_ref[sl]

    def _body():
        q = q_ref[0, :, 0, :].astype(jnp.float32)      # (C, Dh)
        cc = q.shape[0]
        for t in range(pb):
            tok = (pj * pb + t) * page_size + jax.lax.broadcasted_iota(
                jnp.int32, (cc, page_size), 1)
            row = jax.lax.broadcasted_iota(jnp.int32, (cc, page_size), 0)
            ok = (tok <= start + row) & (row < nv)     # causal + live lane
            _online_softmax_page_fold(q, k_refs[t], v_refs[t], ok,
                                      m_scr, l_scr, acc_scr)

    # ragged skip: blocks wholly past the chunk's live extent do nothing
    pl.when((nv > 0) & (pj * pb * page_size < start + nv))(_body)

    @pl.when(pj == npg - 1)
    def _finish():
        denom = l_scr[...][:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        alive = m_scr[...][:, :1] > NEG_INF / 2
        o_ref[0, :, 0, :] = jnp.where(
            alive, acc_scr[...] / denom, 0.0).astype(o_ref.dtype)


def _paged_prefill_pallas(q, k_pages, v_pages, block_tables, chunk_starts,
                          n_valid, scale, interpret, pages_per_block=1):
    if pltpu is None:  # pragma: no cover
        raise RuntimeError("Pallas TPU backend unavailable; use impl='lax'")
    s_slots, c, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    pb = max(1, min(int(pages_per_block), mp))
    qs = (q * jnp.asarray(scale, q.dtype))
    k_specs, v_specs = _paged_kv_specs(ps, dh, mp, pb)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # block_tables, chunk_starts, n_valid
        grid=(s_slots, h, pl.cdiv(mp, pb)),
        in_specs=[
            pl.BlockSpec((1, c, 1, dh),
                         lambda s, hh, j, bt, st, nv: (s, 0, hh, 0)),
            *k_specs,
            *v_specs,
        ],
        out_specs=pl.BlockSpec((1, c, 1, dh),
                               lambda s, hh, j, bt, st, nv: (s, 0, hh, 0)),
        scratch_shapes=[
            pltpu.VMEM((c, 128), jnp.float32),
            pltpu.VMEM((c, 128), jnp.float32),
            pltpu.VMEM((c, dh), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_prefill_kernel, page_size=ps,
                               pages_per_block=pb)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, c, h, dh), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), chunk_starts.astype(jnp.int32),
      n_valid.astype(jnp.int32), qs, *([k_pages] * pb), *([v_pages] * pb))
    return out


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

def ragged_paged_decode_attention(q, k_pages, v_pages, block_tables,
                                  lengths, *, scale: Optional[float] = None,
                                  impl: str = "auto"):
    """One decode step of attention for every slot at once.

    ``q`` (S, H, Dh); ``k_pages``/``v_pages`` (P, page_size, H, Dh);
    ``block_tables`` (S, max_pages) int32; ``lengths`` (S,) int32 valid
    tokens per slot. Returns (S, H, Dh). ``impl``: "auto" (pallas on
    TPU, lax elsewhere), "lax", "pallas", "pallas_interpret".
    """
    from paddle_tpu import kernels
    return kernels.dispatch("ragged_paged_decode", q, k_pages, v_pages,
                            block_tables, lengths, impl=impl, scale=scale)


def ragged_paged_prefill_attention(q, k_pages, v_pages, block_tables,
                                   chunk_starts, n_valid, *,
                                   scale: Optional[float] = None,
                                   impl: str = "auto"):
    """One batched chunked-prefill step of attention for every slot.

    ``q`` (S, C, H, Dh) — a chunk of C query tokens per slot, the first
    ``n_valid[s]`` real (rest padding), at absolute positions
    ``chunk_starts[s] + c``; keys/values are read from each slot's pages
    via ``block_tables`` (S, max_pages). Each live query attends
    causally to all cache positions ``<= chunk_starts[s] + c`` (earlier
    chunks, shared prefix pages, and this chunk's causal prefix — whose
    K/V the caller has already written). Padding lanes and inactive
    slots (``n_valid == 0``) emit exact zeros. Returns (S, C, H, Dh).
    ``impl``: "auto" (pallas on TPU, lax elsewhere), "lax", "pallas",
    "pallas_interpret".
    """
    from paddle_tpu import kernels
    return kernels.dispatch("ragged_paged_prefill", q, k_pages, v_pages,
                            block_tables, chunk_starts, n_valid,
                            impl=impl, scale=scale)


def paged_prefill_attention(q, k_pages, v_pages, block_table_row,
                            positions, *, scale: Optional[float] = None):
    """Chunked-prefill attention for ONE slot.

    ``q`` (C, H, Dh) — a chunk of query tokens at absolute ``positions``
    (C,) int32; keys/values are read from the slot's pages via
    ``block_table_row`` (max_pages,). Each query attends causally to all
    cache positions ``<= positions[c]`` (earlier chunks + the causal
    prefix of this chunk, whose K/V the caller has already written).
    Padded queries (positions past the chunk's valid length) produce
    garbage rows the caller discards. XLA-composed: prefill is a few
    calls per request, the per-step hot path is the decode kernel.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    mp = block_table_row.shape[0]
    ps = k_pages.shape[1]
    h, dh = q.shape[1], q.shape[2]
    k = k_pages[block_table_row].reshape(mp * ps, h, dh)
    v = v_pages[block_table_row].reshape(mp * ps, h, dh)
    scores = jnp.einsum("chd,thd->hct", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    tok = jnp.arange(mp * ps, dtype=jnp.int32)
    causal = tok[None, None, :] <= positions[None, :, None]
    scores = jnp.where(causal, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    alive = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0)
    out = jnp.einsum("hct,thd->chd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# kernel-registry entries (paddle_tpu.kernels)
# ---------------------------------------------------------------------------

def _decode_kernel_pallas(q, k_pages, v_pages, block_tables, lengths, *,
                          block_sizes, interpret, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_decode_pallas(
        q, k_pages, v_pages, block_tables, lengths, scale, interpret,
        pages_per_block=block_sizes.get("pages_per_block", 1))


def _decode_kernel_lax(q, k_pages, v_pages, block_tables, lengths, *,
                       scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_decode_lax(q, k_pages, v_pages, block_tables, lengths,
                             scale)


def _decode_kernel_reference(q, k_pages, v_pages, block_tables, lengths,
                             *, scale=None):
    """NumPy per-slot dense attention — independent of both impls."""
    import numpy as np
    s_slots, h, dh = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(dh)
    mp, ps = block_tables.shape[1], k_pages.shape[1]
    qn = np.asarray(q, np.float32)
    kp = np.asarray(k_pages, np.float32)
    vp = np.asarray(v_pages, np.float32)
    bt = np.asarray(block_tables)
    ln = np.asarray(lengths)
    outs = np.zeros((s_slots, h, dh), np.float32)
    for sl in range(s_slots):
        n = int(ln[sl])
        if n == 0:
            continue
        k = kp[bt[sl]].reshape(mp * ps, h, dh)[:n]
        v = vp[bt[sl]].reshape(mp * ps, h, dh)[:n]
        s = np.einsum("hd,thd->ht", qn[sl], k) * scale
        s = s - s.max(-1, keepdims=True)
        p = np.exp(s)
        p = p / p.sum(-1, keepdims=True)
        outs[sl] = np.einsum("ht,thd->hd", p, v)
    return jnp.asarray(outs).astype(q.dtype)


def _make_paged_sample(seed, *, chunked):
    import numpy as np
    s_slots, h, dh, ps, mp = (
        (4, 2, 16, 8, 3), (6, 4, 32, 16, 4), (8, 4, 64, 16, 6))[seed % 3]
    c = ps  # prefill chunk = one page of queries
    num_pages = s_slots * mp + 1
    rng = np.random.default_rng(seed)
    k_pages = jnp.asarray(
        rng.standard_normal((num_pages, ps, h, dh)), jnp.float32)
    v_pages = jnp.asarray(
        rng.standard_normal((num_pages, ps, h, dh)), jnp.float32)
    perm = rng.permutation(num_pages - 1)[:s_slots * mp] + 1
    block_tables = jnp.asarray(perm.reshape(s_slots, mp), jnp.int32)
    if not chunked:
        q = jnp.asarray(rng.standard_normal((s_slots, h, dh)),
                        jnp.float32)
        lengths = jnp.asarray(
            rng.integers(0, mp * ps + 1, s_slots), jnp.int32)
        return (q, k_pages, v_pages, block_tables, lengths), {}
    q = jnp.asarray(rng.standard_normal((s_slots, c, h, dh)), jnp.float32)
    starts = jnp.asarray(
        rng.integers(0, (mp - 1) * ps, s_slots), jnp.int32)
    n_valid = jnp.asarray(rng.integers(0, c + 1, s_slots), jnp.int32)
    return (q, k_pages, v_pages, block_tables, starts, n_valid), {}


def _paged_tune_signature(args, kwargs):
    q, k_pages, _v, bt = args[0], args[1], args[2], args[3]
    sig = [("s", q.shape[0]), ("h", k_pages.shape[2]),
           ("d", q.shape[-1]), ("ps", k_pages.shape[1]),
           ("mp", bt.shape[1])]
    if q.ndim == 4:                      # prefill: chunk width matters
        sig.insert(1, ("c", q.shape[1]))
    return tuple(sig)


def _paged_vmem_estimate(args, kwargs, blocks):
    q, k_pages = args[0], args[1]
    ps, dh = k_pages.shape[1], k_pages.shape[-1]
    c = q.shape[1] if q.ndim == 4 else 1
    pb = blocks.get("pages_per_block", 1)
    # fp32 working set: pb (k, v) page pairs + q/acc + m/l lane scratch
    return 4 * (2 * pb * ps * dh + 2 * c * dh + 2 * c * 128
                + 2 * c * ps)


def _decode_donation_probe():
    (q, k_pages, v_pages, block_tables, lengths), _ = \
        _make_paged_sample(0, chunked=False)

    def step(kp, vp, q, bt, lens):
        # the engine's real pattern: write this step's token K/V into
        # the pages, attend THROUGH THE PALLAS BODY (interpret lowering
        # — the structure XLA aliases, incl. the pages-passed-
        # pages_per_block-times operand shape), hand the pages back
        kp = kp.at[1, 0].set(q[0])
        vp = vp.at[1, 0].set(q[0])
        out = _decode_kernel_pallas(
            q, kp, vp, bt, lens,
            block_sizes={"pages_per_block": 4}, interpret=True)
        return out, kp, vp

    args = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for a in (k_pages, v_pages, q, block_tables, lengths))
    return step, args, (0, 1)


def _prefill_kernel_pallas(q, k_pages, v_pages, block_tables,
                           chunk_starts, n_valid, *, block_sizes,
                           interpret, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_prefill_pallas(
        q, k_pages, v_pages, block_tables, chunk_starts, n_valid, scale,
        interpret, pages_per_block=block_sizes.get("pages_per_block", 1))


def _prefill_kernel_lax(q, k_pages, v_pages, block_tables, chunk_starts,
                        n_valid, *, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_prefill_lax(q, k_pages, v_pages, block_tables,
                              chunk_starts, n_valid, scale)


def _prefill_kernel_reference(q, k_pages, v_pages, block_tables,
                              chunk_starts, n_valid, *, scale=None):
    """NumPy per-slot, per-row causal attention over the slot's pages."""
    import numpy as np
    s_slots, c, h, dh = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(dh)
    mp, ps = block_tables.shape[1], k_pages.shape[1]
    qn = np.asarray(q, np.float32)
    kp = np.asarray(k_pages, np.float32)
    vp = np.asarray(v_pages, np.float32)
    bt = np.asarray(block_tables)
    st = np.asarray(chunk_starts)
    nv = np.asarray(n_valid)
    outs = np.zeros((s_slots, c, h, dh), np.float32)
    for sl in range(s_slots):
        k = kp[bt[sl]].reshape(mp * ps, h, dh)
        v = vp[bt[sl]].reshape(mp * ps, h, dh)
        for r in range(int(nv[sl])):
            limit = int(st[sl]) + r + 1          # causal horizon
            s = np.einsum("hd,thd->ht", qn[sl, r], k[:limit]) * scale
            s = s - s.max(-1, keepdims=True)
            p = np.exp(s)
            p = p / p.sum(-1, keepdims=True)
            outs[sl, r] = np.einsum("ht,thd->hd", p, v[:limit])
    return jnp.asarray(outs).astype(q.dtype)


def _prefill_donation_probe():
    (q, k_pages, v_pages, block_tables, starts, n_valid), _ = \
        _make_paged_sample(0, chunked=True)

    def step(kp, vp, q, bt, st, nv):
        kp = kp.at[1, 0].set(q[0, 0])
        vp = vp.at[1, 0].set(q[0, 0])
        out = _prefill_kernel_pallas(
            q, kp, vp, bt, st, nv,
            block_sizes={"pages_per_block": 4}, interpret=True)
        return out, kp, vp

    args = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for a in (k_pages, v_pages, q, block_tables, starts,
                           n_valid))
    return step, args, (0, 1)


def _register_paged_kernels():
    from paddle_tpu import kernels
    pb_candidates = {"pages_per_block": (1, 2, 4)}
    kernels.register(kernels.KernelSpec(
        name="ragged_paged_decode",
        contract=kernels.KernelContract(
            version=1,
            arg_layouts={"q": "(S,H,Dh)", "k_pages": "(P,ps,H,Dh)",
                         "v_pages": "(P,ps,H,Dh)",
                         "block_tables": "(S,mp) i32",
                         "lengths": "(S,) i32"},
            out_layout="(S,H,Dh)",
            donatable=("k_pages", "v_pages"),
            grid="(S, H, cdiv(mp,pages_per_block)) block-table scalar "
                 "prefetch, dead-page skip",
            block_candidates=pb_candidates,
            atol=2e-5, rtol=2e-5),
        pallas_fn=_decode_kernel_pallas,
        lax_fn=_decode_kernel_lax,
        reference_fn=_decode_kernel_reference,
        sample_inputs=lambda seed: _make_paged_sample(seed, chunked=False),
        pallas_sites=(
            "paddle_tpu.serving.decode_attention:_paged_decode_pallas",),
        tune_signature=_paged_tune_signature,
        vmem_estimate=_paged_vmem_estimate,
        donation_probe=_decode_donation_probe))
    kernels.register(kernels.KernelSpec(
        name="ragged_paged_prefill",
        contract=kernels.KernelContract(
            version=1,
            arg_layouts={"q": "(S,C,H,Dh)", "k_pages": "(P,ps,H,Dh)",
                         "v_pages": "(P,ps,H,Dh)",
                         "block_tables": "(S,mp) i32",
                         "chunk_starts": "(S,) i32",
                         "n_valid": "(S,) i32"},
            out_layout="(S,C,H,Dh)",
            donatable=("k_pages", "v_pages"),
            grid="(S, H, cdiv(mp,pages_per_block)) block-table scalar "
                 "prefetch, causal + live-lane mask",
            block_candidates=pb_candidates,
            atol=2e-5, rtol=2e-5),
        pallas_fn=_prefill_kernel_pallas,
        lax_fn=_prefill_kernel_lax,
        reference_fn=_prefill_kernel_reference,
        sample_inputs=lambda seed: _make_paged_sample(seed, chunked=True),
        pallas_sites=(
            "paddle_tpu.serving.decode_attention:_paged_prefill_pallas",),
        tune_signature=_paged_tune_signature,
        vmem_estimate=_paged_vmem_estimate,
        donation_probe=_prefill_donation_probe))


_register_paged_kernels()
