"""Ragged paged attention kernels: the serving engine's hot path.

One fixed-shape call attends every slot's query token(s) over only that
slot's *live* KV pages — the "Ragged Paged Attention" TPU serving
pattern (PAPERS.md): sequences of wildly different lengths batch into
one step, and work/HBM traffic scale with live tokens, not with
``batch × max_len`` padding. Two kernels share the layout and the
online-softmax structure (the reusable-kernel argument of Tensor
Processing Primitives — prefill is a chunk-sized variant of decode, not
a fourth bespoke module):

``ragged_paged_decode_attention`` — one query token per slot:
  q            (S, H, Dh)        one query token per decode slot
  k/v pages    (P, ps, H, Dh)    fixed-size pages, token-major
  block_tables (S, max_pages)    page ids per slot (page 0 = null page)
  lengths      (S,)              live tokens per slot (0 = inactive slot)

``ragged_paged_prefill_attention`` — a CHUNK of C query tokens per slot
(the batched multi-request chunked-prefill step, ISSUE 6): queries sit
at absolute positions ``chunk_starts[s] + c`` and attend causally over
everything the slot has cached, including this chunk's own causal
prefix (whose K/V the caller writes before attending). Lanes past
``n_valid[s]`` (and whole inactive slots, ``n_valid == 0``) emit exact
zeros.

Each has two implementations with identical numerics:

- ``impl="lax"``: XLA gather + masked softmax (CPU/debug reference).
- ``impl="pallas"`` / ``"pallas_interpret"``: a Pallas kernel, grid
  ``(S, H, cdiv(max_pages, pages_per_block))``, that scalar-prefetches
  the block table so each kv block's HBM address is known before the
  body runs (the PrefetchScalarGridSpec pattern), does online-softmax
  accumulation over pages, and skips pages past the slot's live extent
  entirely. The interpret path runs the REAL kernel on CPU, so tier-1
  tests exercise it.

Both kernels register with the shared kernel layer
(:mod:`paddle_tpu.kernels`): the public entry points dispatch through
the registry, the ``pages_per_block`` tunable (how many of a slot's
pages one grid step streams — bit-equal output for any setting, the
accumulation order is identical) resolves from the shared autotuner at
trace time, and the registry's parity battery + graph-lint contract
rule cover both.

**Dequant-attend int8 variants** (ISSUE 13):
``ragged_paged_decode_int8_attention`` and
``ragged_paged_prefill_int8_attention`` attend over an INT8 page pool
with per-token-row fp32 scales (``paged_cache.quantize_kv``'s layout).
The Pallas bodies stream the int8 pages through the SAME
``_online_softmax_page_fold`` with the scale broadcast fused into the
QK and PV products — no dequantized fp page is ever materialized, HBM
traffic per attended token halves (the bytes-per-token lever the cost
model gates in CI). Registered like the fp kernels: lax fallbacks with
identical scale-after-dot numerics, independent dense references,
contracts with donation-safe pages AND scales, and the shared
``pages_per_block`` tunable.

**Tensor-parallel variants** (ISSUE 15):
``ragged_paged_{decode,prefill}[_int8]_tp_attention`` run the
single-device kernels per head shard under ``shard_map`` — pages and
queries sharded ``H/tp`` over the mesh's "tp" axis, block-table
geometry (and int8 scale rows) replicated. Heads are independent, so
each shard's output is BIT-identical to the tp=1 kernel; the one
attention-output collective lives at the caller's row-sharded output
projection, not in the kernel. Registered as mesh contracts
(``requires_mesh``) with their own parity battery and engine-shaped
donation probes that the kernel-contract lint lowers to verify
per-shard aliasing AND the declared ``("all_reduce",)`` collective set.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU pallas backend (interpret mode still works without a TPU)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from paddle_tpu.ops.attention import NEG_INF


def _on_tpu() -> bool:
    from paddle_tpu.kernels import harness
    return harness.on_tpu()


# ---------------------------------------------------------------------------
# lax reference path
# ---------------------------------------------------------------------------

def _paged_decode_lax(q, k_pages, v_pages, block_tables, lengths, scale):
    s_slots, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    # contract straight against the gathered 5-D (S, mp, ps, H, Dh)
    # layout — reshaping the gather to token-major would materialize a
    # full extra copy of every slot's K and V per call
    kg = k_pages[block_tables]
    vg = v_pages[block_tables]
    scores = jnp.einsum("shd,smthd->shmt", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) * scale
    scores = scores.reshape(s_slots, h, mp * ps)
    tok = jnp.arange(mp * ps, dtype=jnp.int32)
    valid = tok[None, None, :] < lengths[:, None, None]
    scores = jnp.where(valid, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    # length-0 slots: every key masked -> emit 0, not a uniform mean of v
    alive = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0).reshape(s_slots, h, mp, ps)
    out = jnp.einsum("shmt,smthd->shd", p, vg.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel: grid (S, H, max_pages), block-table scalar prefetch
# ---------------------------------------------------------------------------

def _online_softmax_page_fold(q, k_ref, v_ref, mask, m_scr, l_scr,
                              acc_scr, k_scale=None, v_scale=None):
    """Fold ONE (ps, H-sliced) kv page into the running (m, l, acc)
    online-softmax state. ``mask`` (rows, ps) marks live score entries;
    masked entries go to NEG_INF and contribute exact zeros. Shared by
    the decode and prefill kernels — the accumulation order here IS the
    byte-parity contract, so it must not diverge between them.

    ``k_scale``/``v_scale`` (ps,) are the int8 page pool's per-token-row
    dequant scales (None on the fp path): the scale broadcast is fused
    INTO the QK and PV products — the int8 page goes straight into the
    dot and the per-token scale multiplies the (rows, ps) score/weight
    matrix, so no dequantized fp page is ever materialized (the TPP
    fused-microkernel shape). The m/l/acc update sequence is identical
    either way, so the int8 kernels inherit the same per-page
    accumulation-order contract."""
    k = k_ref[0, :, 0, :].astype(jnp.float32)          # (ps, Dh)
    v = v_ref[0, :, 0, :].astype(jnp.float32)          # (ps, Dh)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # (rows, ps)
    if k_scale is not None:
        s = s * k_scale[None, :]
    s = jnp.where(mask, s, NEG_INF)

    m_prev = m_scr[...]                                # (rows, 128)
    l_prev = l_scr[...]
    m_cur = jnp.max(s, axis=1, keepdims=True)          # (rows, 1)
    m_next = jnp.maximum(m_prev, m_cur)                # lanes broadcast
    alpha = jnp.exp(m_prev - m_next)
    p = jnp.exp(s - m_next[:, :1])                     # (rows, ps)
    l_scr[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    m_scr[...] = m_next
    if v_scale is not None:
        p = p * v_scale[None, :]
    pv = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # (rows, Dh)
    acc_scr[...] = acc_scr[...] * alpha[:, :1] + pv


def _split_kv_refs(rest, pb, quantized):
    """Unpack a paged kernel's trailing refs: ``pb`` k blocks, ``pb`` v
    blocks, (quantized only) ``pb`` k-scale + ``pb`` v-scale rows, then
    the output ref and the three online-softmax scratch buffers. ONE
    unpacking convention for the fp and int8 variants of both kernels."""
    k_refs = rest[:pb]
    v_refs = rest[pb:2 * pb]
    if quantized:
        ks_refs = rest[2 * pb:3 * pb]
        vs_refs = rest[3 * pb:4 * pb]
        base = 4 * pb
    else:
        ks_refs = vs_refs = (None,) * pb
        base = 2 * pb
    o_ref = rest[base]
    m_scr, l_scr, acc_scr = rest[base + 1:]
    return k_refs, v_refs, ks_refs, vs_refs, o_ref, m_scr, l_scr, acc_scr


def _paged_decode_kernel(bt_ref, len_ref, q_ref, *rest, page_size,
                         pages_per_block, quantized=False):
    """Online-softmax over a slot's pages, ``pages_per_block`` pages per
    grid step (the shared autotuner's tunable: fewer grid iterations,
    deeper DMA pipelining; the per-page accumulation ORDER is identical
    to pages_per_block=1, so outputs are bit-equal for any setting).
    ``quantized`` is ONE static flag, not a second kernel: the int8
    page blocks ride with their per-token scale rows and the scales
    fuse into the shared fold — grid, ragged skip, and finish logic
    cannot diverge between the fp and dequant-attend variants."""
    pb = pages_per_block
    (k_refs, v_refs, ks_refs, vs_refs, o_ref, m_scr, l_scr,
     acc_scr) = _split_kv_refs(rest, pb, quantized)
    sl = pl.program_id(0)
    pj = pl.program_id(2)
    npg = pl.num_programs(2)

    @pl.when(pj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[sl]

    def _body():
        q = q_ref[0].astype(jnp.float32)               # (1, Dh)
        for t in range(pb):
            # tokens at/after the slot's length (incl. whole tail pages
            # of this block, and the clamped duplicate page when pb does
            # not divide max_pages) mask to NEG_INF -> exact-zero
            # contributions to l and acc
            tok = (pj * pb + t) * page_size + jax.lax.broadcasted_iota(
                jnp.int32, (1, page_size), 1)
            _online_softmax_page_fold(
                q, k_refs[t], v_refs[t], tok < length, m_scr, l_scr,
                acc_scr,
                k_scale=ks_refs[t][0, :] if quantized else None,
                v_scale=vs_refs[t][0, :] if quantized else None)

    # ragged skip: blocks wholly at/after the slot's length do nothing
    pl.when(pj * pb * page_size < length)(_body)

    @pl.when(pj == npg - 1)
    def _finish():
        denom = l_scr[...][:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        alive = m_scr[...][:, :1] > NEG_INF / 2
        o_ref[0] = jnp.where(alive, acc_scr[...] / denom, 0.0).astype(
            o_ref.dtype)


def _paged_kv_specs(ps, dh, mp, pb):
    """``pb`` (k, v) BlockSpec pairs per grid step: page ``j*pb + t`` of
    the slot's block table (clamped to the last page — the clamped
    duplicate is fully masked by the token test in the kernel body).
    The index maps take the scalar-prefetch refs after the grid ids;
    the block table is always the first of them."""
    def kv_spec(t):
        def index(s, hh, j, bt, *_rest):
            return (bt[s, jnp.minimum(j * pb + t, mp - 1)], 0, hh, 0)
        return pl.BlockSpec((1, ps, 1, dh), index)
    ks = [kv_spec(t) for t in range(pb)]
    vs = [kv_spec(t) for t in range(pb)]
    return ks, vs


def _paged_scale_specs(ps, mp, pb):
    """``pb`` (k_scale, v_scale) BlockSpec pairs — one (1, ps) scale row
    per streamed page, indexed by the SAME block-table entry as the page
    itself, so a page and its dequant scales always arrive together."""
    def sc_spec(t):
        def index(s, hh, j, bt, *_rest):
            return (bt[s, jnp.minimum(j * pb + t, mp - 1)], 0)
        return pl.BlockSpec((1, ps), index)
    ks = [sc_spec(t) for t in range(pb)]
    vs = [sc_spec(t) for t in range(pb)]
    return ks, vs


def _paged_decode_pallas(q, k_pages, v_pages, block_tables, lengths, scale,
                         interpret, pages_per_block=1, k_scales=None,
                         v_scales=None):
    """``k_scales``/``v_scales`` given = the dequant-attend variant:
    same grid and BlockSpecs plus one (1, ps) scale row per streamed
    page, fused into the shared fold inside the ONE kernel body."""
    if pltpu is None:  # pragma: no cover
        raise RuntimeError("Pallas TPU backend unavailable; use impl='lax'")
    quantized = k_scales is not None
    s_slots, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    pb = max(1, min(int(pages_per_block), mp))
    qs = (q * jnp.asarray(scale, q.dtype))
    k_specs, v_specs = _paged_kv_specs(ps, dh, mp, pb)
    sc_specs, sc_args = [], []
    if quantized:
        ks_specs, vs_specs = _paged_scale_specs(ps, mp, pb)
        sc_specs = [*ks_specs, *vs_specs]
        sc_args = [*([k_scales] * pb), *([v_scales] * pb)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block_tables, lengths
        grid=(s_slots, h, pl.cdiv(mp, pb)),
        in_specs=[
            pl.BlockSpec((1, 1, dh), lambda s, hh, j, bt, ln: (s, hh, 0)),
            *k_specs,
            *v_specs,
            *sc_specs,
        ],
        out_specs=pl.BlockSpec((1, 1, dh),
                               lambda s, hh, j, bt, ln: (s, hh, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, dh), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_decode_kernel, page_size=ps,
                               pages_per_block=pb, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, h, dh), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      qs, *([k_pages] * pb), *([v_pages] * pb), *sc_args)
    return out


# ---------------------------------------------------------------------------
# int8 dequant-attend decode: same grid, scales fused into QK/PV
# ---------------------------------------------------------------------------

def _paged_decode_int8_lax(q, k_pages, v_pages, k_scales, v_scales,
                           block_tables, lengths, scale):
    """Lax fallback of the dequant-attend decode kernel: gather the INT8
    pages (half the HBM bytes of bf16) and fold the per-token-row scales
    into the score and weight matrices — structurally the same
    scale-after-dot order as the Pallas body, so numerics agree. The
    int8 pools pass through :func:`slim.int8_resident` so a frozen
    graph that bakes them as constants cannot be constant-folded to fp
    (the keep-quantized idiom, shared with weight PTQ)."""
    from paddle_tpu import slim
    k_pages = slim.int8_resident(k_pages)
    v_pages = slim.int8_resident(v_pages)
    s_slots, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    kg = k_pages[block_tables]                  # (S, mp, ps, H, Dh) int8
    vg = v_pages[block_tables]
    ksg = k_scales[block_tables]                # (S, mp, ps) f32
    vsg = v_scales[block_tables]
    scores = jnp.einsum("shd,smthd->shmt", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) * scale
    scores = scores * ksg[:, None]              # dequant fused post-dot
    scores = scores.reshape(s_slots, h, mp * ps)
    tok = jnp.arange(mp * ps, dtype=jnp.int32)
    valid = tok[None, None, :] < lengths[:, None, None]
    scores = jnp.where(valid, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    alive = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0).reshape(s_slots, h, mp, ps)
    p = p * vsg[:, None]                        # dequant fused pre-PV
    out = jnp.einsum("shmt,smthd->shd", p, vg.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_decode_int8_pallas(q, k_pages, v_pages, k_scales, v_scales,
                              block_tables, lengths, scale, interpret,
                              pages_per_block=1):
    """The dequant-attend decode entry: the SAME kernel body as the fp
    path with ``quantized=True`` — per-page scale rows ride as ``pb``
    extra scalar-prefetched blocks, fused into the QK/PV products
    inside the shared fold (no materialized fp page)."""
    return _paged_decode_pallas(q, k_pages, v_pages, block_tables,
                                lengths, scale, interpret,
                                pages_per_block=pages_per_block,
                                k_scales=k_scales, v_scales=v_scales)


# ---------------------------------------------------------------------------
# batched chunked prefill: lax reference + Pallas kernel
# ---------------------------------------------------------------------------

def _paged_prefill_lax(q, k_pages, v_pages, block_tables, chunk_starts,
                       n_valid, scale):
    s_slots, c, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    kg = k_pages[block_tables]                     # (S, mp, ps, H, Dh)
    vg = v_pages[block_tables]
    scores = jnp.einsum("schd,smthd->shcmt", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) * scale
    scores = scores.reshape(s_slots, h, c, mp * ps)
    tok = jnp.arange(mp * ps, dtype=jnp.int32)
    pos = chunk_starts[:, None] + jnp.arange(c, dtype=jnp.int32)  # (S, C)
    causal = tok[None, None, None, :] <= pos[:, None, :, None]
    row_ok = (jnp.arange(c) < n_valid[:, None])[:, None, :, None]
    scores = jnp.where(causal & row_ok, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    # masked rows (padding lanes / inactive slots) emit exact zeros
    alive = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0).reshape(s_slots, h, c, mp, ps)
    out = jnp.einsum("shcmt,smthd->schd", p, vg.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_prefill_kernel(bt_ref, start_ref, nv_ref, q_ref, *rest,
                          page_size, pages_per_block, quantized=False):
    """Chunked-prefill analog of :func:`_paged_decode_kernel`: same
    ``pages_per_block`` tunable, same bit-equal accumulation order, and
    the same single ``quantized`` flag for the dequant-attend variant
    (scale rows fused into the shared fold)."""
    pb = pages_per_block
    (k_refs, v_refs, ks_refs, vs_refs, o_ref, m_scr, l_scr,
     acc_scr) = _split_kv_refs(rest, pb, quantized)
    sl = pl.program_id(0)
    pj = pl.program_id(2)
    npg = pl.num_programs(2)

    @pl.when(pj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    start = start_ref[sl]
    nv = nv_ref[sl]

    def _body():
        q = q_ref[0, :, 0, :].astype(jnp.float32)      # (C, Dh)
        cc = q.shape[0]
        for t in range(pb):
            tok = (pj * pb + t) * page_size + jax.lax.broadcasted_iota(
                jnp.int32, (cc, page_size), 1)
            row = jax.lax.broadcasted_iota(jnp.int32, (cc, page_size), 0)
            ok = (tok <= start + row) & (row < nv)     # causal + live lane
            _online_softmax_page_fold(
                q, k_refs[t], v_refs[t], ok, m_scr, l_scr, acc_scr,
                k_scale=ks_refs[t][0, :] if quantized else None,
                v_scale=vs_refs[t][0, :] if quantized else None)

    # ragged skip: blocks wholly past the chunk's live extent do nothing
    pl.when((nv > 0) & (pj * pb * page_size < start + nv))(_body)

    @pl.when(pj == npg - 1)
    def _finish():
        denom = l_scr[...][:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        alive = m_scr[...][:, :1] > NEG_INF / 2
        o_ref[0, :, 0, :] = jnp.where(
            alive, acc_scr[...] / denom, 0.0).astype(o_ref.dtype)


def _paged_prefill_pallas(q, k_pages, v_pages, block_tables, chunk_starts,
                          n_valid, scale, interpret, pages_per_block=1,
                          k_scales=None, v_scales=None):
    """``k_scales``/``v_scales`` given = the dequant-attend variant
    (same convention as :func:`_paged_decode_pallas`)."""
    if pltpu is None:  # pragma: no cover
        raise RuntimeError("Pallas TPU backend unavailable; use impl='lax'")
    quantized = k_scales is not None
    s_slots, c, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    pb = max(1, min(int(pages_per_block), mp))
    qs = (q * jnp.asarray(scale, q.dtype))
    k_specs, v_specs = _paged_kv_specs(ps, dh, mp, pb)
    sc_specs, sc_args = [], []
    if quantized:
        ks_specs, vs_specs = _paged_scale_specs(ps, mp, pb)
        sc_specs = [*ks_specs, *vs_specs]
        sc_args = [*([k_scales] * pb), *([v_scales] * pb)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # block_tables, chunk_starts, n_valid
        grid=(s_slots, h, pl.cdiv(mp, pb)),
        in_specs=[
            pl.BlockSpec((1, c, 1, dh),
                         lambda s, hh, j, bt, st, nv: (s, 0, hh, 0)),
            *k_specs,
            *v_specs,
            *sc_specs,
        ],
        out_specs=pl.BlockSpec((1, c, 1, dh),
                               lambda s, hh, j, bt, st, nv: (s, 0, hh, 0)),
        scratch_shapes=[
            pltpu.VMEM((c, 128), jnp.float32),
            pltpu.VMEM((c, 128), jnp.float32),
            pltpu.VMEM((c, dh), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_prefill_kernel, page_size=ps,
                               pages_per_block=pb, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, c, h, dh), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), chunk_starts.astype(jnp.int32),
      n_valid.astype(jnp.int32), qs, *([k_pages] * pb), *([v_pages] * pb),
      *sc_args)
    return out


# ---------------------------------------------------------------------------
# int8 dequant-attend prefill
# ---------------------------------------------------------------------------

def _paged_prefill_int8_lax(q, k_pages, v_pages, k_scales, v_scales,
                            block_tables, chunk_starts, n_valid, scale):
    """Lax fallback of the dequant-attend prefill kernel (the int8 twin
    of :func:`_paged_prefill_lax`; same scale-after-dot order as the
    Pallas body, int8 pools barriered against constant folding)."""
    from paddle_tpu import slim
    k_pages = slim.int8_resident(k_pages)
    v_pages = slim.int8_resident(v_pages)
    s_slots, c, h, dh = q.shape
    mp = block_tables.shape[1]
    ps = k_pages.shape[1]
    kg = k_pages[block_tables]                  # (S, mp, ps, H, Dh) int8
    vg = v_pages[block_tables]
    ksg = k_scales[block_tables]                # (S, mp, ps) f32
    vsg = v_scales[block_tables]
    scores = jnp.einsum("schd,smthd->shcmt", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) * scale
    scores = scores * ksg[:, None, None]        # dequant fused post-dot
    scores = scores.reshape(s_slots, h, c, mp * ps)
    tok = jnp.arange(mp * ps, dtype=jnp.int32)
    pos = chunk_starts[:, None] + jnp.arange(c, dtype=jnp.int32)  # (S, C)
    causal = tok[None, None, None, :] <= pos[:, None, :, None]
    row_ok = (jnp.arange(c) < n_valid[:, None])[:, None, :, None]
    scores = jnp.where(causal & row_ok, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    alive = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0).reshape(s_slots, h, c, mp, ps)
    p = p * vsg[:, None, None]                  # dequant fused pre-PV
    out = jnp.einsum("shcmt,smthd->schd", p, vg.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_prefill_int8_pallas(q, k_pages, v_pages, k_scales, v_scales,
                               block_tables, chunk_starts, n_valid,
                               scale, interpret, pages_per_block=1):
    """The dequant-attend prefill entry: the SAME kernel body as the fp
    path with ``quantized=True`` (see :func:`_paged_decode_int8_pallas`
    for the convention)."""
    return _paged_prefill_pallas(q, k_pages, v_pages, block_tables,
                                 chunk_starts, n_valid, scale, interpret,
                                 pages_per_block=pages_per_block,
                                 k_scales=k_scales, v_scales=v_scales)


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

def ragged_paged_decode_attention(q, k_pages, v_pages, block_tables,
                                  lengths, *, scale: Optional[float] = None,
                                  impl: str = "auto"):
    """One decode step of attention for every slot at once.

    ``q`` (S, H, Dh); ``k_pages``/``v_pages`` (P, page_size, H, Dh);
    ``block_tables`` (S, max_pages) int32; ``lengths`` (S,) int32 valid
    tokens per slot. Returns (S, H, Dh). ``impl``: "auto" (pallas on
    TPU, lax elsewhere), "lax", "pallas", "pallas_interpret".
    """
    from paddle_tpu import kernels
    return kernels.dispatch("ragged_paged_decode", q, k_pages, v_pages,
                            block_tables, lengths, impl=impl, scale=scale)


def ragged_paged_prefill_attention(q, k_pages, v_pages, block_tables,
                                   chunk_starts, n_valid, *,
                                   scale: Optional[float] = None,
                                   impl: str = "auto"):
    """One batched chunked-prefill step of attention for every slot.

    ``q`` (S, C, H, Dh) — a chunk of C query tokens per slot, the first
    ``n_valid[s]`` real (rest padding), at absolute positions
    ``chunk_starts[s] + c``; keys/values are read from each slot's pages
    via ``block_tables`` (S, max_pages). Each live query attends
    causally to all cache positions ``<= chunk_starts[s] + c`` (earlier
    chunks, shared prefix pages, and this chunk's causal prefix — whose
    K/V the caller has already written). Padding lanes and inactive
    slots (``n_valid == 0``) emit exact zeros. Returns (S, C, H, Dh).
    ``impl``: "auto" (pallas on TPU, lax elsewhere), "lax", "pallas",
    "pallas_interpret".
    """
    from paddle_tpu import kernels
    return kernels.dispatch("ragged_paged_prefill", q, k_pages, v_pages,
                            block_tables, chunk_starts, n_valid,
                            impl=impl, scale=scale)


def ragged_paged_decode_int8_attention(q, k_pages, v_pages, k_scales,
                                       v_scales, block_tables, lengths, *,
                                       scale: Optional[float] = None,
                                       impl: str = "auto"):
    """Dequant-attend decode over an INT8 page pool (ISSUE 13).

    Same contract as :func:`ragged_paged_decode_attention` with
    ``k_pages``/``v_pages`` int8 and per-token-row fp32
    ``k_scales``/``v_scales`` (P, page_size) — dequantization
    (``q_int * scale``) is fused into the QK and PV products inside the
    online-softmax page fold, so HBM moves int8 pages, never a
    materialized fp copy. Returns (S, H, Dh) in ``q.dtype``.
    """
    from paddle_tpu import kernels
    return kernels.dispatch("ragged_paged_decode_int8", q, k_pages,
                            v_pages, k_scales, v_scales, block_tables,
                            lengths, impl=impl, scale=scale)


def ragged_paged_prefill_int8_attention(q, k_pages, v_pages, k_scales,
                                        v_scales, block_tables,
                                        chunk_starts, n_valid, *,
                                        scale: Optional[float] = None,
                                        impl: str = "auto"):
    """Dequant-attend batched chunked prefill over an INT8 page pool —
    the int8 twin of :func:`ragged_paged_prefill_attention` (and the
    fixed-shape verify step speculative decoding rides on). Returns
    (S, C, H, Dh) in ``q.dtype``.
    """
    from paddle_tpu import kernels
    return kernels.dispatch("ragged_paged_prefill_int8", q, k_pages,
                            v_pages, k_scales, v_scales, block_tables,
                            chunk_starts, n_valid, impl=impl, scale=scale)


def ragged_paged_decode_tp_attention(q, k_pages, v_pages, block_tables,
                                     lengths, *,
                                     scale: Optional[float] = None,
                                     impl: str = "auto", mesh=None):
    """Tensor-parallel ragged paged decode (ISSUE 15): same contract as
    :func:`ragged_paged_decode_attention` with ``q`` (S, H, Dh) and the
    page pool sharded ``H/tp`` over the mesh's "tp" axis, block tables
    and lengths replicated. Runs the single-device kernel per head
    shard under ``shard_map`` — heads are independent, so the sharded
    output is BIT-identical to the tp=1 kernel on the same pages; the
    attention-output collective lives at the caller's row-sharded
    output projection, not here. Returns (S, H, Dh) sharded like
    ``q``. Must run under a mesh (``mesh_context`` or ``mesh=``)."""
    from paddle_tpu import kernels
    return kernels.dispatch("ragged_paged_decode_tp", q, k_pages,
                            v_pages, block_tables, lengths, impl=impl,
                            scale=scale, mesh=mesh)


def ragged_paged_prefill_tp_attention(q, k_pages, v_pages, block_tables,
                                      chunk_starts, n_valid, *,
                                      scale: Optional[float] = None,
                                      impl: str = "auto", mesh=None):
    """Tensor-parallel batched chunked prefill — the tp twin of
    :func:`ragged_paged_prefill_attention` (``q`` (S, C, H, Dh) and the
    pages sharded ``H/tp``, chunk geometry replicated). Same
    head-independence argument as the decode variant: bit-identical to
    tp=1 per head shard, zero collectives inside the kernel."""
    from paddle_tpu import kernels
    return kernels.dispatch("ragged_paged_prefill_tp", q, k_pages,
                            v_pages, block_tables, chunk_starts, n_valid,
                            impl=impl, scale=scale, mesh=mesh)


def ragged_paged_decode_int8_tp_attention(q, k_pages, v_pages, k_scales,
                                          v_scales, block_tables,
                                          lengths, *,
                                          scale: Optional[float] = None,
                                          impl: str = "auto", mesh=None):
    """Tensor-parallel dequant-attend decode: int8 pages sharded
    ``H/tp``, per-token-row fp32 scales REPLICATED (a token's scale is
    computed over all heads — see ``quantize_kv``'s ``psum_axis`` — so
    every shard dequantizes its head slice with the same row)."""
    from paddle_tpu import kernels
    return kernels.dispatch("ragged_paged_decode_int8_tp", q, k_pages,
                            v_pages, k_scales, v_scales, block_tables,
                            lengths, impl=impl, scale=scale, mesh=mesh)


def ragged_paged_prefill_int8_tp_attention(q, k_pages, v_pages, k_scales,
                                           v_scales, block_tables,
                                           chunk_starts, n_valid, *,
                                           scale: Optional[float] = None,
                                           impl: str = "auto",
                                           mesh=None):
    """Tensor-parallel dequant-attend batched chunked prefill (the int8
    twin of :func:`ragged_paged_prefill_tp_attention`)."""
    from paddle_tpu import kernels
    return kernels.dispatch("ragged_paged_prefill_int8_tp", q, k_pages,
                            v_pages, k_scales, v_scales, block_tables,
                            chunk_starts, n_valid, impl=impl, scale=scale,
                            mesh=mesh)


def paged_prefill_attention(q, k_pages, v_pages, block_table_row,
                            positions, *, scale: Optional[float] = None):
    """Chunked-prefill attention for ONE slot.

    ``q`` (C, H, Dh) — a chunk of query tokens at absolute ``positions``
    (C,) int32; keys/values are read from the slot's pages via
    ``block_table_row`` (max_pages,). Each query attends causally to all
    cache positions ``<= positions[c]`` (earlier chunks + the causal
    prefix of this chunk, whose K/V the caller has already written).
    Padded queries (positions past the chunk's valid length) produce
    garbage rows the caller discards. XLA-composed: prefill is a few
    calls per request, the per-step hot path is the decode kernel.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    mp = block_table_row.shape[0]
    ps = k_pages.shape[1]
    h, dh = q.shape[1], q.shape[2]
    k = k_pages[block_table_row].reshape(mp * ps, h, dh)
    v = v_pages[block_table_row].reshape(mp * ps, h, dh)
    scores = jnp.einsum("chd,thd->hct", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    tok = jnp.arange(mp * ps, dtype=jnp.int32)
    causal = tok[None, None, :] <= positions[None, :, None]
    scores = jnp.where(causal, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    alive = jnp.max(scores, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0)
    out = jnp.einsum("hct,thd->chd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# kernel-registry entries (paddle_tpu.kernels)
# ---------------------------------------------------------------------------

def _decode_kernel_pallas(q, k_pages, v_pages, block_tables, lengths, *,
                          block_sizes, interpret, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_decode_pallas(
        q, k_pages, v_pages, block_tables, lengths, scale, interpret,
        pages_per_block=block_sizes.get("pages_per_block", 1))


def _decode_kernel_lax(q, k_pages, v_pages, block_tables, lengths, *,
                       scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_decode_lax(q, k_pages, v_pages, block_tables, lengths,
                             scale)


def _decode_kernel_reference(q, k_pages, v_pages, block_tables, lengths,
                             *, scale=None):
    """NumPy per-slot dense attention — independent of both impls."""
    import numpy as np
    s_slots, h, dh = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(dh)
    mp, ps = block_tables.shape[1], k_pages.shape[1]
    qn = np.asarray(q, np.float32)
    kp = np.asarray(k_pages, np.float32)
    vp = np.asarray(v_pages, np.float32)
    bt = np.asarray(block_tables)
    ln = np.asarray(lengths)
    outs = np.zeros((s_slots, h, dh), np.float32)
    for sl in range(s_slots):
        n = int(ln[sl])
        if n == 0:
            continue
        k = kp[bt[sl]].reshape(mp * ps, h, dh)[:n]
        v = vp[bt[sl]].reshape(mp * ps, h, dh)[:n]
        s = np.einsum("hd,thd->ht", qn[sl], k) * scale
        s = s - s.max(-1, keepdims=True)
        p = np.exp(s)
        p = p / p.sum(-1, keepdims=True)
        outs[sl] = np.einsum("ht,thd->hd", p, v)
    return jnp.asarray(outs).astype(q.dtype)


def _make_paged_sample(seed, *, chunked):
    import numpy as np
    s_slots, h, dh, ps, mp = (
        (4, 2, 16, 8, 3), (6, 4, 32, 16, 4), (8, 4, 64, 16, 6))[seed % 3]
    c = ps  # prefill chunk = one page of queries
    num_pages = s_slots * mp + 1
    rng = np.random.default_rng(seed)
    k_pages = jnp.asarray(
        rng.standard_normal((num_pages, ps, h, dh)), jnp.float32)
    v_pages = jnp.asarray(
        rng.standard_normal((num_pages, ps, h, dh)), jnp.float32)
    perm = rng.permutation(num_pages - 1)[:s_slots * mp] + 1
    block_tables = jnp.asarray(perm.reshape(s_slots, mp), jnp.int32)
    if not chunked:
        q = jnp.asarray(rng.standard_normal((s_slots, h, dh)),
                        jnp.float32)
        lengths = jnp.asarray(
            rng.integers(0, mp * ps + 1, s_slots), jnp.int32)
        return (q, k_pages, v_pages, block_tables, lengths), {}
    q = jnp.asarray(rng.standard_normal((s_slots, c, h, dh)), jnp.float32)
    starts = jnp.asarray(
        rng.integers(0, (mp - 1) * ps, s_slots), jnp.int32)
    n_valid = jnp.asarray(rng.integers(0, c + 1, s_slots), jnp.int32)
    return (q, k_pages, v_pages, block_tables, starts, n_valid), {}


def _paged_tune_signature(args, kwargs):
    q, k_pages, _v, bt = args[0], args[1], args[2], args[3]
    sig = [("s", q.shape[0]), ("h", k_pages.shape[2]),
           ("d", q.shape[-1]), ("ps", k_pages.shape[1]),
           ("mp", bt.shape[1])]
    if q.ndim == 4:                      # prefill: chunk width matters
        sig.insert(1, ("c", q.shape[1]))
    return tuple(sig)


def _paged_vmem_estimate(args, kwargs, blocks):
    q, k_pages = args[0], args[1]
    ps, dh = k_pages.shape[1], k_pages.shape[-1]
    c = q.shape[1] if q.ndim == 4 else 1
    pb = blocks.get("pages_per_block", 1)
    # fp32 working set: pb (k, v) page pairs + q/acc + m/l lane scratch
    return 4 * (2 * pb * ps * dh + 2 * c * dh + 2 * c * 128
                + 2 * c * ps)


def _decode_donation_probe():
    (q, k_pages, v_pages, block_tables, lengths), _ = \
        _make_paged_sample(0, chunked=False)

    def step(kp, vp, q, bt, lens):
        # the engine's real pattern: write this step's token K/V into
        # the pages, attend THROUGH THE PALLAS BODY (interpret lowering
        # — the structure XLA aliases, incl. the pages-passed-
        # pages_per_block-times operand shape), hand the pages back
        kp = kp.at[1, 0].set(q[0])
        vp = vp.at[1, 0].set(q[0])
        out = _decode_kernel_pallas(
            q, kp, vp, bt, lens,
            block_sizes={"pages_per_block": 4}, interpret=True)
        return out, kp, vp

    args = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for a in (k_pages, v_pages, q, block_tables, lengths))
    return step, args, (0, 1)


def _prefill_kernel_pallas(q, k_pages, v_pages, block_tables,
                           chunk_starts, n_valid, *, block_sizes,
                           interpret, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_prefill_pallas(
        q, k_pages, v_pages, block_tables, chunk_starts, n_valid, scale,
        interpret, pages_per_block=block_sizes.get("pages_per_block", 1))


def _prefill_kernel_lax(q, k_pages, v_pages, block_tables, chunk_starts,
                        n_valid, *, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_prefill_lax(q, k_pages, v_pages, block_tables,
                              chunk_starts, n_valid, scale)


def _prefill_kernel_reference(q, k_pages, v_pages, block_tables,
                              chunk_starts, n_valid, *, scale=None):
    """NumPy per-slot, per-row causal attention over the slot's pages."""
    import numpy as np
    s_slots, c, h, dh = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(dh)
    mp, ps = block_tables.shape[1], k_pages.shape[1]
    qn = np.asarray(q, np.float32)
    kp = np.asarray(k_pages, np.float32)
    vp = np.asarray(v_pages, np.float32)
    bt = np.asarray(block_tables)
    st = np.asarray(chunk_starts)
    nv = np.asarray(n_valid)
    outs = np.zeros((s_slots, c, h, dh), np.float32)
    for sl in range(s_slots):
        k = kp[bt[sl]].reshape(mp * ps, h, dh)
        v = vp[bt[sl]].reshape(mp * ps, h, dh)
        for r in range(int(nv[sl])):
            limit = int(st[sl]) + r + 1          # causal horizon
            s = np.einsum("hd,thd->ht", qn[sl, r], k[:limit]) * scale
            s = s - s.max(-1, keepdims=True)
            p = np.exp(s)
            p = p / p.sum(-1, keepdims=True)
            outs[sl, r] = np.einsum("ht,thd->hd", p, v[:limit])
    return jnp.asarray(outs).astype(q.dtype)


def _prefill_donation_probe():
    (q, k_pages, v_pages, block_tables, starts, n_valid), _ = \
        _make_paged_sample(0, chunked=True)

    def step(kp, vp, q, bt, st, nv):
        kp = kp.at[1, 0].set(q[0, 0])
        vp = vp.at[1, 0].set(q[0, 0])
        out = _prefill_kernel_pallas(
            q, kp, vp, bt, st, nv,
            block_sizes={"pages_per_block": 4}, interpret=True)
        return out, kp, vp

    args = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for a in (k_pages, v_pages, q, block_tables, starts,
                           n_valid))
    return step, args, (0, 1)


# -- int8 dequant-attend registry plumbing ----------------------------------

def _decode_int8_kernel_pallas(q, k_pages, v_pages, k_scales, v_scales,
                               block_tables, lengths, *, block_sizes,
                               interpret, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_decode_int8_pallas(
        q, k_pages, v_pages, k_scales, v_scales, block_tables, lengths,
        scale, interpret,
        pages_per_block=block_sizes.get("pages_per_block", 1))


def _decode_int8_kernel_lax(q, k_pages, v_pages, k_scales, v_scales,
                            block_tables, lengths, *, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_decode_int8_lax(q, k_pages, v_pages, k_scales, v_scales,
                                  block_tables, lengths, scale)


def _dequant_pages_np(k_pages, v_pages, k_scales, v_scales):
    """Host-side dequant for the dense references — independent of the
    fused in-kernel path (the parity battery's whole point)."""
    import numpy as np
    kf = np.asarray(k_pages, np.float32) \
        * np.asarray(k_scales, np.float32)[:, :, None, None]
    vf = np.asarray(v_pages, np.float32) \
        * np.asarray(v_scales, np.float32)[:, :, None, None]
    return jnp.asarray(kf), jnp.asarray(vf)


def _decode_int8_kernel_reference(q, k_pages, v_pages, k_scales, v_scales,
                                  block_tables, lengths, *, scale=None):
    kf, vf = _dequant_pages_np(k_pages, v_pages, k_scales, v_scales)
    return _decode_kernel_reference(q, kf, vf, block_tables, lengths,
                                    scale=scale)


def _prefill_int8_kernel_pallas(q, k_pages, v_pages, k_scales, v_scales,
                                block_tables, chunk_starts, n_valid, *,
                                block_sizes, interpret, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_prefill_int8_pallas(
        q, k_pages, v_pages, k_scales, v_scales, block_tables,
        chunk_starts, n_valid, scale, interpret,
        pages_per_block=block_sizes.get("pages_per_block", 1))


def _prefill_int8_kernel_lax(q, k_pages, v_pages, k_scales, v_scales,
                             block_tables, chunk_starts, n_valid, *,
                             scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_prefill_int8_lax(q, k_pages, v_pages, k_scales,
                                   v_scales, block_tables, chunk_starts,
                                   n_valid, scale)


def _prefill_int8_kernel_reference(q, k_pages, v_pages, k_scales,
                                   v_scales, block_tables, chunk_starts,
                                   n_valid, *, scale=None):
    kf, vf = _dequant_pages_np(k_pages, v_pages, k_scales, v_scales)
    return _prefill_kernel_reference(q, kf, vf, block_tables,
                                     chunk_starts, n_valid, scale=scale)


def _make_paged_int8_sample(seed, *, chunked):
    """The fp sample's pages quantized per token row — THROUGH
    :func:`paged_cache.quantize_kv` itself, so the registry's parity
    and tuning samples can never drift from the convention the engine
    actually stores."""
    from paddle_tpu.serving.paged_cache import quantize_kv
    args, kwargs = _make_paged_sample(seed, chunked=chunked)
    q, k_pages, v_pages = args[0], args[1], args[2]
    rest = args[3:]
    kq, ks = quantize_kv(k_pages, (2, 3))          # scales (P, ps)
    vq, vs = quantize_kv(v_pages, (2, 3))
    return (q, kq, vq, ks, vs) + rest, kwargs


def _paged_int8_tune_signature(args, kwargs):
    q, k_pages, bt = args[0], args[1], args[5]
    sig = [("s", q.shape[0]), ("h", k_pages.shape[2]),
           ("d", q.shape[-1]), ("ps", k_pages.shape[1]),
           ("mp", bt.shape[1])]
    if q.ndim == 4:                      # prefill: chunk width matters
        sig.insert(1, ("c", q.shape[1]))
    return tuple(sig)


def _paged_int8_vmem_estimate(args, kwargs, blocks):
    q, k_pages = args[0], args[1]
    ps, dh = k_pages.shape[1], k_pages.shape[-1]
    c = q.shape[1] if q.ndim == 4 else 1
    pb = blocks.get("pages_per_block", 1)
    # int8 working set: pb (k, v) page pairs at 1 byte + their fp32
    # scale rows + fp32 q/acc + m/l lane scratch + the score block
    return (2 * pb * ps * dh + 4 * (2 * pb * ps + 2 * c * dh
                                    + 2 * c * 128 + 2 * c * ps))


def _decode_int8_donation_probe():
    (q, k_pages, v_pages, k_scales, v_scales, block_tables, lengths), _ \
        = _make_paged_int8_sample(0, chunked=False)

    def step(kp, vp, ks, vs, q, bt, lens):
        # the engine's real pattern: quantize this step's token K/V into
        # the int8 pages + scale rows, attend THROUGH THE PALLAS BODY,
        # hand all four buffers back (pages AND scales must alias)
        from paddle_tpu.serving.paged_cache import quantize_kv
        kq, ksc = quantize_kv(q[:1], (1, 2))
        kp = kp.at[1, 0].set(kq[0])
        vp = vp.at[1, 0].set(kq[0])
        ks = ks.at[1, 0].set(ksc[0])
        vs = vs.at[1, 0].set(ksc[0])
        out = _decode_int8_kernel_pallas(
            q, kp, vp, ks, vs, bt, lens,
            block_sizes={"pages_per_block": 4}, interpret=True)
        return out, kp, vp, ks, vs

    args = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for a in (k_pages, v_pages, k_scales, v_scales, q,
                           block_tables, lengths))
    return step, args, (0, 1, 2, 3)


def _prefill_int8_donation_probe():
    (q, k_pages, v_pages, k_scales, v_scales, block_tables, starts,
     n_valid), _ = _make_paged_int8_sample(0, chunked=True)

    def step(kp, vp, ks, vs, q, bt, st, nv):
        from paddle_tpu.serving.paged_cache import quantize_kv
        kq, ksc = quantize_kv(q[:1, 0], (1, 2))
        kp = kp.at[1, 0].set(kq[0])
        vp = vp.at[1, 0].set(kq[0])
        ks = ks.at[1, 0].set(ksc[0])
        vs = vs.at[1, 0].set(ksc[0])
        out = _prefill_int8_kernel_pallas(
            q, kp, vp, ks, vs, bt, st, nv,
            block_sizes={"pages_per_block": 4}, interpret=True)
        return out, kp, vp, ks, vs

    args = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for a in (k_pages, v_pages, k_scales, v_scales, q,
                           block_tables, starts, n_valid))
    return step, args, (0, 1, 2, 3)


def _register_paged_kernels():
    from paddle_tpu import kernels
    pb_candidates = {"pages_per_block": (1, 2, 4)}
    kernels.register(kernels.KernelSpec(
        name="ragged_paged_decode",
        contract=kernels.KernelContract(
            version=1,
            arg_layouts={"q": "(S,H,Dh)", "k_pages": "(P,ps,H,Dh)",
                         "v_pages": "(P,ps,H,Dh)",
                         "block_tables": "(S,mp) i32",
                         "lengths": "(S,) i32"},
            out_layout="(S,H,Dh)",
            donatable=("k_pages", "v_pages"),
            grid="(S, H, cdiv(mp,pages_per_block)) block-table scalar "
                 "prefetch, dead-page skip",
            block_candidates=pb_candidates,
            atol=2e-5, rtol=2e-5),
        pallas_fn=_decode_kernel_pallas,
        lax_fn=_decode_kernel_lax,
        reference_fn=_decode_kernel_reference,
        sample_inputs=lambda seed: _make_paged_sample(seed, chunked=False),
        pallas_sites=(
            "paddle_tpu.serving.decode_attention:_paged_decode_pallas",),
        tune_signature=_paged_tune_signature,
        vmem_estimate=_paged_vmem_estimate,
        donation_probe=_decode_donation_probe,
        # per-shard (H/tp) buckets the tp wrappers dispatch this kernel
        # at — lambdas so the late-defined helper resolves at call time
        tune_sample_variants=(
            lambda s: _tp_local_sample(s, tp=2, chunked=False),
            lambda s: _tp_local_sample(s, tp=4, chunked=False))))
    kernels.register(kernels.KernelSpec(
        name="ragged_paged_prefill",
        contract=kernels.KernelContract(
            version=1,
            arg_layouts={"q": "(S,C,H,Dh)", "k_pages": "(P,ps,H,Dh)",
                         "v_pages": "(P,ps,H,Dh)",
                         "block_tables": "(S,mp) i32",
                         "chunk_starts": "(S,) i32",
                         "n_valid": "(S,) i32"},
            out_layout="(S,C,H,Dh)",
            donatable=("k_pages", "v_pages"),
            grid="(S, H, cdiv(mp,pages_per_block)) block-table scalar "
                 "prefetch, causal + live-lane mask",
            block_candidates=pb_candidates,
            atol=2e-5, rtol=2e-5),
        pallas_fn=_prefill_kernel_pallas,
        lax_fn=_prefill_kernel_lax,
        reference_fn=_prefill_kernel_reference,
        sample_inputs=lambda seed: _make_paged_sample(seed, chunked=True),
        pallas_sites=(
            "paddle_tpu.serving.decode_attention:_paged_prefill_pallas",),
        tune_signature=_paged_tune_signature,
        vmem_estimate=_paged_vmem_estimate,
        donation_probe=_prefill_donation_probe,
        tune_sample_variants=(
            lambda s: _tp_local_sample(s, tp=2, chunked=True),
            lambda s: _tp_local_sample(s, tp=4, chunked=True))))
    kernels.register(kernels.KernelSpec(
        name="ragged_paged_decode_int8",
        contract=kernels.KernelContract(
            version=1,
            arg_layouts={"q": "(S,H,Dh)", "k_pages": "(P,ps,H,Dh) i8",
                         "v_pages": "(P,ps,H,Dh) i8",
                         "k_scales": "(P,ps) f32",
                         "v_scales": "(P,ps) f32",
                         "block_tables": "(S,mp) i32",
                         "lengths": "(S,) i32"},
            out_layout="(S,H,Dh)",
            donatable=("k_pages", "v_pages", "k_scales", "v_scales"),
            grid="(S, H, cdiv(mp,pages_per_block)) block-table scalar "
                 "prefetch, dead-page skip, scales fused into QK/PV",
            block_candidates=pb_candidates,
            atol=5e-5, rtol=5e-5),
        pallas_fn=_decode_int8_kernel_pallas,
        lax_fn=_decode_int8_kernel_lax,
        reference_fn=_decode_int8_kernel_reference,
        sample_inputs=lambda seed: _make_paged_int8_sample(seed,
                                                           chunked=False),
        # the int8 variant runs THROUGH the fp kernel's pallas_call site
        # (one body, quantized=True) — no site of its own
        pallas_sites=(
            "paddle_tpu.serving.decode_attention:_paged_decode_pallas",),
        tune_signature=_paged_int8_tune_signature,
        vmem_estimate=_paged_int8_vmem_estimate,
        donation_probe=_decode_int8_donation_probe,
        tune_sample_variants=(
            lambda s: _tp_local_sample(s, tp=2, chunked=False,
                                       quantized=True),
            lambda s: _tp_local_sample(s, tp=4, chunked=False,
                                       quantized=True))))
    kernels.register(kernels.KernelSpec(
        name="ragged_paged_prefill_int8",
        contract=kernels.KernelContract(
            version=1,
            arg_layouts={"q": "(S,C,H,Dh)", "k_pages": "(P,ps,H,Dh) i8",
                         "v_pages": "(P,ps,H,Dh) i8",
                         "k_scales": "(P,ps) f32",
                         "v_scales": "(P,ps) f32",
                         "block_tables": "(S,mp) i32",
                         "chunk_starts": "(S,) i32",
                         "n_valid": "(S,) i32"},
            out_layout="(S,C,H,Dh)",
            donatable=("k_pages", "v_pages", "k_scales", "v_scales"),
            grid="(S, H, cdiv(mp,pages_per_block)) block-table scalar "
                 "prefetch, causal + live-lane mask, scales fused into "
                 "QK/PV",
            block_candidates=pb_candidates,
            atol=5e-5, rtol=5e-5),
        pallas_fn=_prefill_int8_kernel_pallas,
        lax_fn=_prefill_int8_kernel_lax,
        reference_fn=_prefill_int8_kernel_reference,
        sample_inputs=lambda seed: _make_paged_int8_sample(seed,
                                                           chunked=True),
        pallas_sites=(
            "paddle_tpu.serving.decode_attention:_paged_prefill_pallas",),
        tune_signature=_paged_int8_tune_signature,
        vmem_estimate=_paged_int8_vmem_estimate,
        donation_probe=_prefill_int8_donation_probe,
        tune_sample_variants=(
            lambda s: _tp_local_sample(s, tp=2, chunked=True,
                                       quantized=True),
            lambda s: _tp_local_sample(s, tp=4, chunked=True,
                                       quantized=True))))


_register_paged_kernels()


# ---------------------------------------------------------------------------
# tensor-parallel wrappers (ISSUE 15): heads sharded H/tp over "tp"
# ---------------------------------------------------------------------------

from jax.sharding import PartitionSpec as _P  # noqa: E402

#: the canonical tp specs: pages/queries sharded on the HEAD axis,
#: block-table geometry (and int8 scale rows) replicated
_TP_KV_SPEC = _P(None, None, "tp", None)          # (P, ps, H, Dh)
_TP_Q_DECODE = _P(None, "tp", None)               # (S, H, Dh)
_TP_Q_PREFILL = _P(None, None, "tp", None)        # (S, C, H, Dh)


def _tp_mesh(mesh):
    from paddle_tpu.core import mesh as mesh_lib
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None:
        raise ValueError("tp paged attention requires a mesh "
                         "(use mesh_context or pass mesh=)")
    return mesh


def _tp_run(inner_name, args, specs, *, inner_impl, block_sizes,
            scale, mesh):
    """Run the single-device kernel ``inner_name`` per head shard under
    shard_map. The inner dispatch resolves its block sizes from the
    shared autotuner at the LOCAL (H/tp) shapes — trace-time host code,
    so the tp wrappers stay recompile-safe; ``--seed`` keeps the
    committed manifest covering those buckets (tune_sample_variants)."""
    mesh = _tp_mesh(mesh)
    from paddle_tpu.core.compat import shard_map

    def body(*local):
        from paddle_tpu import kernels
        return kernels.dispatch(inner_name, *local, impl=inner_impl,
                                block_sizes=block_sizes or None,
                                scale=scale)

    out_spec = specs[0]       # output sharded like q
    return shard_map(body, mesh=mesh, in_specs=specs,
                     out_specs=out_spec, check_vma=False)(*args)


def _make_tp_fns(inner_name, specs):
    """(pallas_fn, lax_fn) pair for one tp wrapper spec."""
    def pallas_fn(*args, block_sizes, interpret, scale=None, mesh=None):
        if scale is None:
            scale = 1.0 / math.sqrt(args[0].shape[-1])
        impl = "pallas_interpret" if interpret else "pallas"
        return _tp_run(inner_name, args, specs, inner_impl=impl,
                       block_sizes=block_sizes, scale=scale, mesh=mesh)

    def lax_fn(*args, scale=None, mesh=None):
        if scale is None:
            scale = 1.0 / math.sqrt(args[0].shape[-1])
        return _tp_run(inner_name, args, specs, inner_impl="lax",
                       block_sizes=None, scale=scale, mesh=mesh)

    return pallas_fn, lax_fn


def _tp_parity_mesh():
    """Largest dp×(tp=2) mesh covering every device — tp=2 divides all
    sample head counts; None when the box cannot host one."""
    n = len(jax.devices())
    if n < 2 or n % 2:
        return None
    from paddle_tpu.core.mesh import MeshConfig, make_mesh
    return make_mesh(MeshConfig(dp=n // 2, tp=2))


def _make_tp_parity_fn(name, inner_name, sample_fn, reference_fn,
                       quantized=False):
    """Mesh-orchestrated battery for one tp wrapper: lax and
    pallas-interpret through the sharded dispatch vs the dense
    reference, PLUS the bit-equality pin — the tp lax path must equal
    the single-device lax kernel exactly (heads are independent). The
    int8 variants pin to 1e-6 instead: XLA's codegen for the fused
    cast-dequant dot reassociates differently at different head counts,
    so the per-shard dequant einsum can drift a last ulp from the
    full-head one (the engine-level acceptance — greedy tokens
    identical to tp=1 — is pinned exactly in tests/test_serving_tp.py
    and the serving_tp bench)."""
    def parity(seed):
        import numpy as np
        mesh = _tp_parity_mesh()
        if mesh is None:
            return {}
        args, kwargs = sample_fn(seed)
        from paddle_tpu import kernels
        contract = kernels.get(name).contract
        ref = np.asarray(reference_fn(*args, **kwargs), np.float32)
        from paddle_tpu.core.mesh import mesh_context
        errs = {}
        with mesh_context(mesh):
            for impl in ("lax", "pallas_interpret"):
                out = np.asarray(jax.jit(
                    lambda *a, _i=impl: kernels.dispatch(
                        name, *a, impl=_i, mesh=mesh, **kwargs))(*args),
                    np.float32)
                np.testing.assert_allclose(
                    out, ref, atol=contract.atol, rtol=contract.rtol,
                    err_msg=f"{name}[{impl}] diverged from the dense "
                            "reference")
                errs[impl] = float(np.max(np.abs(out - ref)))
            tp_lax = np.asarray(jax.jit(
                lambda *a: kernels.dispatch(
                    name, *a, impl="lax", mesh=mesh, **kwargs))(*args))
            tp1 = np.asarray(kernels.dispatch(inner_name, *args,
                                              impl="lax", **kwargs))
            if quantized:
                np.testing.assert_allclose(
                    tp_lax, tp1, rtol=1e-6, atol=1e-6,
                    err_msg=f"{name} tp output drifted from the "
                            f"single-device {inner_name} kernel")
            else:
                np.testing.assert_array_equal(
                    tp_lax, tp1,
                    err_msg=f"{name} tp output is not bit-identical to "
                            f"the single-device {inner_name} kernel")
        return errs
    return parity


def _tp_probe_mesh():
    devs = jax.devices()
    if len(devs) < 2:
        return None
    from paddle_tpu.core.mesh import MeshConfig, make_mesh
    return make_mesh(MeshConfig(tp=2), devices=devs[:2])


def _tp_local_sample(seed, *, tp, chunked, quantized=False):
    """The fp/int8 sample with its head axis cut to ONE tp shard's
    slice — the per-shard shapes the tp wrappers dispatch the inner
    kernel at. ``--seed`` tunes these buckets so a tp mesh resolves
    from the committed manifest instead of a cold prior. None when this
    seed's head count is not divisible by ``tp``."""
    maker = _make_paged_int8_sample if quantized else _make_paged_sample
    args, kwargs = maker(seed, chunked=chunked)
    q, k_pages, v_pages = args[0], args[1], args[2]
    h = k_pages.shape[2]
    if h % tp:
        return None
    hl = h // tp
    q = q[:, :, :hl] if q.ndim == 4 else q[:, :hl]
    return (q, k_pages[:, :, :hl], v_pages[:, :, :hl]) + args[3:], kwargs


def _tp_donation_probe(*, chunked, quantized):
    """Engine-shaped donation probe for one tp wrapper: write this
    step's K/V into the PER-SHARD pages (quantized: int8 rows + the
    replicated scale rows, with the pmax-completed global scale), attend
    through the sharded kernel, then the row-sharded output projection
    with THE one attention-output psum — and hand every pool buffer
    back. Lowered by the kernel-contract lint: per-shard aliasing
    (``jax.buffer_donor`` under SPMD) and exactly the contract's
    ``("all_reduce",)`` collective kind. None when the box cannot host
    a tp=2 mesh."""
    mesh = _tp_probe_mesh()
    if mesh is None:
        return None
    from paddle_tpu.core.compat import shard_map
    if quantized:
        (q, kp, vp, ks, vs, *rest), _ = _make_paged_int8_sample(
            0, chunked=chunked)
    else:
        (q, kp, vp, *rest), _ = _make_paged_sample(0, chunked=chunked)
    h, dh = kp.shape[2], kp.shape[3]
    d_model = h * dh
    wo = jnp.zeros((h, dh, d_model), jnp.float32)
    inner = ("ragged_paged_prefill" if chunked else "ragged_paged_decode")
    inner += "_int8" if quantized else ""
    q_spec = _TP_Q_PREFILL if chunked else _TP_Q_DECODE
    geo_specs = tuple(_P() for _ in rest)

    if quantized:
        def local(kp, vp, ks, vs, q, wo, *geo):
            from paddle_tpu import kernels
            from paddle_tpu.serving.paged_cache import quantize_kv
            tok = q[:1, 0] if chunked else q[:1]
            kq, ksc = quantize_kv(tok, (1, 2), psum_axis="tp")
            kp = kp.at[1, 0].set(kq[0])
            vp = vp.at[1, 0].set(kq[0])
            ks = ks.at[1, 0].set(ksc[0])
            vs = vs.at[1, 0].set(ksc[0])
            att = kernels.dispatch(inner, q, kp, vp, ks, vs, *geo,
                                   impl="lax")
            part = (jnp.einsum("schk,hkd->scd", att, wo) if chunked
                    else jnp.einsum("shk,hkd->sd", att, wo))
            out = jax.lax.psum(part, "tp")
            return out, kp, vp, ks, vs

        fn = shard_map(
            local, mesh=mesh,
            in_specs=(_TP_KV_SPEC, _TP_KV_SPEC, _P(), _P(), q_spec,
                      _P("tp", None, None)) + geo_specs,
            out_specs=(_P(), _TP_KV_SPEC, _TP_KV_SPEC, _P(), _P()),
            check_vma=False)
        arrs = (kp, vp, ks, vs, q, wo) + tuple(rest)
        donate = (0, 1, 2, 3)
    else:
        def local(kp, vp, q, wo, *geo):
            from paddle_tpu import kernels
            tok = q[0, 0] if chunked else q[0]
            kp = kp.at[1, 0].set(tok)
            vp = vp.at[1, 0].set(tok)
            att = kernels.dispatch(inner, q, kp, vp, *geo, impl="lax")
            part = (jnp.einsum("schk,hkd->scd", att, wo) if chunked
                    else jnp.einsum("shk,hkd->sd", att, wo))
            out = jax.lax.psum(part, "tp")
            return out, kp, vp

        fn = shard_map(
            local, mesh=mesh,
            in_specs=(_TP_KV_SPEC, _TP_KV_SPEC, q_spec,
                      _P("tp", None, None)) + geo_specs,
            out_specs=(_P(), _TP_KV_SPEC, _TP_KV_SPEC),
            check_vma=False)
        arrs = (kp, vp, q, wo) + tuple(rest)
        donate = (0, 1)
    args = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrs)
    return fn, args, donate


def _register_tp_kernels():
    from paddle_tpu import kernels
    grid = "shard_map over tp: inner kernel per H/tp head shard; the " \
           "attention-output collective lives at the caller's " \
           "row-sharded projection"
    defs = (
        ("ragged_paged_decode_tp", "ragged_paged_decode", False, False,
         {"q": "(S,H,Dh) H/tp", "k_pages": "(P,ps,H,Dh) H/tp",
          "v_pages": "(P,ps,H,Dh) H/tp", "block_tables": "(S,mp) i32",
          "lengths": "(S,) i32"}, "(S,H,Dh) H/tp"),
        ("ragged_paged_prefill_tp", "ragged_paged_prefill", True, False,
         {"q": "(S,C,H,Dh) H/tp", "k_pages": "(P,ps,H,Dh) H/tp",
          "v_pages": "(P,ps,H,Dh) H/tp", "block_tables": "(S,mp) i32",
          "chunk_starts": "(S,) i32", "n_valid": "(S,) i32"},
         "(S,C,H,Dh) H/tp"),
        ("ragged_paged_decode_int8_tp", "ragged_paged_decode_int8",
         False, True,
         {"q": "(S,H,Dh) H/tp", "k_pages": "(P,ps,H,Dh) i8 H/tp",
          "v_pages": "(P,ps,H,Dh) i8 H/tp",
          "k_scales": "(P,ps) f32 replicated",
          "v_scales": "(P,ps) f32 replicated",
          "block_tables": "(S,mp) i32", "lengths": "(S,) i32"},
         "(S,H,Dh) H/tp"),
        ("ragged_paged_prefill_int8_tp", "ragged_paged_prefill_int8",
         True, True,
         {"q": "(S,C,H,Dh) H/tp", "k_pages": "(P,ps,H,Dh) i8 H/tp",
          "v_pages": "(P,ps,H,Dh) i8 H/tp",
          "k_scales": "(P,ps) f32 replicated",
          "v_scales": "(P,ps) f32 replicated",
          "block_tables": "(S,mp) i32", "chunk_starts": "(S,) i32",
          "n_valid": "(S,) i32"}, "(S,C,H,Dh) H/tp"),
    )
    for name, inner, chunked, quantized, layouts, out_layout in defs:
        q_spec = _TP_Q_PREFILL if chunked else _TP_Q_DECODE
        n_geo = len(layouts) - (5 if quantized else 3)
        specs = (q_spec, _TP_KV_SPEC, _TP_KV_SPEC)
        if quantized:
            specs += (_P(), _P())             # scale rows replicated
        specs += tuple(_P() for _ in range(n_geo))
        pallas_fn, lax_fn = _make_tp_fns(inner, specs)
        sample_fn = (
            (lambda s, _c=chunked: _make_paged_int8_sample(s, chunked=_c))
            if quantized else
            (lambda s, _c=chunked: _make_paged_sample(s, chunked=_c)))
        inner_spec = kernels.get(inner)
        kernels.register(kernels.KernelSpec(
            name=name,
            contract=kernels.KernelContract(
                version=1,
                arg_layouts=layouts,
                out_layout=out_layout,
                donatable=inner_spec.contract.donatable,
                grid=grid,
                collectives=("all_reduce",),
                atol=inner_spec.contract.atol,
                rtol=inner_spec.contract.rtol),
            pallas_fn=pallas_fn,
            lax_fn=lax_fn,
            reference_fn=None,        # parity_fn orchestrates the mesh
            sample_inputs=sample_fn,
            pallas_sites=(),          # reuses the inner kernel's sites
            requires_mesh=True,
            parity_fn=_make_tp_parity_fn(name, inner, sample_fn,
                                         inner_spec.reference_fn,
                                         quantized=quantized),
            donation_probe=functools.partial(
                _tp_donation_probe, chunked=chunked,
                quantized=quantized)))


_register_tp_kernels()
