"""Fleet router: prefix-affinity placement over N serving replicas.

One ServingEngine is a hard ceiling; the fleet fronts N of them behind
a single ``submit``/``step`` surface. Placement is two-tier:

1. **Prefix affinity.** The router hashes the prompt's page-aligned
   prefix digests (:func:`~paddle_tpu.serving.paged_cache.
   prompt_prefix_digests` — the SAME content-hash chain
   ``publish_prefix`` commits to each replica's prefix index) and
   counts how many leading pages each replica's advertised digest set
   already holds. The best match wins: shared-system-prompt traffic
   lands where its pages are hot and prefill is skipped, a locality
   signal no generic load balancer has.
2. **Power-of-two-choices.** No replica holds any prefix page (or
   several tie): sample two replicas and take the less loaded by live
   ``health()`` (queue depth + in-flight slots) — the classic
   O(log log n)-imbalance balancer, fed by the snapshot-published
   health the engines expose for exactly this cross-thread poll.

Every request gets a router-minted ``trace_id`` that propagates into
the replica's ``serving.request`` span (``router.route`` /
``router.migrate`` spans carry the same id), so one Perfetto timeline
shows the request crossing the fleet.

Scale-in drains **migrate** instead of killing: queued requests are
re-routed to peers; in-flight slots are snapshotted (sha256-verified
per-page shards), restored into peers' free slots, and resume decode
byte-identically — see :meth:`FleetRouter.drain_replica`.
"""

from __future__ import annotations

import random
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.serving.engine import SlotMigrationError
from paddle_tpu.serving.paged_cache import prompt_prefix_digests
from paddle_tpu.serving.scheduler import LoadShedError


class FleetRouter:
    """Single front door over N :class:`ReplicaHandle` replicas.

    ``submit()`` routes and returns a fleet-level rid; ``step()``
    advances every replica one engine iteration (the synchronous CI
    drive — threaded replicas instead run their own loops) and returns
    ``{fleet_rid: generated tokens}`` for requests that finished.
    ``policy``: ``"affinity"`` (prefix-affinity, power-of-two-choices
    fallback — the default), ``"p2c"`` (balance only), or
    ``"round_robin"`` (the baseline the routing tests beat).
    """

    def __init__(self, replicas: Sequence, *, policy: str = "affinity",
                 registry=None, tracer=None, seed: int = 0,
                 autoscaler=None):
        if not replicas:
            raise ValueError("need at least one replica")
        if policy not in ("affinity", "p2c", "round_robin"):
            raise ValueError(f"unknown policy {policy!r}")
        self.replicas: List = list(replicas)
        self.policy = policy
        from paddle_tpu import observability as obs
        self._reg = registry or obs.default()
        self.tracer = tracer or obs.tracing.default()
        self._rng = random.Random(seed)
        self.autoscaler = autoscaler
        if autoscaler is not None:
            autoscaler.bind(self)
        self._frids = iter(range(1, 1 << 62))
        self._where: Dict[int, tuple] = {}     # frid -> (replica, lrid)
        self._trace: Dict[int, int] = {}       # frid -> trace_id
        self._rev: Dict[tuple, int] = {}       # (id(rep), lrid) -> frid
        self._results: "OrderedDict[int, np.ndarray]" = OrderedDict()
        self._stats: "OrderedDict[int, Dict]" = OrderedDict()
        self._results_cap = 1024
        self._rr = 0                           # round-robin cursor
        self.migrations_total = 0
        self.routed_affinity_total = 0
        self.routed_balance_total = 0

    # -- placement ---------------------------------------------------------

    def _load(self, rep) -> float:
        h = rep.health()
        return (float(h.get("queue_depth", 0))
                + float(h.get("requests_in_flight", 0)))

    def _candidates(self, exclude=None):
        return [r for r in self.replicas
                if not getattr(r, "draining", False) and r is not exclude]

    def _pick_p2c(self, cands):
        if len(cands) == 1:
            return cands[0]
        a, b = self._rng.sample(cands, 2)
        return a if self._load(a) <= self._load(b) else b

    def _route(self, prompt, exclude=None):
        """(replica, affinity_pages) for this prompt."""
        cands = self._candidates(exclude)
        if not cands:
            raise SlotMigrationError("no routable replica")
        if self.policy == "round_robin":
            rep = cands[self._rr % len(cands)]
            self._rr += 1
            return rep, 0
        if self.policy == "affinity":
            digests = prompt_prefix_digests(
                prompt, cands[0].page_size())
            if digests:
                best, best_hits = None, 0
                for r in cands:
                    held = r.prefix_digests()
                    hits = 0
                    for d in digests:       # leading run only: pages
                        if d not in held:   # map in order or not at all
                            break
                        hits += 1
                    if hits > best_hits or (hits == best_hits and hits
                                            and best is not None
                                            and self._load(r)
                                            < self._load(best)):
                        best, best_hits = r, hits
                if best is not None and best_hits > 0:
                    self.routed_affinity_total += 1
                    return best, best_hits
        rep = self._pick_p2c(cands)
        self.routed_balance_total += 1
        return rep, 0

    # -- request surface ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None, *, lane: str = "default",
               ttft_deadline_s: Optional[float] = None) -> int:
        """Route and enqueue; returns the fleet rid. A replica that
        load-sheds is retried on the remaining replicas in load order
        before the shed propagates — one hot replica must not turn
        away traffic the rest of the fleet could serve."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        rep, hits = self._route(prompt)
        span = None
        if self.tracer.enabled:
            span = self.tracer.start_span(
                "router.route", lane=lane,
                prompt_tokens=int(prompt.shape[0]))
        trace_id = span.trace_id if span is not None else 0
        tried = []
        try:
            while True:
                try:
                    lrid = rep.submit(
                        prompt, max_new_tokens, eos_id, lane=lane,
                        ttft_deadline_s=ttft_deadline_s,
                        trace_id=trace_id or None)
                    break
                except LoadShedError:
                    tried.append(rep)
                    rest = [r for r in self._candidates()
                            if r not in tried]
                    if not rest:
                        if span is not None:
                            span.finish(status="shed")
                        raise
                    rest.sort(key=self._load)
                    rep, hits = rest[0], 0
        except Exception:
            if span is not None and span.end is None:
                span.finish(status="error")
            raise
        frid = next(self._frids)
        self._where[frid] = (rep, lrid)
        self._rev[(id(rep), lrid)] = frid
        if trace_id:
            self._trace[frid] = trace_id
        if span is not None:
            span.set_attrs(replica=rep.name, fleet_rid=frid,
                           affinity_pages=hits,
                           policy=("affinity" if hits
                                   else ("round_robin"
                                         if self.policy == "round_robin"
                                         else "p2c")))
            span.finish()
        self._reg.counter("fleet_requests_total",
                          "requests routed by the fleet router").inc(
                              replica=rep.name)
        if hits:
            self._reg.counter(
                "fleet_affinity_routed_total",
                "requests placed by prefix affinity").inc()
        return frid

    def step(self) -> Dict[int, np.ndarray]:
        """One synchronous fleet iteration: every replica steps once;
        finished requests come back under their fleet rids. Runs the
        autoscaler's ``tick()`` when one is attached."""
        finished: Dict[int, np.ndarray] = {}
        for rep in list(self.replicas):
            if rep.idle():
                continue
            for lrid, toks in rep.step().items():
                finished.update(self._finish(rep, lrid, toks))
        if self.autoscaler is not None:
            self.autoscaler.tick()
        return finished

    def _finish(self, rep, lrid, toks) -> Dict[int, np.ndarray]:
        frid = self._rev.pop((id(rep), lrid), None)
        if frid is None:
            return {}
        self._where.pop(frid, None)
        st = rep.request_stats(lrid)
        if st is not None:
            st["replica"] = rep.name
            self._stats[frid] = st
        rep.result(lrid)                      # drop the replica's copy
        self._results[frid] = toks
        while len(self._results) > self._results_cap:
            self._results.popitem(last=False)
        while len(self._stats) > self._results_cap:
            self._stats.popitem(last=False)
        self._trace.pop(frid, None)
        return {frid: toks}

    def run_until_idle(self, max_steps: Optional[int] = None
                       ) -> Dict[int, np.ndarray]:
        out: Dict[int, np.ndarray] = {}
        steps = 0
        while not self.idle():
            out.update(self.step())
            steps += 1
            if max_steps is not None and steps > max_steps:
                raise RuntimeError(f"fleet not idle in {max_steps} steps")
        return out

    def idle(self) -> bool:
        return all(r.idle() for r in self.replicas)

    def result(self, frid: int) -> Optional[np.ndarray]:
        return self._results.pop(frid, None)

    def request_stats(self, frid: int) -> Optional[Dict]:
        return self._stats.pop(frid, None)

    def trace_id(self, frid: int) -> int:
        return self._trace.get(frid, 0)

    def health(self) -> Dict[str, object]:
        """Fleet-level aggregation of every replica's health snapshot
        (the fleet ``/healthz`` payload)."""
        per = {r.name: r.health() for r in self.replicas}
        occ = [float(h.get("slot_occupancy", 0.0)) for h in per.values()]
        return {
            "replicas": len(self.replicas),
            "queue_depth_total": sum(int(h.get("queue_depth", 0))
                                     for h in per.values()),
            "requests_in_flight": sum(int(h.get("requests_in_flight", 0))
                                      for h in per.values()),
            "slot_occupancy_mean": (sum(occ) / len(occ)) if occ else 0.0,
            "recompiles": sum(int(h.get("recompiles", 0))
                              for h in per.values()),
            "migrations_total": self.migrations_total,
            "per_replica": per,
        }

    # -- elasticity --------------------------------------------------------

    def add_replica(self, rep):
        """Attach an already-warmed replica (the autoscaler precompiles
        via ``warmup_plan`` BEFORE the replica takes traffic)."""
        self.replicas.append(rep)
        self._reg.gauge("fleet_replicas",
                        "replicas serving traffic").set(
                            len(self.replicas))

    def drain_replica(self, rep, *, remove: bool = True) -> int:
        """Live-drain one replica: stop admitting, re-route its queued
        requests, migrate every in-flight slot to a peer (snapshot →
        sha256-verified restore → resume decode), then detach it.
        Returns the number of in-flight requests migrated. A snapshot
        no peer can place is restored straight back into the source
        and the drain aborts with :class:`SlotMigrationError` — drain
        never loses a request."""
        if rep not in self.replicas:
            raise ValueError(f"{rep.name} is not in this fleet")
        if len(self.replicas) < 2:
            raise SlotMigrationError("cannot drain the last replica")
        rep.draining = True
        # queued (unadmitted) requests: plain re-route, KV not built
        # yet. Every remaining peer is tried in load order before a
        # shed counts (the first p2c-sampled target shedding is not a
        # fleet-wide verdict); a request EVERY peer sheds is dropped
        # with its fleet bookkeeping cleaned — the same outcome a
        # direct submit to a saturated fleet would have had.
        for (lrid, prompt, mnew, eos, lane, dl) in rep.drain_queue():
            frid = self._rev.pop((id(rep), lrid), None)
            trace_id = self._trace.get(frid, 0) if frid else 0
            first, _hits = self._route(prompt, exclude=rep)
            others = sorted((r for r in self._candidates(exclude=rep)
                             if r is not first), key=self._load)
            nrid, target = None, None
            for peer in [first] + others:
                try:
                    nrid = peer.submit(prompt, mnew, eos, lane=lane,
                                       ttft_deadline_s=dl,
                                       trace_id=trace_id or None)
                    target = peer
                    break
                except LoadShedError:
                    continue
            if nrid is None:
                if frid is not None:
                    self._where.pop(frid, None)
                    self._trace.pop(frid, None)
                self._reg.counter(
                    "fleet_requeue_shed_total",
                    "drain re-routes shed by every remaining replica"
                ).inc()
                if self.tracer.enabled:
                    self.tracer.record_span(
                        "router.requeue", duration_s=0.0, status="shed",
                        trace_id=trace_id or None, src=rep.name)
                continue
            if frid is not None:
                self._where[frid] = (target, nrid)
                self._rev[(id(target), nrid)] = frid
            if self.tracer.enabled:
                self.tracer.record_span(
                    "router.requeue", duration_s=0.0,
                    trace_id=trace_id or None, src=rep.name,
                    dst=target.name)
        migrated = 0
        snaps = rep.snapshot_inflight()
        for pos, (lrid, snap) in enumerate(snaps):
            frid = self._rev.pop((id(rep), lrid), None)
            span = None
            if self.tracer.enabled:
                span = self.tracer.start_span(
                    "router.migrate",
                    trace_id=int(snap.get("trace_id") or 0) or None,
                    src=rep.name)
            peers = sorted(self._candidates(exclude=rep),
                           key=self._load)
            nrid, target = None, None
            for peer in peers:
                try:
                    nrid = peer.restore(snap, parent_span=span)
                    target = peer
                    break
                except SlotMigrationError:
                    continue
            if nrid is None:
                # nowhere to put it: give this one AND every remaining
                # snapshot back (their slots were already released for
                # the transfer), then abort — drain never loses a
                # request
                for bfrid, bsnap in [(frid, snap)] + [
                        (self._rev.pop((id(rep), blrid), None), bsnap2)
                        for (blrid, bsnap2) in snaps[pos + 1:]]:
                    back = rep.restore(bsnap)
                    if bfrid is not None:
                        self._where[bfrid] = (rep, back)
                        self._rev[(id(rep), back)] = bfrid
                rep.draining = False
                if span is not None:
                    span.finish(status="aborted")
                raise SlotMigrationError(
                    "no peer capacity for in-flight request; "
                    "drain aborted")
            if frid is not None:
                self._where[frid] = (target, nrid)
                self._rev[(id(target), nrid)] = frid
            migrated += 1
            self.migrations_total += 1
            self._reg.counter(
                "fleet_migrations_total",
                "in-flight requests live-migrated between replicas"
            ).inc()
            if span is not None:
                span.set_attrs(dst=target.name,
                               kv_tokens=int(snap["state"]["length"]))
                span.finish()
        if remove:
            self.replicas.remove(rep)
            rep.close()
            self._reg.gauge("fleet_replicas",
                            "replicas serving traffic").set(
                                len(self.replicas))
        return migrated


class FleetMonitor:
    """Aggregates per-replica health into fleet-level gauges in ONE
    registry, served from one exposition endpoint: ``collect()`` after
    each fleet step (or on a poll thread) refreshes
    ``fleet_replicas`` / ``fleet_queue_depth`` /
    ``fleet_requests_in_flight`` / ``fleet_slot_occupancy`` (mean and
    max) / ``fleet_page_utilization`` plus per-replica labeled series,
    and :meth:`start_exposition` exposes them with the router's
    aggregated ``/healthz``."""

    def __init__(self, router: FleetRouter, registry=None):
        from paddle_tpu import observability as obs
        self.router = router
        self.reg = registry or router._reg
        self.tracer = router.tracer
        self._obs = obs

    def collect(self) -> Dict[str, object]:
        h = self.router.health()
        g = self.reg.gauge
        g("fleet_replicas", "replicas serving traffic").set(
            h["replicas"])
        g("fleet_queue_depth", "queued requests across the fleet").set(
            h["queue_depth_total"])
        g("fleet_requests_in_flight",
          "admitted requests across the fleet").set(
              h["requests_in_flight"])
        occ, util, burn = [], [], []
        for name, rh in h["per_replica"].items():
            occ.append(float(rh.get("slot_occupancy", 0.0)))
            util.append(float(rh.get("page_utilization", 0.0)))
            g("fleet_replica_queue_depth",
              "per-replica queued requests").set(
                  rh.get("queue_depth", 0), replica=name)
            g("fleet_replica_slot_occupancy",
              "per-replica decode-slot occupancy").set(
                  rh.get("slot_occupancy", 0.0), replica=name)
            slo = rh.get("slo")
            if slo:
                burn.append(float(slo.get("burn_fast", 0.0)))
                g("fleet_replica_burn_rate",
                  "per-replica fast-window SLO burn").set(
                      slo.get("burn_fast", 0.0), replica=name)
        if occ:
            g("fleet_slot_occupancy_mean",
              "mean decode-slot occupancy").set(sum(occ) / len(occ))
            g("fleet_slot_occupancy_max",
              "max decode-slot occupancy").set(max(occ))
        if util:
            g("fleet_page_utilization_mean",
              "mean page-pool utilization").set(sum(util) / len(util))
        if burn:
            g("fleet_burn_rate_max",
              "hottest replica's fast-window burn").set(max(burn))
        return h

    def start_exposition(self, port: int = 0, host: str = "127.0.0.1"):
        """One live endpoint for the whole fleet: ``/metrics`` serves
        the aggregated registry, ``/healthz`` the router's fleet
        summary, ``/traces`` the shared tracer's ring (router spans and
        every replica's request spans — one timeline)."""
        srv = self._obs.ExpositionServer(registry=self.reg,
                                         tracer=self.tracer,
                                         port=port, host=host)
        srv.add_health("fleet", lambda: self.collect())
        return srv.start()
