"""Fleet router: prefix-affinity placement over N serving replicas.

One ServingEngine is a hard ceiling; the fleet fronts N of them behind
a single ``submit``/``step`` surface. Placement is two-tier:

1. **Prefix affinity.** The router hashes the prompt's page-aligned
   prefix digests (:func:`~paddle_tpu.serving.paged_cache.
   prompt_prefix_digests` — the SAME content-hash chain
   ``publish_prefix`` commits to each replica's prefix index) and
   counts how many leading pages each replica's advertised digest set
   already holds. The best match wins: shared-system-prompt traffic
   lands where its pages are hot and prefill is skipped, a locality
   signal no generic load balancer has.
2. **Power-of-two-choices.** No replica holds any prefix page (or
   several tie): sample two replicas and take the less loaded by live
   ``health()`` (queue depth + in-flight slots) — the classic
   O(log log n)-imbalance balancer, fed by the snapshot-published
   health the engines expose for exactly this cross-thread poll.

Every request gets a router-minted ``trace_id`` that propagates into
the replica's ``serving.request`` span (``router.route`` /
``router.migrate`` spans carry the same id), so one Perfetto timeline
shows the request crossing the fleet.

Scale-in drains **migrate** instead of killing: queued requests are
re-routed to peers; in-flight slots are snapshotted (sha256-verified
per-page shards), restored into peers' free slots, and resume decode
byte-identically — see :meth:`FleetRouter.drain_replica`.

**Involuntary failure** (ISSUE 14) is the hard counterpart: a replica
that crashes, hangs, or starts throwing is *ejected* — its KV is gone,
so queued requests re-route and in-flight requests are **redriven**:
the router records every request's prompt/budget and polls emitted
tokens each step, so after a crash it resubmits ``prompt +
tokens-observed-so-far`` as the new prompt with the remaining
``max_new_tokens`` budget to a peer (or warm-restores the newest
micro-checkpoint when the engine runs ``snapshot_every_blocks``), then
concatenates the observed prefix onto the peer's output exactly once —
greedy decode is deterministic, so the final token sequence is
bit-identical to a failure-free run. A per-request redrive budget and
deadline awareness turn hopeless requests into structured
:class:`~paddle_tpu.serving.Reject`\\ s (``redrive_budget`` /
``deadline_expired`` / ``no_replica``) — never silent loss. Transient
sickness short of death trips a per-replica
:class:`~paddle_tpu.serving.fleet.CircuitBreaker` (closed → open →
half-open probe → closed) that pauses routing without ejecting; all of
it is driven by the :class:`~paddle_tpu.serving.fleet.FailureDetector`
under one :class:`~paddle_tpu.serving.fleet.FaultPolicy`.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.analysis.concurrency import guarded_by
from paddle_tpu.serving.engine import SlotMigrationError
from paddle_tpu.serving.fleet.faults import (BREAKER_GAUGE, CircuitBreaker,
                                             FailureDetector, FaultPolicy,
                                             ReplicaCrashed,
                                             ReplicaUnavailable)
from paddle_tpu.serving.paged_cache import prompt_prefix_digests
from paddle_tpu.serving.scheduler import LoadShedError, Reject

# exceptions a peer retry can absorb: transport-shaped failures. A
# ValueError (malformed request) would fail identically everywhere and
# must propagate to the caller instead.
TRANSPORT_ERRORS = (ReplicaCrashed, ReplicaUnavailable, OSError,
                    TimeoutError)


@dataclasses.dataclass
class _FleetRequest:
    """Router-side replay record: everything needed to redrive a
    request after its replica dies. ``observed`` is the token stream
    seen so far (``committed`` — tokens already folded into the current
    submission's prompt by an earlier cold redrive — plus the live
    replica's progress poll)."""

    prompt: np.ndarray
    max_new_tokens: int
    eos_id: Optional[int]
    lane: str
    ttft_deadline_s: Optional[float]
    submitted_at: float
    trace_id: int = 0
    redrives: int = 0
    committed: List[int] = dataclasses.field(default_factory=list)
    observed: List[int] = dataclasses.field(default_factory=list)
    checkpoint: Optional[Dict] = None
    # leading prefix pages the routed replica advertised at submit time
    # — compared against the replica's actual shared_tokens at finish to
    # catch stale affinity views (fleet_affinity_miss_total)
    affinity_pages: int = 0


@guarded_by("_view_lock", "_postmortems", "_tiers")
class FleetRouter:
    """Single front door over N :class:`ReplicaHandle` replicas.

    ``submit()`` routes and returns a fleet-level rid; ``step()``
    advances every replica one engine iteration (the synchronous CI
    drive — threaded replicas instead run their own loops) and returns
    ``{fleet_rid: generated tokens}`` for requests that finished.
    ``policy``: ``"affinity"`` (prefix-affinity, power-of-two-choices
    fallback — the default), ``"p2c"`` (balance only), or
    ``"round_robin"`` (the baseline the routing tests beat).
    """

    def __init__(self, replicas: Sequence, *, policy: str = "affinity",
                 registry=None, tracer=None, seed: int = 0,
                 autoscaler=None, faults: Optional[FaultPolicy] = None,
                 clock=time.monotonic,
                 postmortem_dir: Optional[str] = None,
                 shed_spike_threshold: int = 4,
                 prefix_fetch: bool = True):
        if not replicas:
            raise ValueError("need at least one replica")
        if policy not in ("affinity", "p2c", "round_robin"):
            raise ValueError(f"unknown policy {policy!r}")
        self.replicas: List = list(replicas)
        self.policy = policy
        # fleet-global prefix reuse (ISSUE 20): when the routed replica
        # misses prefix pages a peer advertises, pull the committed
        # pages from the holder instead of re-prefilling
        self.prefix_fetch = bool(prefix_fetch)
        from paddle_tpu import observability as obs
        self._reg = registry or obs.default()
        self.tracer = tracer or obs.tracing.default()
        self._rng = random.Random(seed)
        self.autoscaler = autoscaler
        if autoscaler is not None:
            autoscaler.bind(self)
        self._frids = iter(range(1, 1 << 62))
        self._where: Dict[int, tuple] = {}     # frid -> (replica, lrid)
        self._trace: Dict[int, int] = {}       # frid -> trace_id
        self._rev: Dict[tuple, int] = {}       # (id(rep), lrid) -> frid
        self._results: "OrderedDict[int, np.ndarray]" = OrderedDict()
        self._stats: "OrderedDict[int, Dict]" = OrderedDict()
        self._rejects: "OrderedDict[int, Reject]" = OrderedDict()
        self._results_cap = 1024
        self._rr = 0                           # round-robin cursor
        self.migrations_total = 0
        self.handoffs_total = 0
        self.routed_affinity_total = 0
        self.routed_balance_total = 0
        # involuntary-failure machinery (ISSUE 14): replay records for
        # redrive, a failure detector, and per-replica circuit breakers
        self.faults = FaultPolicy() if faults is None else faults
        self._clock = clock
        self._reqs: Dict[int, _FleetRequest] = {}
        self._detector = FailureDetector(
            max_consecutive_failures=self.faults.max_consecutive_failures,
            probe_timeout_s=self.faults.probe_timeout_s)
        self._breakers: Dict[int, CircuitBreaker] = {}
        self.breaker_transitions: List[tuple] = []  # (replica, old, new)
        self.ejected_total = 0
        self.redrives_total = 0
        # crash flight recorder (ISSUE 16): every eject / breaker-open /
        # shed spike pulls the victim replica's black box into a bounded
        # bundle ring (served at /debug/postmortem) and, when a dump dir
        # is configured, onto disk for the offline renderer
        self.postmortem_dir = postmortem_dir
        self.shed_spike_threshold = int(shed_spike_threshold)
        # the bundle ring crosses threads: the pump appends in
        # _dump_postmortem while the exposition HTTP thread reads it
        # through postmortems()/health()
        self._view_lock = threading.Lock()
        self._postmortems: "deque" = deque(maxlen=16)
        # serving tier per replica ("prefill"/"decode"/"colocated") —
        # immutable per engine, cached on first successful health().
        # Crosses threads: the pump caches during step()/submit() while
        # the exposition HTTP thread may trigger a lookup via health()
        self._tiers: Dict[int, str] = {}
        self._sheds_since_dump = 0
        self._postmortem_seq = 0

    # -- placement ---------------------------------------------------------

    def _load(self, rep) -> float:
        try:
            h = rep.health()
        except NotImplementedError:
            raise
        except Exception:
            if not self.faults.enabled:
                raise               # PR 9 contract: health errors surface
            return float("inf")     # unreachable: worst possible load
        return (float(h.get("queue_depth", 0))
                + float(h.get("requests_in_flight", 0)))

    def _load_or_zero(self, rep) -> float:
        """Load for witness selection: an unreachable replica must not
        win the max() (it gets its own eject-time postmortem)."""
        load = self._load(rep)
        return 0.0 if load == float("inf") else load

    def _breaker(self, rep) -> CircuitBreaker:
        b = self._breakers.get(id(rep))
        if b is None:
            name = rep.name

            def on_transition(old, new, trace_id, _name=name, _rep=rep):
                self.breaker_transitions.append((_name, old, new))
                self._reg.gauge(
                    "fleet_breaker_state",
                    "per-replica circuit breaker "
                    "(0 closed / 1 half-open / 2 open)").set(
                        BREAKER_GAUGE[new], replica=_name)
                self._reg.counter(
                    "fleet_breaker_transitions_total",
                    "circuit-breaker state transitions").inc(
                        replica=_name, to=new)
                if self.tracer.enabled:
                    # on the triggering request's original trace id, so
                    # the breaker flip lands on that request's timeline
                    self.tracer.record_span(
                        "fleet.breaker", duration_s=0.0,
                        trace_id=trace_id or None, replica=_name,
                        **{"from": old, "to": new})
                if new == CircuitBreaker.OPEN:
                    # a sick-but-alive replica testifies at the moment
                    # the fleet stops trusting it
                    self._dump_postmortem(
                        _rep, "breaker_open",
                        trace_ids=(int(trace_id),) if trace_id else ())

            b = CircuitBreaker(threshold=self.faults.breaker_threshold,
                               cooldown_s=self.faults.breaker_cooldown_s,
                               clock=self._clock,
                               on_transition=on_transition)
            self._breakers[id(rep)] = b
        return b

    def is_routable(self, rep) -> bool:
        """Can new work land here? Draining and breaker-open replicas
        are not routable (an open breaker past its cooldown half-opens
        here, becoming probe-routable)."""
        if getattr(rep, "draining", False):
            return False
        if not self.faults.enabled:
            return True
        b = self._breakers.get(id(rep))
        if b is None:
            return True
        b.poll()
        return b.state != CircuitBreaker.OPEN

    def routable_count(self) -> int:
        """Effective capacity: replicas new work can land on. The
        autoscaler reads this — an open breaker or an ejection is lost
        capacity a replacement spawn restores."""
        return sum(1 for r in self.replicas if self.is_routable(r))

    def _candidates(self, exclude=None):
        cands = [r for r in self.replicas
                 if not getattr(r, "draining", False) and r is not exclude]
        if not self.faults.enabled:
            return cands
        return [r for r in cands if self._breaker(r).allow()]

    # -- disaggregation (ISSUE 19) -----------------------------------------

    def replica_tier(self, rep) -> str:
        """Serving tier of one replica: ``"prefill"``, ``"decode"``, or
        ``"colocated"``. The tier is fixed at engine construction, so
        the first successful ``health()`` read is cached; an
        unreachable replica reads as ``"colocated"`` WITHOUT caching
        (the next call re-asks). A failed read here is a transport
        failure like any other: it feeds the breaker and the
        consecutive-failure count exactly as a probe failure would —
        swallowing it would let the tier lookup silently absorb health
        flakes the detection loop needs to see. A breaker-open replica
        is already quarantined and is not asked at all."""
        with self._view_lock:
            tier = self._tiers.get(id(rep))
        if tier is not None:
            return tier
        if self.faults.enabled:
            b = self._breakers.get(id(rep))
            if b is not None and b.state == CircuitBreaker.OPEN:
                return "colocated"
        try:
            h = rep.health()
        except NotImplementedError:
            raise
        except Exception as e:
            if not self.faults.enabled:
                raise
            if not isinstance(e, ReplicaCrashed):
                self._breaker(rep).record_failure()
            reason = self._detector.observe_failure(rep.name, e)
            if reason is not None and rep in self.replicas:
                self.eject_replica(rep, reason=reason)
            return "colocated"
        tier = str(h.get("tier") or "colocated")
        with self._view_lock:
            self._tiers[id(rep)] = tier
        return tier

    def _prompt_candidates(self, exclude=None):
        """Candidates for a FRESH prompt. Decode-tier replicas only
        take restored prefill-complete slots — routing them a prompt
        would be refused by the engine anyway (``ValueError``), so they
        are filtered here and the router never even tries."""
        return [r for r in self._candidates(exclude)
                if self.replica_tier(r) != "decode"]

    def _flops_headroom(self, rep) -> float:
        """Prefill placement signal: the flops headroom the engine's
        resource plane publishes (1 = idle compute, 0 = saturated)."""
        try:
            h = rep.health()
        except NotImplementedError:
            raise
        except Exception:
            if not self.faults.enabled:
                raise
            return -1.0
        return float((h.get("headroom") or {}).get("flops", 0.0))

    def _decode_headroom(self, rep) -> float:
        """Decode placement signal: a restored slot needs pages AND a
        free slot, so the binding resource is the min of the two
        headrooms."""
        try:
            h = rep.health()
        except NotImplementedError:
            raise
        except Exception:
            if not self.faults.enabled:
                raise
            return -1.0
        hd = h.get("headroom") or {}
        return min(float(hd.get("pages", 0.0)),
                   float(hd.get("slots", 0.0)))

    def _pick_p2c(self, cands):
        if len(cands) == 1:
            return cands[0]
        a, b = self._rng.sample(cands, 2)
        return a if self._load(a) <= self._load(b) else b

    def _route(self, prompt, exclude=None):
        """(replica, affinity_pages) for this prompt. Routes PROMPTS,
        so decode-tier replicas are never candidates; in a
        disaggregated fleet the prefill tier is preferred and the
        balance pick is by flops headroom (prefill is flops-bound —
        queue depth alone misreads a replica mid-chunked-prefill)."""
        cands = self._prompt_candidates(exclude)
        if not cands:
            raise SlotMigrationError("no routable replica")
        pre = [r for r in cands
               if self.replica_tier(r) == "prefill"]
        tiered = bool(pre)
        if tiered:
            cands = pre
        if self.faults.enabled:
            # a half-open breaker needs its probe request SENT, not
            # left to sampling chance: route the next request there
            # deliberately (allow() bounds it to one probe in flight)
            for r in cands:
                b = self._breakers.get(id(r))
                if b is not None and b.state == CircuitBreaker.HALF_OPEN:
                    b.note_probe()
                    return r, 0
        if self.policy == "round_robin":
            rep = cands[self._rr % len(cands)]
            self._rr += 1
            return rep, 0
        if self.policy == "affinity":
            digests = prompt_prefix_digests(
                prompt, cands[0].page_size())
            if digests:
                best, best_hits = None, 0
                for r in cands:
                    held = r.prefix_digests()
                    hits = 0
                    for d in digests:       # leading run only: pages
                        if d not in held:   # map in order or not at all
                            break
                        hits += 1
                    if hits > best_hits or (hits == best_hits and hits
                                            and best is not None
                                            and self._load(r)
                                            < self._load(best)):
                        best, best_hits = r, hits
                if best is not None and best_hits > 0:
                    self.routed_affinity_total += 1
                    return best, best_hits
        rep = (max(cands, key=self._flops_headroom) if tiered
               else self._pick_p2c(cands))
        self.routed_balance_total += 1
        return rep, 0

    # -- request surface ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None, *, lane: str = "default",
               ttft_deadline_s: Optional[float] = None) -> int:
        """Route and enqueue; returns the fleet rid. A replica that
        load-sheds is retried on the remaining replicas in load order
        before the shed propagates — one hot replica must not turn
        away traffic the rest of the fleet could serve."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        rep, hits = self._route(prompt)
        span = None
        if self.tracer.enabled:
            span = self.tracer.start_span(
                "router.route", lane=lane,
                prompt_tokens=int(prompt.shape[0]))
        trace_id = span.trace_id if span is not None else 0
        enabled = self.faults.enabled
        tried = []
        try:
            while True:
                try:
                    lrid = rep.submit(
                        prompt, max_new_tokens, eos_id, lane=lane,
                        ttft_deadline_s=ttft_deadline_s,
                        trace_id=trace_id or None)
                    self._note_transport_success(rep, trace_id)
                    break
                except LoadShedError:
                    # a shed proves the replica is ALIVE: the breaker
                    # tracks transport health, not load
                    if enabled:
                        self._breaker(rep).record_success(trace_id)
                    tried.append(rep)
                    rest = [r for r in self._prompt_candidates()
                            if r not in tried]
                    if not rest:
                        if span is not None:
                            span.finish(status="shed")
                        raise
                    rest.sort(key=self._load)
                    rep, hits = rest[0], 0
                except TRANSPORT_ERRORS as e:
                    if not enabled:
                        raise
                    self._note_transport_failure(rep, e, trace_id)
                    tried.append(rep)
                    rest = [r for r in self._prompt_candidates()
                            if r not in tried]
                    if not rest:
                        if span is not None:
                            span.finish(status="error")
                        raise
                    rest.sort(key=self._load)
                    rep, hits = rest[0], 0
        except Exception:
            if span is not None and span.end is None:
                span.finish(status="error")
            raise
        fetched = self._prefix_fetch(rep, hits, prompt, trace_id)
        frid = next(self._frids)
        self._where[frid] = (rep, lrid)
        self._rev[(id(rep), lrid)] = frid
        self._reqs[frid] = _FleetRequest(
            prompt=prompt, max_new_tokens=max_new_tokens, eos_id=eos_id,
            lane=lane, ttft_deadline_s=ttft_deadline_s,
            submitted_at=self._clock(), trace_id=trace_id,
            affinity_pages=hits + fetched)
        if trace_id:
            self._trace[frid] = trace_id
        if span is not None:
            span.set_attrs(replica=rep.name, fleet_rid=frid,
                           affinity_pages=hits,
                           policy=("affinity" if hits
                                   else ("round_robin"
                                         if self.policy == "round_robin"
                                         else "p2c")))
            span.finish()
        self._reg.counter("fleet_requests_total",
                          "requests routed by the fleet router").inc(
                              replica=rep.name)
        if hits:
            self._reg.counter(
                "fleet_affinity_routed_total",
                "requests placed by prefix affinity").inc()
        return frid

    def _prefix_fetch(self, target, hits: int, prompt,
                      trace_id: int = 0) -> int:
        """Fleet-global prefix reuse (ISSUE 20): when the routed
        replica misses leading prefix pages a peer advertises, pull the
        committed pages from the holder as hash-chained migration
        shards and install them on the target BEFORE its next admission
        — re-use instead of re-prefill. Strictly best-effort: a holder
        that drained, crashed, or got autoscaled away mid-fetch, and a
        bundle the importer refuses, all degrade to local re-prefill
        with a structured marker. The request itself is never touched.
        Returns pages installed on the target."""
        if not self.prefix_fetch:
            return 0
        try:
            digests = prompt_prefix_digests(prompt, target.page_size())
        except TRANSPORT_ERRORS:
            return 0
        if not digests:
            return 0
        holders = []
        for r in self.replicas:
            if r is target:
                continue
            # draining replicas stay candidates: a drain refuses NEW
            # work, but exporting committed pages is a read — exactly
            # the window where a drained replica's prefixes must
            # survive by copying out
            try:
                held = r.prefix_digests()
            except NotImplementedError:
                raise
            except Exception:
                continue        # unreachable holder: not a candidate
            run = 0
            for d in digests:   # leading run only, like _route
                if d not in held:
                    break
                run += 1
            if run > hits:
                holders.append((run, r))
        if not holders:
            return 0
        holders.sort(key=lambda t: -t[0])
        t0 = self._clock()
        for run, holder in holders:
            try:
                bundle = holder.export_prefix_pages(digests[:run])
            except TRANSPORT_ERRORS as e:
                # the holder died/drained mid-fetch: breaker + detector
                # see it like any transport failure, next holder serves
                if self.faults.enabled:
                    self._note_transport_failure(holder, e, trace_id)
                self._reg.counter(
                    "fleet_prefix_fetch_failed_total",
                    "prefix-page fetches failed before install").inc(
                        reason="transport")
                continue
            if bundle is None:
                # stale advertisement: the pages left the holder
                # between the scan and the export
                self._reg.counter(
                    "fleet_prefix_fetch_failed_total",
                    "prefix-page fetches failed before install").inc(
                        reason="gone")
                continue
            self._note_transport_success(holder, trace_id)
            try:
                installed = target.import_prefix_pages(bundle)
            except SlotMigrationError as e:
                # corrupt or unprovable bundle REFUSED by the importer
                # — never installed, never decoded from
                self._reg.counter(
                    "fleet_prefix_fetch_refused_total",
                    "prefix bundles refused by the importer "
                    "(corrupt or incompatible)").inc()
                self._degrade_prefix_fetch(target, holder, trace_id,
                                           str(e))
                return 0
            except TRANSPORT_ERRORS as e:
                # the TARGET failed mid-install: the request's own
                # redrive machinery owns that failure, not the fetch
                if self.faults.enabled:
                    self._note_transport_failure(target, e, trace_id)
                self._degrade_prefix_fetch(target, holder, trace_id,
                                           type(e).__name__)
                return 0
            if installed:
                self._reg.counter(
                    "fleet_prefix_fetch_total",
                    "prefix-page fetch transfers completed").inc(
                        src=holder.name, dst=target.name)
                self._reg.counter(
                    "fleet_prefix_fetch_pages_total",
                    "prefix pages installed from fleet peers").inc(
                        installed)
                self._reg.counter(
                    "fleet_prefix_fetch_bytes_total",
                    "prefix-page bytes shipped between replicas").inc(
                        int(bundle.get("bytes") or 0))
                if self.tracer.enabled:
                    self.tracer.record_span(
                        "router.prefix_fetch",
                        duration_s=self._clock() - t0,
                        trace_id=trace_id or None, src=holder.name,
                        dst=target.name, pages=installed,
                        status="fetched")
            return installed
        self._degrade_prefix_fetch(target, None, trace_id,
                                   "no holder reachable")
        return 0

    def _degrade_prefix_fetch(self, target, holder, trace_id: int,
                              reason: str):
        """Structured degrade marker: the fetch failed, the request
        re-prefills locally — visible as a counter and a span, never an
        error on the request."""
        self._reg.counter(
            "fleet_prefix_fetch_degraded_total",
            "prefix fetches degraded to local re-prefill").inc()
        if self.tracer.enabled:
            self.tracer.record_span(
                "router.prefix_fetch", duration_s=0.0,
                trace_id=trace_id or None, dst=target.name,
                src=holder.name if holder is not None else "",
                status="degraded_local_prefill", reason=reason)

    def _note_transport_failure(self, rep, exc, trace_id: int = 0):
        """Breaker + detector accounting for a transport-shaped
        failure; ejects the replica when the detector declares death."""
        self._breaker(rep).record_failure(trace_id)
        reason = self._detector.observe_failure(rep.name, exc)
        if reason is not None and rep in self.replicas:
            self.eject_replica(rep, reason=reason)

    def _note_transport_success(self, rep, trace_id: int = 0):
        """EVERY successful transport interaction must feed the
        breaker — a half-open probe can be delivered by any submit /
        restore path (redrive included), and a success that goes
        unrecorded leaves the breaker stuck half-open with its one
        probe permanently in flight."""
        if self.faults.enabled:
            self._detector.observe_success(rep.name)
            self._breaker(rep).record_success(trace_id)

    def _probe(self, rep) -> Optional[str]:
        """Health-probe one replica; returns a death reason or None.
        Probe exceptions feed the circuit breaker AND count toward the
        consecutive-failure threshold (with breaker_threshold below
        max_consecutive_failures, a transiently flaky health endpoint
        quarantines behind the breaker before the death verdict fires);
        a successful probe can still carry a terminal verdict
        (replica-surfaced loop crash, stale heartbeat with work
        pending)."""
        try:
            h = rep.health()
        except NotImplementedError:
            raise
        except Exception as e:
            if not isinstance(e, ReplicaCrashed):
                self._breaker(rep).record_failure()
            return self._detector.observe_failure(rep.name, e)
        return self._detector.check_health(rep.name, h)

    def _poll_progress(self, rep):
        """Record each in-flight request's emitted tokens (and newest
        micro-checkpoint) into its replay record, so a later crash of
        this replica cannot take the progress with it. The poll is
        incremental — ``progress(since=...)`` returns only tokens past
        what the record already holds, so tracking costs O(new tokens)
        per step, not O(stream length)."""
        rid_key = id(rep)
        since: Dict[int, int] = {}
        recs: Dict[int, _FleetRequest] = {}
        for (okey, lrid), frid in self._rev.items():
            if okey != rid_key:
                continue
            rec = self._reqs.get(frid)
            if rec is not None:
                recs[lrid] = rec
                since[lrid] = len(rec.observed) - len(rec.committed)
        try:
            prog = rep.progress(since)
            cps = rep.poll_checkpoints()
        except NotImplementedError:
            raise
        except Exception:
            return                  # dying replica: keep last knowns
        for lrid, tail in prog.items():
            rec = recs.get(lrid)
            if rec is None:
                continue
            if getattr(tail, "full_replay", False):
                # the replica answered a stale cursor with the whole
                # stream (progress contract hardening): REPLACE the
                # live portion of the record — extending would
                # double-count every token already held
                rec.observed = list(rec.committed) + [int(t)
                                                      for t in tail]
            else:
                rec.observed.extend(int(t) for t in tail)
        for lrid, snap in cps:
            rec = recs.get(lrid)
            if rec is not None:
                rec.checkpoint = snap

    def _reconcile_rejects(self, rep):
        """A replica's engine can shed a queued request on its own
        (TTFT deadline expired before admission). Its step() never
        returns that rid, so without this poll the request would be
        silently lost at the fleet level — here the engine's structured
        verdict is lifted into ``router.reject_reason`` and the replay
        record is cleaned."""
        rid_key = id(rep)
        mine = [(frid, lrid) for (okey, lrid), frid
                in list(self._rev.items()) if okey == rid_key]
        for frid, lrid in mine:
            try:
                rej = rep.reject_reason(lrid)
            except NotImplementedError:
                raise
            except Exception:
                return              # dying replica: eject path handles it
            if rej is None:
                continue
            self._rev.pop((rid_key, lrid), None)
            self._where.pop(frid, None)
            rec = self._reqs.pop(frid, None)
            self._rejects[frid] = rej
            while len(self._rejects) > self._results_cap:
                self._rejects.popitem(last=False)
            tid = (rec.trace_id if rec is not None
                   else self._trace.get(frid, 0))
            self._trace.pop(frid, None)
            self._reg.counter(
                "fleet_replica_shed_total",
                "requests shed by a replica's own engine after "
                "queueing, surfaced as fleet rejects").inc(
                    reason=rej.reason)
            if self.tracer.enabled:
                self.tracer.record_span(
                    "router.replica_shed", duration_s=0.0,
                    status="shed", trace_id=tid or None,
                    replica=rep.name, reason=rej.reason)

    def step(self) -> Dict[int, np.ndarray]:
        """One synchronous fleet iteration: every replica steps once;
        finished requests come back under their fleet rids. Runs the
        autoscaler's ``tick()`` when one is attached.

        With ``faults.enabled`` this is also the detection loop: each
        replica is health-probed (probe exception / replica-surfaced
        loop crash / stale heartbeat with work pending), step
        exceptions count toward the consecutive-failure threshold
        (:class:`ReplicaCrashed` is immediately terminal), and a death
        verdict triggers :meth:`eject_replica` — queued requests
        re-route, in-flight requests redrive exactly-once."""
        finished: Dict[int, np.ndarray] = {}
        enabled = self.faults.enabled
        for rep in list(self.replicas):
            if rep not in self.replicas:
                continue            # ejected by an earlier iteration
            if enabled:
                # a breaker-open replica is already quarantined: keep
                # stepping its in-flight work but stop health-probing
                # it, so a transient flake cannot walk the consecutive
                # count to the death verdict while the breaker holds
                b = self._breakers.get(id(rep))
                if b is None or b.state != CircuitBreaker.OPEN:
                    reason = self._probe(rep)
                    if reason is not None:
                        self.eject_replica(rep, reason=reason)
                        continue
            try:
                if rep.idle():
                    continue
                out = rep.step()
            except NotImplementedError:
                raise
            except Exception as e:
                if not enabled:
                    raise
                self._reg.counter(
                    "fleet_step_failures_total",
                    "replica step()/idle() exceptions seen by the "
                    "router").inc(replica=rep.name)
                reason = self._detector.observe_failure(rep.name, e)
                if reason is not None:
                    self.eject_replica(rep, reason=reason)
                continue
            if enabled:
                self._detector.observe_success(rep.name)
            for lrid, toks in out.items():
                finished.update(self._finish(rep, lrid, toks))
            if enabled:
                self._poll_progress(rep)
                self._reconcile_rejects(rep)
        self._pump_handoffs()
        if self.autoscaler is not None:
            self.autoscaler.tick()
        return finished

    # -- prefill -> decode streaming (ISSUE 19) ----------------------------

    def _pump_handoffs(self):
        """Drain every prefill-tier replica's handoff outbox and place
        each prefill-complete slot onto the decode tier. Runs every
        fleet step regardless of ``faults.enabled`` — disaggregation is
        a serving mode, not a fault feature."""
        for rep in list(self.replicas):
            if rep not in self.replicas:
                continue            # ejected mid-sweep
            if self.replica_tier(rep) != "prefill":
                continue
            try:
                handoffs = rep.poll_handoffs()
            except NotImplementedError:
                raise
            except Exception as e:
                if not self.faults.enabled:
                    raise
                # the slots were snapshotted-or-kept atomically by the
                # engine, so a crash here loses no request: the eject
                # path redrives from the replay records
                reason = self._detector.observe_failure(rep.name, e)
                if reason is not None and rep in self.replicas:
                    self.eject_replica(rep, reason=reason)
                continue
            for lrid, snap in handoffs:
                self._place_handoff(rep, lrid, snap)

    def _place_handoff(self, src, lrid, snap):
        """Place one prefill-complete snapshot onto the decode replica
        with the most page/slot headroom (decode is bandwidth-bound —
        the binding resource is KV capacity, not compute). Placement
        failure falls back to restoring the snapshot into the SOURCE
        with the ``decode_in_place`` marker — the prefill engine
        finishes the decode itself rather than losing the request; if
        even the source cannot take it back, the request redrives from
        its replay record. A handoff NEVER loses a request."""
        frid = self._rev.pop((id(src), lrid), None)
        if frid is not None:
            self._where.pop(frid, None)
        tid = int(snap.get("trace_id") or 0)
        span = None
        if self.tracer.enabled:
            span = self.tracer.start_span(
                "router.handoff", trace_id=tid or None, src=src.name)
        nbytes = sum(int(m.get("bytes", 0))
                     for m in snap.get("manifest", ()))
        decoders = sorted(
            (r for r in self._candidates(exclude=src)
             if self.replica_tier(r) == "decode"),
            key=self._decode_headroom, reverse=True)
        for peer in decoders:
            try:
                nrid = peer.restore(snap, parent_span=span)
            except NotImplementedError:
                raise
            except SlotMigrationError:
                continue            # no capacity there: next decoder
            except TRANSPORT_ERRORS as e:
                if not self.faults.enabled:
                    raise
                self._note_transport_failure(peer, e, tid)
                continue
            self._note_transport_success(peer, tid)
            if frid is not None:
                self._where[frid] = (peer, nrid)
                self._rev[(id(peer), nrid)] = frid
                rec = self._reqs.get(frid)
                if rec is not None:
                    rec.observed = list(rec.committed) + [
                        int(t) for t in snap["state"]["generated"]]
            self.handoffs_total += 1
            self._reg.counter(
                "fleet_handoff_total",
                "prefill-complete slots streamed to the decode "
                "tier").inc(src=src.name, dst=peer.name)
            self._reg.counter(
                "fleet_handoff_bytes_total",
                "sha256-verified page bytes shipped prefill -> "
                "decode").inc(nbytes, src=src.name, dst=peer.name)
            if span is not None:
                span.set_attrs(dst=peer.name, bytes=nbytes,
                               kv_tokens=int(snap["state"]["length"]))
                span.finish()
            return
        back = dict(snap)
        back["decode_in_place"] = True
        try:
            nrid = src.restore(back, parent_span=span)
        except NotImplementedError:
            raise
        except Exception:
            # source slot already freed and unplaceable anywhere: the
            # replay record (prompt + observed tokens) redrives it —
            # structured Reject at worst, never silent loss
            if span is not None:
                span.finish(status="redrive")
            if frid is not None:
                rec = self._reqs.get(frid)
                if rec is not None:
                    rec.observed = list(rec.committed) + [
                        int(t) for t in snap["state"]["generated"]]
                self._redrive(frid, src=src.name)
            return
        if frid is not None:
            self._where[frid] = (src, nrid)
            self._rev[(id(src), nrid)] = frid
        self._reg.counter(
            "fleet_handoff_fallback_total",
            "handoffs decoded in place on the prefill tier (no "
            "decode capacity)").inc(replica=src.name)
        if span is not None:
            span.finish(status="decode_in_place")

    def _finish(self, rep, lrid, toks) -> Dict[int, np.ndarray]:
        frid = self._rev.pop((id(rep), lrid), None)
        if frid is None:
            return {}
        self._where.pop(frid, None)
        rec = self._reqs.pop(frid, None)
        if rec is not None and rec.committed:
            # dedup on assembly (exactly-once): tokens a cold redrive
            # folded into the resubmitted prompt come back EXACTLY once,
            # prepended here — the peer only generated the remainder
            toks = np.concatenate([
                np.asarray(rec.committed, np.int32),
                np.asarray(toks, np.int32).reshape(-1)])
        st = rep.request_stats(lrid)
        if st is not None:
            st["replica"] = rep.name
            if rec is not None and rec.redrives:
                st["redrives"] = rec.redrives
            self._stats[frid] = st
            if rec is not None and rec.affinity_pages \
                    and not rec.redrives \
                    and not float(st.get("shared_tokens") or 0.0):
                # stale affinity view (ISSUE 20): routing promised
                # shared pages the replica no longer held at admission
                # — prefix_gen propagation should keep this at zero
                self._reg.counter(
                    "fleet_affinity_miss_total",
                    "affinity-routed requests that mapped no shared "
                    "pages on arrival").inc()
        rep.result(lrid)                      # drop the replica's copy
        self._results[frid] = toks
        while len(self._results) > self._results_cap:
            self._results.popitem(last=False)
        while len(self._stats) > self._results_cap:
            self._stats.popitem(last=False)
        self._trace.pop(frid, None)
        return {frid: toks}

    def run_until_idle(self, max_steps: Optional[int] = None
                       ) -> Dict[int, np.ndarray]:
        out: Dict[int, np.ndarray] = {}
        steps = 0
        while not self.idle():
            out.update(self.step())
            steps += 1
            if max_steps is not None and steps > max_steps:
                raise RuntimeError(f"fleet not idle in {max_steps} steps")
        return out

    def idle(self) -> bool:
        for r in self.replicas:
            try:
                if not r.idle():
                    return False
            except NotImplementedError:
                raise
            except Exception:
                if self.faults.enabled:
                    return False    # not idle: step() must eject it
                raise
        return True

    def result(self, frid: int) -> Optional[np.ndarray]:
        return self._results.pop(frid, None)

    def reject_reason(self, frid: int) -> Optional[Reject]:
        """Structured verdict for a request the fleet shed after
        acceptance (redrive budget spent, deadline expired before any
        token, or no replica left) — pop-on-read, mirroring
        ``ServingEngine.reject_reason``. A request is NEVER silently
        lost: it has a result or a reject."""
        return self._rejects.pop(frid, None)

    def request_stats(self, frid: int) -> Optional[Dict]:
        return self._stats.pop(frid, None)

    def progress(self, frid: int) -> Optional[List[int]]:
        """Tokens observed so far for an in-flight request (committed
        redrive prefix + the live replica's progress polls) — the
        incremental-token feed the streaming front door delivers from.
        None once the request has finished, shed, or was never
        accepted; non-destructive, unlike ``result``."""
        rec = self._reqs.get(frid)
        return None if rec is None else list(rec.observed)

    def trace_id(self, frid: int) -> int:
        return self._trace.get(frid, 0)

    def health(self) -> Dict[str, object]:
        """Fleet-level aggregation of every replica's health snapshot
        (the fleet ``/healthz`` payload). The fault-tolerance section
        carries per-replica breaker states, routable capacity, and the
        eject/redrive totals; ``degraded`` is set while any breaker is
        open or half-open, which the exposition endpoint surfaces as
        HTTP 503."""
        # called from the exposition HTTP thread while the pump mutates
        # the fleet: snapshot the replica list once so add/eject mid-
        # iteration can't blow up the scrape
        reps = list(self.replicas)
        per = {}
        for r in reps:
            try:
                per[r.name] = r.health()
            except NotImplementedError:
                raise
            except Exception as e:
                if not self.faults.enabled:
                    raise           # PR 9 contract: health errors surface
                per[r.name] = {"error": f"{type(e).__name__}: {e}"}
        occ = [float(h.get("slot_occupancy", 0.0)) for h in per.values()]
        breakers = {r.name: self._breakers[id(r)].status()
                    for r in reps if id(r) in self._breakers}
        with self._view_lock:
            n_postmortems = len(self._postmortems)
        return {
            "replicas": len(reps),
            # chips behind the fleet (ISSUE 15): a tp=4 replica is 4
            # chips of capacity — the autoscaler and /healthz must not
            # read it as one
            "chips_total": sum(int(h.get("mesh_devices", 1) or 1)
                               for h in per.values()),
            "queue_depth_total": sum(int(h.get("queue_depth", 0) or 0)
                                     for h in per.values()),
            "requests_in_flight": sum(
                int(h.get("requests_in_flight", 0) or 0)
                for h in per.values()),
            "slot_occupancy_mean": (sum(occ) / len(occ)) if occ else 0.0,
            "recompiles": sum(int(h.get("recompiles", 0) or 0)
                              for h in per.values()),
            "migrations_total": self.migrations_total,
            "handoffs_total": self.handoffs_total,
            "routable": self.routable_count(),
            "ejected_total": self.ejected_total,
            "redrives_total": self.redrives_total,
            "postmortems": n_postmortems,
            "breakers": breakers,
            "degraded": any(b["state"] != CircuitBreaker.CLOSED
                            for b in breakers.values()),
            "per_replica": per,
        }

    # -- elasticity --------------------------------------------------------

    def add_replica(self, rep):
        """Attach an already-warmed replica (the autoscaler precompiles
        via ``warmup_plan`` BEFORE the replica takes traffic)."""
        self.replicas.append(rep)
        self._reg.gauge("fleet_replicas",
                        "replicas serving traffic").set(
                            len(self.replicas))

    def eject_replica(self, rep, *, reason: str = "crashed") -> int:
        """Hard removal of a dead replica — the involuntary counterpart
        of :meth:`drain_replica`. Its KV is gone, so nothing can be
        migrated: queued requests re-route and in-flight requests are
        **redriven** from the router's replay records (warm-restore of
        the newest micro-checkpoint when one exists, else resubmit
        ``prompt + tokens-observed-so-far`` with the remaining budget).
        Greedy decode is deterministic, so redriven outputs are
        bit-identical to a failure-free run; requests that cannot be
        redriven (budget spent, deadline expired, no replica left) shed
        with a structured :class:`~paddle_tpu.serving.Reject` — never
        silently lost. Returns the number of requests redriven or
        shed."""
        if rep not in self.replicas:
            return 0
        rep.draining = True         # never a redrive target
        victims = [(frid, lrid)
                   for (okey, lrid), frid in list(self._rev.items())
                   if okey == id(rep)]
        for frid, lrid in victims:
            self._rev.pop((id(rep), lrid), None)
            self._where.pop(frid, None)
        self.replicas.remove(rep)
        self.ejected_total += 1
        self._breakers.pop(id(rep), None)
        self._reg.counter(
            "fleet_ejected_total",
            "replicas declared dead and removed").inc(
                reason=reason.split(":", 1)[0])
        self._reg.gauge("fleet_replicas",
                        "replicas serving traffic").set(
                            len(self.replicas))
        if self.tracer.enabled:
            self.tracer.record_span(
                "router.eject", duration_s=0.0, replica=rep.name,
                reason=reason, requests=len(victims))
        # flight recorder: the black box comes off BEFORE close() —
        # victim trace ids link the bundle to every redriven request's
        # timeline
        tids = []
        for frid, _lrid in victims:
            rec = self._reqs.get(frid)
            tid = ((rec.trace_id if rec is not None else 0)
                   or self._trace.get(frid, 0))
            if tid:
                tids.append(int(tid))
        self._dump_postmortem(
            rep, "eject", trace_ids=tids,
            extra={"cause": reason, "victims": len(victims)})
        try:
            rep.close()             # best-effort: it is already dead
        except Exception:
            pass
        for frid, _lrid in victims:
            self._redrive(frid, src=rep.name)
        return len(victims)

    def _dump_postmortem(self, rep, reason: str, *, trace_ids=(),
                         extra=None):
        """Pull ``rep``'s flight-recorder black box into the router's
        bounded bundle ring (and onto ``postmortem_dir`` when one is
        configured, for the offline renderer). Best-effort by design:
        postmortem capture must never turn one failure into two."""
        try:
            bundle = rep.postmortem(reason, trace_ids=trace_ids)
        except NotImplementedError:
            raise
        except Exception:
            bundle = None
        if bundle is None:
            return None
        if extra:
            bundle.setdefault("extra", {}).update(extra)
        with self._view_lock:
            self._postmortems.append(bundle)
        self._postmortem_seq += 1
        self._reg.counter(
            "fleet_postmortems_total",
            "postmortem bundles captured by the router").inc(
                reason=reason)
        if self.tracer.enabled:
            self.tracer.record_span(
                "router.postmortem", duration_s=0.0, replica=rep.name,
                reason=reason, victims=len(tuple(trace_ids)))
        if self.postmortem_dir:
            from paddle_tpu.observability import flight as _flight
            try:
                os.makedirs(self.postmortem_dir, exist_ok=True)
                path = os.path.join(
                    self.postmortem_dir,
                    f"postmortem_{self._postmortem_seq:04d}_"
                    f"{rep.name}.json")
                _flight.write_bundle(bundle, path)
            except OSError:
                pass                # capture survives a full disk
        return bundle

    def postmortems(self, limit: Optional[int] = None) -> List[Dict]:
        """Captured postmortem bundles, oldest first (bounded ring) —
        the ``/debug/postmortem`` payload source (HTTP thread)."""
        with self._view_lock:
            out = list(self._postmortems)
        return out[-limit:] if limit else out

    def _redrive(self, frid: int, *, src: str = "?"):
        """Exactly-once redrive of one request whose replica died."""
        rec = self._reqs.get(frid)
        if rec is None:             # already finished or never recorded
            self._trace.pop(frid, None)
            return
        tid = rec.trace_id or self._trace.get(frid, 0)
        observed = list(rec.observed)
        # the observed stream may already be complete (the replica died
        # between emitting the last token and reporting the finish):
        # deliver it directly, exactly once
        if rec.eos_id is not None and rec.eos_id in observed:
            observed = observed[:observed.index(rec.eos_id) + 1]
            return self._finish_from_observed(frid, rec, observed, src)
        if len(observed) >= rec.max_new_tokens:
            return self._finish_from_observed(
                frid, rec, observed[:rec.max_new_tokens], src)
        rec.redrives += 1
        if rec.redrives > self.faults.max_redrives:
            return self._shed_redrive(frid, rec, "redrive_budget", src)
        # deadline awareness: once the first token was observed the TTFT
        # deadline is already met; before that, an expired deadline
        # sheds with a structured reason instead of redriving a request
        # nobody is waiting for
        deadline = None
        if rec.ttft_deadline_s is not None and not observed:
            dl_at = rec.submitted_at + rec.ttft_deadline_s
            now = self._clock()
            if now > dl_at:
                return self._shed_redrive(frid, rec, "deadline_expired",
                                          src)
            deadline = dl_at - now
        # warm path: restore the newest micro-checkpoint into a peer —
        # KV travels, only the post-checkpoint tail re-decodes
        if rec.checkpoint is not None:
            snap, rec.checkpoint = rec.checkpoint, None  # consume once
            span = None
            if self.tracer.enabled:
                span = self.tracer.start_span(
                    "router.redrive", trace_id=tid or None, mode="warm",
                    src=src, tokens_observed=len(observed))
            for peer in sorted(self._candidates(), key=self._load):
                try:
                    nrid = peer.restore(snap, parent_span=span)
                except NotImplementedError:
                    raise
                except Exception:
                    continue        # corrupt / no capacity / dying peer
                self._note_transport_success(peer, tid)
                self._where[frid] = (peer, nrid)
                self._rev[(id(peer), nrid)] = frid
                # the restored slot carries its generated tokens; the
                # observed stream continues from the snapshot's state
                rec.observed = list(rec.committed) + [
                    int(t) for t in snap["state"]["generated"]]
                self.redrives_total += 1
                self._reg.counter(
                    "fleet_redrive_total",
                    "in-flight requests redriven after replica "
                    "death").inc(mode="warm")
                if span is not None:
                    span.set_attrs(dst=peer.name)
                    span.finish()
                return
            if span is not None:
                span.finish(status="fallback_cold")
        # cold path: resubmit prompt + observed as the new prompt with
        # the remaining budget — greedy determinism makes the
        # continuation identical to the uninterrupted run
        if observed:
            new_prompt = np.concatenate([
                rec.prompt, np.asarray(observed, np.int32)])
        else:
            new_prompt = rec.prompt
        remaining = rec.max_new_tokens - len(observed)
        try:
            first, _hits = self._route(new_prompt)
        except SlotMigrationError:
            return self._shed_redrive(frid, rec, "no_replica", src)
        others = sorted((r for r in self._prompt_candidates()
                         if r is not first), key=self._load)
        last_shed: Optional[LoadShedError] = None
        for peer in [first] + others:
            try:
                nrid = peer.submit(new_prompt, remaining, rec.eos_id,
                                   lane=rec.lane,
                                   ttft_deadline_s=deadline,
                                   trace_id=tid or None)
            except LoadShedError as e:
                # alive but loaded: close-probe accounting, then move on
                if self.faults.enabled:
                    self._breaker(peer).record_success(tid)
                last_shed = e
                continue
            except NotImplementedError:
                raise
            except TRANSPORT_ERRORS as e:
                self._note_transport_failure(peer, e, tid)
                continue
            except Exception:
                continue            # dying peer: its own probe ejects it
            self._note_transport_success(peer, tid)
            self._where[frid] = (peer, nrid)
            self._rev[(id(peer), nrid)] = frid
            rec.committed = list(observed)
            rec.observed = list(observed)
            self.redrives_total += 1
            self._reg.counter(
                "fleet_redrive_total",
                "in-flight requests redriven after replica death").inc(
                    mode="cold")
            if self.tracer.enabled:
                self.tracer.record_span(
                    "router.redrive", duration_s=0.0,
                    trace_id=tid or None, mode="cold", src=src,
                    dst=peer.name, tokens_observed=len(observed),
                    remaining=remaining)
            return
        reason = (last_shed.reject.reason if last_shed is not None
                  else "no_replica")
        return self._shed_redrive(frid, rec, reason, src)

    def _finish_from_observed(self, frid, rec, observed, src):
        toks = np.asarray(observed, np.int32)
        self._results[frid] = toks
        while len(self._results) > self._results_cap:
            self._results.popitem(last=False)
        self._reqs.pop(frid, None)
        self._trace.pop(frid, None)
        self.redrives_total += 1
        self._reg.counter(
            "fleet_redrive_total",
            "in-flight requests redriven after replica death").inc(
                mode="observed")
        if self.tracer.enabled:
            self.tracer.record_span(
                "router.redrive", duration_s=0.0,
                trace_id=(rec.trace_id or None), mode="observed",
                src=src, tokens_observed=len(observed))

    def _shed_redrive(self, frid, rec, reason: str, src: str):
        """A request the fleet cannot redrive sheds with a structured
        verdict (surfaced via :meth:`reject_reason`) — the no-silent-
        loss contract."""
        self._rejects[frid] = Reject(reason, rec.lane, 0, 0.0, 0.001)
        while len(self._rejects) > self._results_cap:
            self._rejects.popitem(last=False)
        self._reqs.pop(frid, None)
        self._trace.pop(frid, None)
        self._reg.counter(
            "fleet_redrive_shed_total",
            "redrives shed with a structured reason").inc(reason=reason)
        if self.tracer.enabled:
            self.tracer.record_span(
                "router.redrive", duration_s=0.0, status="shed",
                trace_id=(rec.trace_id or None), src=src, reason=reason)
        # shed spike: losing requests in bulk is a fleet-level incident
        # even when no single replica died — the busiest survivor's
        # black box is the congestion witness
        self._sheds_since_dump += 1
        if (self.shed_spike_threshold
                and self._sheds_since_dump >= self.shed_spike_threshold):
            witness = None
            cands = [r for r in self.replicas
                     if not getattr(r, "draining", False)]
            if cands:
                witness = max(cands, key=self._load_or_zero)
            if witness is not None:
                self._dump_postmortem(
                    witness, "shed_spike",
                    trace_ids=(int(rec.trace_id),) if rec.trace_id else (),
                    extra={"sheds": self._sheds_since_dump,
                           "last_reason": reason, "last_src": src})
            self._sheds_since_dump = 0

    def _drain_crashed(self, rep, exc: BaseException) -> int:
        """A replica died mid-drain: fall through to eject + redrive
        (nothing is lost — queued requests already re-routed, in-flight
        requests redrive from the replay records)."""
        if not self.faults.enabled:
            raise exc
        self._reg.counter(
            "fleet_drain_crash_total",
            "replicas that died mid-drain (fell through to "
            "eject + redrive)").inc()
        if self.tracer.enabled:
            self.tracer.record_span(
                "router.drain_crashed", duration_s=0.0,
                replica=rep.name,
                error=f"{type(exc).__name__}: {exc}")
        return self.eject_replica(
            rep, reason=f"crashed_mid_drain:{type(exc).__name__}")

    def drain_replica(self, rep, *, remove: bool = True) -> int:
        """Live-drain one replica: stop admitting, re-route its queued
        requests, migrate every in-flight slot to a peer (snapshot →
        sha256-verified restore → resume decode), then detach it.
        Returns the number of in-flight requests migrated. A snapshot
        no peer can place is restored straight back into the source
        and the drain aborts with :class:`SlotMigrationError` — drain
        never loses a request."""
        if rep not in self.replicas:
            raise ValueError(f"{rep.name} is not in this fleet")
        if len(self.replicas) < 2:
            raise SlotMigrationError("cannot drain the last replica")
        rep.draining = True
        # queued (unadmitted) requests: plain re-route, KV not built
        # yet. Every remaining peer is tried in load order before a
        # shed counts (the first p2c-sampled target shedding is not a
        # fleet-wide verdict); a request EVERY peer sheds is dropped
        # with its fleet bookkeeping cleaned — the same outcome a
        # direct submit to a saturated fleet would have had.
        try:
            queued = rep.drain_queue()
        except NotImplementedError:
            raise
        except Exception as e:
            return self._drain_crashed(rep, e)
        for (lrid, prompt, mnew, eos, lane, dl) in queued:
            frid = self._rev.pop((id(rep), lrid), None)
            trace_id = self._trace.get(frid, 0) if frid else 0
            first, _hits = self._route(prompt, exclude=rep)
            others = sorted((r for r in self._prompt_candidates(exclude=rep)
                             if r is not first), key=self._load)
            nrid, target = None, None
            for peer in [first] + others:
                try:
                    nrid = peer.submit(prompt, mnew, eos, lane=lane,
                                       ttft_deadline_s=dl,
                                       trace_id=trace_id or None)
                    self._note_transport_success(peer, trace_id or 0)
                    target = peer
                    break
                except LoadShedError:
                    continue
                except TRANSPORT_ERRORS as e:
                    if not self.faults.enabled:
                        raise
                    self._note_transport_failure(peer, e,
                                                 trace_id or 0)
                    continue
            if nrid is None:
                if frid is not None:
                    self._where.pop(frid, None)
                    self._trace.pop(frid, None)
                    rec = self._reqs.pop(frid, None)
                    # structured verdict, never silence: the caller can
                    # distinguish "shed everywhere" from "still running"
                    self._rejects[frid] = Reject(
                        "requeue_shed", rec.lane if rec else lane,
                        0, 0.0, 0.001)
                    while len(self._rejects) > self._results_cap:
                        self._rejects.popitem(last=False)
                self._reg.counter(
                    "fleet_requeue_shed_total",
                    "drain re-routes shed by every remaining replica"
                ).inc()
                if self.tracer.enabled:
                    self.tracer.record_span(
                        "router.requeue", duration_s=0.0, status="shed",
                        trace_id=trace_id or None, src=rep.name)
                continue
            if frid is not None:
                self._where[frid] = (target, nrid)
                self._rev[(id(target), nrid)] = frid
            if self.tracer.enabled:
                self.tracer.record_span(
                    "router.requeue", duration_s=0.0,
                    trace_id=trace_id or None, src=rep.name,
                    dst=target.name)
        migrated = 0
        # the drain-vs-crash race: a replica that dies HERE — after its
        # queue was handed over but before migration completes — must
        # not take the in-flight requests with it. The failure falls
        # through to the eject path, which redrives them from the
        # router's replay records.
        try:
            snaps = rep.snapshot_inflight()
        except NotImplementedError:
            raise
        except Exception as e:
            return self._drain_crashed(rep, e)
        for pos, (lrid, snap) in enumerate(snaps):
            frid = self._rev.pop((id(rep), lrid), None)
            span = None
            if self.tracer.enabled:
                span = self.tracer.start_span(
                    "router.migrate",
                    trace_id=int(snap.get("trace_id") or 0) or None,
                    src=rep.name)
            peers = sorted(self._candidates(exclude=rep),
                           key=self._load)
            nrid, target = None, None
            for peer in peers:
                try:
                    nrid = peer.restore(snap, parent_span=span)
                    self._note_transport_success(peer)
                    target = peer
                    break
                except SlotMigrationError:
                    continue
                except TRANSPORT_ERRORS as e:
                    if not self.faults.enabled:
                        raise
                    self._note_transport_failure(peer, e)
                    continue
            if nrid is None:
                # nowhere to put it: give this one AND every remaining
                # snapshot back (their slots were already released for
                # the transfer), then abort — drain never loses a
                # request
                for bfrid, bsnap in [(frid, snap)] + [
                        (self._rev.pop((id(rep), blrid), None), bsnap2)
                        for (blrid, bsnap2) in snaps[pos + 1:]]:
                    back = rep.restore(bsnap)
                    if bfrid is not None:
                        self._where[bfrid] = (rep, back)
                        self._rev[(id(rep), back)] = bfrid
                rep.draining = False
                if span is not None:
                    span.finish(status="aborted")
                raise SlotMigrationError(
                    "no peer capacity for in-flight request; "
                    "drain aborted")
            if frid is not None:
                self._where[frid] = (target, nrid)
                self._rev[(id(target), nrid)] = frid
            migrated += 1
            self.migrations_total += 1
            self._reg.counter(
                "fleet_migrations_total",
                "in-flight requests live-migrated between replicas"
            ).inc()
            if span is not None:
                span.set_attrs(dst=target.name,
                               kv_tokens=int(snap["state"]["length"]))
                span.finish()
        if remove:
            self.replicas.remove(rep)
            rep.close()
            self._reg.gauge("fleet_replicas",
                            "replicas serving traffic").set(
                                len(self.replicas))
        return migrated


class FleetMonitor:
    """Aggregates per-replica health into fleet-level gauges in ONE
    registry, served from one exposition endpoint: ``collect()`` after
    each fleet step (or on a poll thread) refreshes
    ``fleet_replicas`` / ``fleet_queue_depth`` /
    ``fleet_requests_in_flight`` / ``fleet_slot_occupancy`` (mean and
    max) / ``fleet_page_utilization`` plus per-replica labeled series,
    and :meth:`start_exposition` exposes them with the router's
    aggregated ``/healthz``."""

    # per-replica labeled series collect() owns — dropped for vanished
    # replicas so an ejected replica's last gauge values don't haunt
    # /metrics (and dashboards) for the life of the process
    _PER_REPLICA_METRICS = ("fleet_replica_queue_depth",
                            "fleet_replica_slot_occupancy",
                            "fleet_replica_tp",
                            "fleet_replica_burn_rate",
                            "fleet_replica_headroom",
                            "fleet_breaker_state")

    def __init__(self, router: FleetRouter, registry=None):
        from paddle_tpu import observability as obs
        self.router = router
        self.reg = registry or router._reg
        self.tracer = router.tracer
        self._obs = obs
        self._seen_replicas: set = set()

    def _drop_stale(self, live) -> int:
        dropped = 0
        for name in self._seen_replicas - set(live):
            for mname in self._PER_REPLICA_METRICS:
                m = self.reg.get(mname)
                if m is not None:
                    dropped += m.remove_matching(replica=name)
        self._seen_replicas = set(live)
        return dropped

    def collect(self) -> Dict[str, object]:
        h = self.router.health()
        self._drop_stale(h["per_replica"])
        g = self.reg.gauge
        g("fleet_replicas", "replicas serving traffic").set(
            h["replicas"])
        g("fleet_chips", "accelerator chips behind the fleet "
          "(tp-degree-weighted replica count)").set(
              h.get("chips_total", h["replicas"]))
        g("fleet_queue_depth", "queued requests across the fleet").set(
            h["queue_depth_total"])
        g("fleet_requests_in_flight",
          "admitted requests across the fleet").set(
              h["requests_in_flight"])
        g("fleet_routable_replicas",
          "replicas new work can land on (breaker-closed, "
          "not draining)").set(h.get("routable", h["replicas"]))
        for name, bs in (h.get("breakers") or {}).items():
            g("fleet_breaker_state",
              "per-replica circuit breaker "
              "(0 closed / 1 half-open / 2 open)").set(
                  BREAKER_GAUGE[bs["state"]], replica=name)
        occ, util, burn = [], [], []
        head_min: Dict[str, float] = {}
        for name, rh in h["per_replica"].items():
            occ.append(float(rh.get("slot_occupancy", 0.0)))
            util.append(float(rh.get("page_utilization", 0.0)))
            # disaggregation (ISSUE 19): tiered replicas carry their
            # tier on every per-replica series; colocated fleets keep
            # the exact pre-tier label sets (dashboards and exact-label
            # value() lookups stay byte-identical)
            tier = str(rh.get("tier") or "colocated")
            lbl = ({"replica": name} if tier == "colocated"
                   else {"replica": name, "tier": tier})
            # resource-headroom plane (ISSUE 16): per-replica gauges +
            # the fleet-level bottleneck (min across replicas) the
            # autoscaler and /healthz read
            for res, v in (rh.get("headroom") or {}).items():
                if res in ("flops", "pages", "slots", "hbm", "spill"):
                    v = float(v)
                    g("fleet_replica_headroom",
                      "per-replica resource headroom "
                      "(1 = idle, 0 = saturated)").set(
                          v, resource=res, **lbl)
                    head_min[res] = min(head_min.get(res, 1.0), v)
            g("fleet_replica_queue_depth",
              "per-replica queued requests").set(
                  rh.get("queue_depth", 0), **lbl)
            g("fleet_replica_slot_occupancy",
              "per-replica decode-slot occupancy").set(
                  rh.get("slot_occupancy", 0.0), **lbl)
            g("fleet_replica_tp",
              "per-replica tensor-parallel degree (mesh chips)").set(
                  rh.get("mesh_devices", 1) or 1, **lbl)
            slo = rh.get("slo")
            if slo:
                burn.append(float(slo.get("burn_fast", 0.0)))
                g("fleet_replica_burn_rate",
                  "per-replica fast-window SLO burn").set(
                      slo.get("burn_fast", 0.0), **lbl)
        if occ:
            g("fleet_slot_occupancy_mean",
              "mean decode-slot occupancy").set(sum(occ) / len(occ))
            g("fleet_slot_occupancy_max",
              "max decode-slot occupancy").set(max(occ))
        if util:
            g("fleet_page_utilization_mean",
              "mean page-pool utilization").set(sum(util) / len(util))
        if burn:
            g("fleet_burn_rate_max",
              "hottest replica's fast-window burn").set(max(burn))
        for res, v in head_min.items():
            g("fleet_headroom_min",
              "fleet bottleneck headroom per resource "
              "(min across replicas)").set(v, resource=res)
        h["headroom"] = head_min
        return h

    def start_exposition(self, port: int = 0, host: str = "127.0.0.1"):
        """One live endpoint for the whole fleet: ``/metrics`` serves
        the aggregated registry, ``/healthz`` the router's fleet
        summary, ``/traces`` the shared tracer's ring (router spans and
        every replica's request spans — one timeline)."""
        srv = self._obs.ExpositionServer(registry=self.reg,
                                         tracer=self.tracer,
                                         port=port, host=host)
        srv.add_health("fleet", lambda: self.collect())
        srv.add_postmortem("fleet", self.router.postmortems)
        return srv.start()
