"""Elastic autoscaling: the BurnRateMonitor becomes the scale signal.

Each replica already runs a multi-window SLO burn-rate monitor over its
TTFT histogram (PR 8); the autoscaler reads that burn straight out of
``health()["slo"]`` and turns sustained deadline pressure into capacity:

- **Scale out**: any replica's burn over ``scale_out_burn`` on BOTH
  windows (the page-severity shape — a spike alone never scales) for
  ``sustain_s`` seconds → ``spawn_replica()`` builds a fresh replica,
  the autoscaler runs its full ``warmup()`` (every decode/prefill/
  migration bucket precompiled — ``warmup_plan`` discipline) BEFORE
  the router sees it, so a scale-out never injects compiles into the
  serving path.
- **Scale in**: the whole fleet idle-ish (occupancy under
  ``idle_occupancy`` and no queue) for ``idle_s`` seconds with more
  than ``min_replicas`` running → the least-loaded replica is drained
  through :meth:`FleetRouter.drain_replica` — queued requests
  re-routed, in-flight requests **live-migrated** (snapshot → verified
  restore → resume), never killed.

A ``cooldown_s`` gate after either action stops flapping, and an
injected ``clock`` makes every threshold unit-testable without
sleeping. Replicas that exit as OS processes on scale-in should use
:data:`~paddle_tpu.resilience.preempt.EXIT_DRAINED` so
``fleet.ElasticCoordinator`` retires them without burning respawn
budget.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional


class FleetAutoscaler:
    """Burn-rate-driven elastic sizing for a :class:`FleetRouter`.

    ``spawn_replica(index) -> ReplicaHandle`` builds (but need not
    warm) a new replica; the autoscaler warms it before attaching.
    ``tick()`` is called once per fleet step (the router does this
    automatically when constructed with ``autoscaler=``); it returns
    ``"scale_out"`` / ``"scale_in"`` / ``"replace"`` / ``None`` for
    observability and tests. ``"replace"`` (ISSUE 14) restores
    capacity lost *involuntarily*: when ejections and open circuit
    breakers drop the ROUTABLE replica count below ``min_replicas``,
    a warmed replacement spawns (cooldown-gated) — the autoscaler
    treats an open breaker exactly as lost capacity, while voluntary
    drains shrink the fleet on purpose and are never replaced.
    """

    def __init__(self, spawn_replica: Callable[[int], object], *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 scale_out_burn: float = 6.0, sustain_s: float = 2.0,
                 idle_occupancy: float = 0.1, idle_s: float = 5.0,
                 cooldown_s: float = 5.0, headroom_floor: float = 0.0,
                 tiers: Optional[Dict[str, Dict]] = None,
                 registry=None, clock=time.monotonic):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas < min_replicas")
        self.spawn_replica = spawn_replica
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.scale_out_burn = float(scale_out_burn)
        self.sustain_s = float(sustain_s)
        self.idle_occupancy = float(idle_occupancy)
        self.idle_s = float(idle_s)
        self.cooldown_s = float(cooldown_s)
        self.headroom_floor = float(headroom_floor)
        self._clock = clock
        from paddle_tpu import observability as obs
        self._reg = registry or obs.default()
        self.router = None
        self._spawned = 0
        self._hot_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._cooldown_until = float("-inf")
        self.scale_outs = 0
        self.scale_ins = 0
        self.events: List[Dict] = []
        # disaggregated fleets (ISSUE 19) scale each tier on ITS
        # binding resource: the prefill tier is flops-bound (queue wait
        # and compute headroom), the decode tier is KV-capacity-bound
        # (page/slot headroom). Each tier gets its own spawn factory,
        # min/max, sustain/idle windows (shared durations) and
        # cooldown. ``tiers=None`` keeps the single-pool behavior for
        # colocated fleets bit-for-bit.
        self.tiers: Optional[Dict[str, Dict]] = None
        if tiers:
            self.tiers = {}
            for tname, tcfg in tiers.items():
                if tname not in ("prefill", "decode"):
                    raise ValueError(
                        f"unknown tier {tname!r} (prefill/decode)")
                if not callable(tcfg.get("spawn")):
                    raise ValueError(
                        f"tier {tname!r} needs a spawn callable")
                tmin = int(tcfg.get("min", 1))
                tmax = int(tcfg.get("max", max_replicas))
                if tmin < 1 or tmax < tmin:
                    raise ValueError(
                        f"tier {tname!r}: bad min/max ({tmin}/{tmax})")
                self.tiers[tname] = {
                    "spawn": tcfg["spawn"], "min": tmin, "max": tmax,
                    "queue_hot": int(tcfg.get("queue_hot", 4)),
                    "headroom_floor": float(
                        tcfg.get("headroom_floor", 0.25)),
                }
            self._tier_hot: Dict[str, Optional[float]] = {
                t: None for t in self.tiers}
            self._tier_idle: Dict[str, Optional[float]] = {
                t: None for t in self.tiers}
            self._tier_cooldown: Dict[str, float] = {
                t: float("-inf") for t in self.tiers}

    def bind(self, router):
        self.router = router
        self._spawned = len(router.replicas)

    # -- signal reads ------------------------------------------------------

    def _routable(self):
        """Replicas new work can land on — open breakers and ejected
        replicas are LOST capacity, invisible to the burn/idle signals
        and replaced by :meth:`_replace`."""
        router = self.router
        if hasattr(router, "is_routable"):
            return [r for r in router.replicas if router.is_routable(r)]
        return list(router.replicas)

    def _pressure(self) -> float:
        """Hottest routable replica's burn, counted only when BOTH
        windows breach (the alerting shape — one latency spike never
        scales)."""
        worst = 0.0
        for rep in self._routable():
            try:
                slo = rep.health().get("slo") or {}
            except NotImplementedError:
                raise
            except Exception:
                continue            # dying replica: the detector's job
            bf = float(slo.get("burn_fast", 0.0))
            bs = float(slo.get("burn_slow", 0.0))
            if bf >= self.scale_out_burn and bs >= self.scale_out_burn:
                worst = max(worst, bf)
        return worst

    def _fleet_idle(self) -> bool:
        h = self.router.health()
        if (h["queue_depth_total"] != 0
                or h["slot_occupancy_mean"] > self.idle_occupancy):
            return False
        # headroom cross-check (ISSUE 16), opt-in via headroom_floor>0:
        # occupancy can read idle between decode bursts while KV pages
        # are still pinned — a replica below the page/slot/HBM headroom
        # floor is holding live state, and draining it would migrate
        # all of it for nothing. The default floor of 0.0 disables the
        # veto so an operator who tuned idle_occupancy alone keeps the
        # scale-in timing they asked for; replicas without a headroom
        # plane pass regardless.
        for rh in h["per_replica"].values():
            head = rh.get("headroom") or {}
            # "spill" joins the veto (ISSUE 20): a replica whose host
            # pool is full of spilled prefix pages is the fleet's cold
            # prefix store — scaling it in would destroy pages peers
            # still fetch (spill reads 1.0 when the tier is off)
            for res in ("pages", "slots", "hbm", "spill"):
                if float(head.get(res, 1.0)) < self.headroom_floor:
                    return False
        return True

    # -- the periodic decision ---------------------------------------------

    def tick(self) -> Optional[str]:
        if self.router is None:
            raise RuntimeError("autoscaler not bound to a router")
        now = self._clock()
        if self.tiers:
            return self._tick_tiered(now)
        if now < self._cooldown_until:
            return None
        n = len(self.router.replicas)
        n_routable = len(self._routable())
        # lost capacity first: a crash ejection or an open breaker has
        # dropped the ROUTABLE fleet below the floor — spawn a warmed
        # replacement (the crashed/drained distinction from PR 9:
        # drains shrink the fleet on purpose and do not replace)
        if n_routable < self.min_replicas and n < self.max_replicas:
            return self._replace(n_routable)
        burn = self._pressure()
        if burn > 0.0 and n < self.max_replicas:
            self._idle_since = None
            if self._hot_since is None:
                self._hot_since = now
            if now - self._hot_since >= self.sustain_s:
                return self._scale_out(burn)
            return None
        self._hot_since = None
        if n > self.min_replicas and self._fleet_idle():
            if self._idle_since is None:
                self._idle_since = now
            if now - self._idle_since >= self.idle_s:
                return self._scale_in()
            return None
        self._idle_since = None
        return None

    def _replace(self, n_routable: int) -> str:
        """Spawn a warmed replacement for capacity lost involuntarily
        (ejected replica / open breaker) — same full-warmup-before-
        traffic discipline as scale-out, its own counter so crash
        churn is distinguishable from demand growth."""
        rep = self.spawn_replica(self._spawned)
        self._spawned += 1
        rep.warmup()
        self.router.add_replica(rep)
        self._cooldown_until = self._clock() + self.cooldown_s
        self._reg.counter(
            "fleet_replace_spawn_total",
            "replicas spawned to replace lost capacity").inc()
        self.events.append({"action": "replace",
                            "routable": n_routable,
                            "replicas": len(self.router.replicas),
                            "replica": rep.name})
        if self.router.tracer.enabled:
            self.router.tracer.record_span(
                "fleet.replace", duration_s=0.0, routable=n_routable,
                replicas=len(self.router.replicas), replica=rep.name)
        return "replace"

    def _scale_out(self, burn: float) -> str:
        rep = self.spawn_replica(self._spawned)
        self._spawned += 1
        rep.warmup()        # every bucket compiled BEFORE first traffic
        self.router.add_replica(rep)
        self.scale_outs += 1
        self._hot_since = None
        self._cooldown_until = self._clock() + self.cooldown_s
        self._reg.counter("fleet_scale_out_total",
                          "replicas added by the autoscaler").inc()
        self.events.append({"action": "scale_out", "burn": burn,
                            "replicas": len(self.router.replicas),
                            "replica": rep.name})
        if self.router.tracer.enabled:
            self.router.tracer.record_span(
                "fleet.scale_out", duration_s=0.0, burn=round(burn, 3),
                replicas=len(self.router.replicas), replica=rep.name)
        return "scale_out"

    def _scale_in(self) -> Optional[str]:
        from paddle_tpu.serving.engine import SlotMigrationError
        # victims come from the ROUTABLE set: draining a breaker-open
        # replica would try to live-migrate through the very transport
        # that is failing. A fleet with no routable victim (fleet-wide
        # breaker flap) simply cannot shrink right now — never crash
        # the serve loop over it.
        cands = [r for r in self._routable()
                 if not getattr(r, "draining", False)]
        if not cands:
            self._idle_since = None
            return None
        victim = min(
            cands,
            key=lambda r: float(
                r.health().get("requests_in_flight", 0)))
        try:
            migrated = self.router.drain_replica(victim)
        except SlotMigrationError:
            # peers had no capacity for the victim's in-flight work —
            # the drain restored everything back and lost nothing, but
            # the fleet cannot shrink right now. Back off a cooldown
            # instead of re-raising into the serve loop (which would
            # retry-and-crash every step while the condition holds).
            self._idle_since = None
            self._cooldown_until = self._clock() + self.cooldown_s
            self._reg.counter(
                "fleet_scale_in_aborted_total",
                "scale-in drains aborted for lack of peer capacity"
            ).inc()
            self.events.append({"action": "scale_in_aborted",
                                "replica": victim.name,
                                "replicas": len(self.router.replicas)})
            return None
        self.scale_ins += 1
        self._idle_since = None
        self._cooldown_until = self._clock() + self.cooldown_s
        self._reg.counter("fleet_scale_in_total",
                          "replicas drained by the autoscaler").inc()
        self.events.append({"action": "scale_in", "migrated": migrated,
                            "replicas": len(self.router.replicas),
                            "replica": victim.name})
        if self.router.tracer.enabled:
            self.router.tracer.record_span(
                "fleet.scale_in", duration_s=0.0, migrated=migrated,
                replicas=len(self.router.replicas), replica=victim.name)
        return "scale_in"

    # -- per-tier scaling (ISSUE 19) ---------------------------------------

    def _tick_tiered(self, now: float) -> Optional[str]:
        """One decision pass over each configured tier. Tiers are
        independent — a hot prefill tier scales out while an idle
        decode tier scales in, each behind its own cooldown."""
        router = self.router
        action = None
        for tname, cfg in self.tiers.items():
            if now < self._tier_cooldown[tname]:
                continue
            members = [r for r in router.replicas
                       if router.replica_tier(r) == tname]
            routable = [r for r in members if router.is_routable(r)]
            # lost capacity first, same rule as the single pool
            if len(routable) < cfg["min"] and len(members) < cfg["max"]:
                action = self._tier_spawn(tname, cfg, "replace",
                                          routable=len(routable))
                continue
            if (self._tier_pressure(tname, cfg, routable)
                    and len(members) < cfg["max"]):
                self._tier_idle[tname] = None
                if self._tier_hot[tname] is None:
                    self._tier_hot[tname] = now
                if now - self._tier_hot[tname] >= self.sustain_s:
                    action = self._tier_spawn(tname, cfg, "scale_out")
                continue
            self._tier_hot[tname] = None
            if (len(members) > cfg["min"]
                    and self._tier_is_idle(routable)):
                if self._tier_idle[tname] is None:
                    self._tier_idle[tname] = now
                if now - self._tier_idle[tname] >= self.idle_s:
                    action = self._tier_scale_in(
                        tname, routable) or action
                continue
            self._tier_idle[tname] = None
        return action

    def _tier_pressure(self, tname: str, cfg: Dict, routable) -> bool:
        """Tier-specific saturation: prefill is flops-bound (compute
        headroom under the floor, or queued prompts piling up); decode
        is KV-bound (page/slot headroom under the floor)."""
        floor = cfg["headroom_floor"]
        for rep in routable:
            try:
                h = rep.health()
            except NotImplementedError:
                raise
            except Exception:
                continue            # dying replica: the detector's job
            head = h.get("headroom") or {}
            if tname == "prefill":
                if int(h.get("queue_depth", 0) or 0) >= cfg["queue_hot"]:
                    return True
                if float(head.get("flops", 1.0)) < floor:
                    return True
            else:
                if min(float(head.get("pages", 1.0)),
                       float(head.get("slots", 1.0))) < floor:
                    return True
        return False

    def _tier_is_idle(self, members) -> bool:
        for rep in members:
            try:
                h = rep.health()
            except NotImplementedError:
                raise
            except Exception:
                return False
            if (int(h.get("queue_depth", 0) or 0) != 0
                    or float(h.get("slot_occupancy", 0.0))
                    > self.idle_occupancy):
                return False
        return bool(members)

    def _tier_spawn(self, tname: str, cfg: Dict, action: str,
                    routable: Optional[int] = None) -> str:
        rep = cfg["spawn"](self._spawned)
        self._spawned += 1
        rep.warmup()        # every bucket compiled BEFORE first traffic
        self.router.add_replica(rep)
        self._tier_hot[tname] = None
        self._tier_cooldown[tname] = self._clock() + self.cooldown_s
        if action == "scale_out":
            self.scale_outs += 1
            self._reg.counter(
                "fleet_scale_out_total",
                "replicas added by the autoscaler").inc(tier=tname)
        else:
            self._reg.counter(
                "fleet_replace_spawn_total",
                "replicas spawned to replace lost capacity").inc(
                    tier=tname)
        ev = {"action": action, "tier": tname,
              "replicas": len(self.router.replicas),
              "replica": rep.name}
        if routable is not None:
            ev["routable"] = routable
        self.events.append(ev)
        if self.router.tracer.enabled:
            self.router.tracer.record_span(
                f"fleet.{action}", duration_s=0.0, tier=tname,
                replicas=len(self.router.replicas), replica=rep.name)
        return f"{action}:{tname}"

    def _tier_scale_in(self, tname: str, routable) -> Optional[str]:
        from paddle_tpu.serving.engine import SlotMigrationError
        cands = [r for r in routable
                 if not getattr(r, "draining", False)]
        if not cands:
            self._tier_idle[tname] = None
            return None
        victim = min(
            cands,
            key=lambda r: float(
                r.health().get("requests_in_flight", 0)))
        try:
            migrated = self.router.drain_replica(victim)
        except SlotMigrationError:
            self._tier_idle[tname] = None
            self._tier_cooldown[tname] = self._clock() + self.cooldown_s
            self._reg.counter(
                "fleet_scale_in_aborted_total",
                "scale-in drains aborted for lack of peer capacity"
            ).inc(tier=tname)
            self.events.append({"action": "scale_in_aborted",
                                "tier": tname, "replica": victim.name,
                                "replicas": len(self.router.replicas)})
            return None
        self.scale_ins += 1
        self._tier_idle[tname] = None
        self._tier_cooldown[tname] = self._clock() + self.cooldown_s
        self._reg.counter(
            "fleet_scale_in_total",
            "replicas drained by the autoscaler").inc(tier=tname)
        self.events.append({"action": "scale_in", "tier": tname,
                            "migrated": migrated,
                            "replicas": len(self.router.replicas),
                            "replica": victim.name})
        if self.router.tracer.enabled:
            self.router.tracer.record_span(
                "fleet.scale_in", duration_s=0.0, tier=tname,
                migrated=migrated,
                replicas=len(self.router.replicas),
                replica=victim.name)
        return f"scale_in:{tname}"
