"""Multi-replica serving fleet: router, autoscaler, live migration.

The first subsystem that treats :class:`~paddle_tpu.serving.
ServingEngine` replicas as cattle (ROADMAP open item 2). Three parts:

1. **Replica handles** (`replica.py`): :class:`ReplicaHandle` is the
   transport interface (submit / step / health / prefix digests /
   snapshot / restore); :class:`LocalReplica` implements it in-process
   — synchronous stepping for deterministic CI, optional background
   thread — so a process/HTTP transport can slot in later without the
   router changing.
2. **Router** (`router.py`): :class:`FleetRouter` places requests by
   **prefix affinity** first (the prompt's page-aligned content-hash
   digests vs each replica's published prefix index — shared-prompt
   traffic lands where its pages are hot) with **power-of-two-choices**
   over live ``health()`` as fallback; router-minted ``trace_id``
   propagates into replica spans so one Perfetto timeline crosses the
   fleet; :class:`FleetMonitor` folds per-replica metrics into
   fleet-level gauges behind one exposition endpoint.
3. **Autoscaler** (`autoscaler.py`): :class:`FleetAutoscaler` turns
   sustained SLO burn (each replica's BurnRateMonitor) into scale-out
   — new replicas fully ``warmup()``-precompiled before taking traffic
   — and sustained idle into scale-in via **live migration**: queued
   requests re-routed, in-flight slots snapshotted (sha256-verified
   per-page shards), restored into peers, decode resumed
   byte-identically.
4. **Fault tolerance** (`faults.py`, ISSUE 14): involuntary failure
   as a first-class citizen — :class:`FailureDetector` declares a
   replica dead (crash, hang, N consecutive exceptions, replica-
   surfaced loop death), :meth:`FleetRouter.eject_replica` redrives
   its requests **exactly once** (bit-identical greedy outputs, warm
   micro-checkpoint restore or cold ``prompt + observed`` resubmit,
   structured sheds for hopeless requests), per-replica
   :class:`CircuitBreaker`\\ s pause routing to transiently sick
   replicas (the autoscaler spawns replacements for the lost
   capacity), and :class:`ChaosReplica` injects all of it
   deterministically for the chaos test battery.
5. **Network serving** (`net/`, ISSUE 17): the PR 9 promise cashed in —
   ``net.ReplicaServer`` runs one engine per process behind a framed
   wire protocol, ``net.NetReplica`` is the client-side
   :class:`ReplicaHandle` the router drives with zero code forks, and
   ``net.FrontDoor`` streams tokens to clients incrementally with
   bounded buffers and structured rejects.
"""

from paddle_tpu.serving.fleet.replica import (FullReplay, LocalReplica,
                                              ReplicaHandle)
from paddle_tpu.serving.fleet.router import FleetMonitor, FleetRouter
from paddle_tpu.serving.fleet.autoscaler import FleetAutoscaler
from paddle_tpu.serving.fleet.faults import (ChaosReplica, ChaosSpec,
                                             CircuitBreaker,
                                             FailureDetector, FaultPolicy,
                                             ReplicaCrashed,
                                             ReplicaUnavailable,
                                             chaos_schedule)
from paddle_tpu.serving.engine import SlotMigrationError
from paddle_tpu.serving.paged_cache import prompt_prefix_digests

__all__ = [
    "ReplicaHandle", "LocalReplica", "FullReplay",
    "FleetRouter", "FleetMonitor",
    "FleetAutoscaler", "SlotMigrationError", "prompt_prefix_digests",
    "ChaosReplica", "ChaosSpec", "CircuitBreaker", "FailureDetector",
    "FaultPolicy", "ReplicaCrashed", "ReplicaUnavailable",
    "chaos_schedule",
]
