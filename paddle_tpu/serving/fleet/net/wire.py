"""Length-prefixed framed wire protocol for network serving.

One message = one **envelope frame** (msgpack when the optional
``msgpack`` package is importable, JSON otherwise — the container rule:
no new hard dependencies) followed by N **binary frames**, one per
numpy array the payload references. Every frame is::

    !4sBBI  = MAGIC "PTNW" | version | kind | payload length

and every binary frame's bytes are sha256-checksummed against the
digest the envelope declared for it — the same integrity discipline as
the migration manifest (``ServingEngine.snapshot_slot`` hashes each
(page, tp-shard) the same way), so a KV snapshot crossing a socket is
verified twice: once per frame here, once per shard by
``restore_slot``. A checksum or framing mismatch raises
:class:`WireError`, which subclasses :class:`ConnectionError` so it
lands in the router's ``TRANSPORT_ERRORS`` and feeds the PR 12
breaker/detector machinery like any other dead transport.

The payload codec round-trips exactly the structures the
:class:`~paddle_tpu.serving.fleet.replica.ReplicaHandle` surface
traffics in: numpy arrays (binary frames), tuples (preserved — a
quantized snapshot shard is a ``(kv, scales)`` tuple, not a list),
int-keyed dicts (``progress`` maps rid → tokens; JSON would silently
stringify the keys), ``bytes``, sets, and the
:class:`~paddle_tpu.serving.fleet.replica.FullReplay` marker the
``progress(since=)`` contract-hardening introduced (a full replay that
loses its marker in transit would be double-counted by the router).
Wall-clock timestamps are deliberately absent from the protocol:
heartbeat ages travel as the sender's **monotonic deltas**, never as
timestamps a receiver would subtract its own clock from (NTP steps
between hosts would mis-detect hangs).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.serving.engine import SlotMigrationError
from paddle_tpu.serving.fleet.faults import (ReplicaCrashed,
                                             ReplicaUnavailable)
from paddle_tpu.serving.fleet.replica import FullReplay
from paddle_tpu.serving.scheduler import (LoadShedError, REJECT_REASONS,
                                          Reject)

try:                                # optional accelerator, never required
    import msgpack                  # type: ignore
except ImportError:                 # pragma: no cover - env-dependent
    msgpack = None

MAGIC = b"PTNW"
WIRE_VERSION = 1
KIND_JSON = 1
KIND_MSGPACK = 2
KIND_BIN = 3
_HEADER = struct.Struct("!4sBBI")
HEADER_BYTES = _HEADER.size

# one frame is bounded: a runaway length prefix (corruption, a non-PTNW
# client) must fail fast instead of allocating gigabytes
DEFAULT_MAX_FRAME_BYTES = 1 << 28


class WireError(ConnectionError):
    """Protocol-level failure: bad magic/version, oversized or torn
    frame, checksum mismatch, peer gone mid-message. A
    :class:`ConnectionError` (→ ``OSError``) on purpose: the router
    already treats ``OSError`` as a transport failure, so a corrupt
    stream feeds the circuit breaker exactly like a refused connect."""


class RemoteError(RuntimeError):
    """A remote exception type this side has no class for; carries the
    remote type name + message so the failure is attributable."""


def default_codec() -> str:
    return "msgpack" if msgpack is not None else "json"


# -- payload codec ----------------------------------------------------------

def encode_payload(obj: Any) -> Tuple[Any, List[np.ndarray]]:
    """Lower ``obj`` to a codec-safe tree + the array buffers it
    references (in placeholder order)."""
    bufs: List[np.ndarray] = []

    def enc(x):
        if isinstance(x, np.ndarray):
            bufs.append(np.ascontiguousarray(x))
            return {"__buf__": len(bufs) - 1}
        if isinstance(x, (np.integer,)):
            return int(x)
        if isinstance(x, (np.floating,)):
            return float(x)
        if isinstance(x, (np.bool_,)):
            return bool(x)
        if isinstance(x, (bytes, bytearray)):
            return {"__bytes__": bytes(x).hex()}
        if isinstance(x, tuple):
            return {"__tuple__": [enc(v) for v in x]}
        if isinstance(x, FullReplay):
            return {"__full_replay__": [enc(v) for v in x]}
        if isinstance(x, (set, frozenset)):
            return {"__set__": sorted(enc(v) for v in x)}
        if isinstance(x, dict):
            if all(isinstance(k, str) and not k.startswith("__")
                   for k in x):
                return {k: enc(v) for k, v in x.items()}
            # int keys (progress maps) or reserved-prefix keys: JSON
            # would stringify/collide them — pair-encode instead
            return {"__map__": [[enc(k), enc(v)] for k, v in x.items()]}
        if isinstance(x, list):
            return [enc(v) for v in x]
        if x is None or isinstance(x, (bool, int, float, str)):
            return x
        raise TypeError(
            f"wire payload cannot carry {type(x).__name__}: {x!r}")

    return enc(obj), bufs


def decode_payload(obj: Any, bufs: List[np.ndarray]) -> Any:
    def dec(x):
        if isinstance(x, dict):
            if "__buf__" in x:
                return bufs[int(x["__buf__"])]
            if "__bytes__" in x:
                return bytes.fromhex(x["__bytes__"])
            if "__tuple__" in x:
                return tuple(dec(v) for v in x["__tuple__"])
            if "__full_replay__" in x:
                return FullReplay(dec(v) for v in x["__full_replay__"])
            if "__set__" in x:
                return frozenset(dec(v) for v in x["__set__"])
            if "__map__" in x:
                return {dec(k): dec(v) for k, v in x["__map__"]}
            return {k: dec(v) for k, v in x.items()}
        if isinstance(x, list):
            return [dec(v) for v in x]
        return x

    return dec(obj)


def _dumps(obj: Any, codec: str) -> Tuple[bytes, int]:
    if codec == "msgpack" and msgpack is not None:
        return msgpack.packb(obj, use_bin_type=True), KIND_MSGPACK
    return (json.dumps(obj, separators=(",", ":"),
                       allow_nan=True).encode("utf-8"), KIND_JSON)


def _loads(data: bytes, kind: int) -> Any:
    if kind == KIND_MSGPACK:
        if msgpack is None:
            raise WireError("peer sent a msgpack envelope but msgpack "
                            "is not importable here")
        return msgpack.unpackb(data, raw=False)
    return json.loads(data.decode("utf-8"))


def encode_message(payload: Any, *, codec: Optional[str] = None) -> bytes:
    """One full message as bytes: envelope frame + binary frames."""
    codec = codec or default_codec()
    body, bufs = encode_payload(payload)
    meta = []
    for a in bufs:
        raw = a.tobytes()
        meta.append({"dtype": str(a.dtype), "shape": list(a.shape),
                     "bytes": len(raw),
                     "sha256": hashlib.sha256(raw).hexdigest()})
    head, kind = _dumps({"v": WIRE_VERSION, "bufs": meta, "body": body},
                        codec)
    out = [_HEADER.pack(MAGIC, WIRE_VERSION, kind, len(head)), head]
    for a, m in zip(bufs, meta):
        raw = a.tobytes()
        out.append(_HEADER.pack(MAGIC, WIRE_VERSION, KIND_BIN, len(raw)))
        out.append(raw)
    return b"".join(out)


class MessageDecoder:
    """Incremental frame parser: ``feed(bytes)`` returns every message
    completed so far. Shared by the selectors-based servers (non-
    blocking reads land partial frames) and the blocking client (one
    recv can carry several pipelined responses)."""

    def __init__(self, max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self.max_frame_bytes = int(max_frame_bytes)
        self._buf = bytearray()
        self._head: Optional[Dict] = None   # envelope awaiting buffers
        self._bufs: List[np.ndarray] = []

    def feed(self, data: bytes) -> List[Any]:
        self._buf.extend(data)
        out = []
        while True:
            frame = self._next_frame()
            if frame is None:
                return out
            kind, raw = frame
            if self._head is None:
                if kind == KIND_BIN:
                    raise WireError("binary frame with no envelope")
                self._head = _loads(bytes(raw), kind)
                if self._head.get("v") != WIRE_VERSION:
                    raise WireError(
                        f"envelope version {self._head.get('v')!r}, "
                        f"want {WIRE_VERSION}")
                self._bufs = []
            else:
                if kind != KIND_BIN:
                    raise WireError(
                        "expected binary frame "
                        f"{len(self._bufs)}/{len(self._head['bufs'])}, "
                        f"got kind {kind}")
                m = self._head["bufs"][len(self._bufs)]
                if len(raw) != int(m["bytes"]):
                    raise WireError(
                        f"shard frame is {len(raw)}B, manifest says "
                        f"{m['bytes']}B")
                digest = hashlib.sha256(raw).hexdigest()
                if digest != m["sha256"]:
                    raise WireError(
                        f"shard checksum mismatch: {digest[:12]} != "
                        f"{m['sha256'][:12]} (torn or corrupt frame)")
                self._bufs.append(
                    np.frombuffer(bytes(raw), dtype=np.dtype(m["dtype"]))
                    .reshape(m["shape"]).copy())
            if self._head is not None \
                    and len(self._bufs) == len(self._head["bufs"]):
                head, bufs = self._head, self._bufs
                self._head, self._bufs = None, []
                out.append(decode_payload(head["body"], bufs))

    def _next_frame(self) -> Optional[Tuple[int, bytearray]]:
        if len(self._buf) < HEADER_BYTES:
            return None
        magic, ver, kind, length = _HEADER.unpack_from(self._buf)
        if magic != MAGIC:
            raise WireError(f"bad frame magic {bytes(magic)!r}")
        if ver != WIRE_VERSION:
            raise WireError(f"frame version {ver}, want {WIRE_VERSION}")
        if length > self.max_frame_bytes:
            raise WireError(f"frame of {length}B exceeds the "
                            f"{self.max_frame_bytes}B bound")
        if len(self._buf) < HEADER_BYTES + length:
            return None
        raw = self._buf[HEADER_BYTES:HEADER_BYTES + length]
        del self._buf[:HEADER_BYTES + length]
        return kind, raw


def recv_message(sock, decoder: MessageDecoder, pending: list) -> Any:
    """Blocking read until one full message is available. ``pending``
    holds messages a previous recv over-read (pipelined responses)."""
    while not pending:
        data = sock.recv(1 << 16)
        if not data:
            raise WireError("peer closed the connection mid-message")
        pending.extend(decoder.feed(data))
    return pending.pop(0)


# -- structured rejects / errors --------------------------------------------

def reject_to_wire(rej: Reject) -> Dict[str, Any]:
    return dataclasses.asdict(rej)


def reject_from_wire(d: Dict[str, Any]) -> Reject:
    rej = Reject(**d)
    if rej.reason not in REJECT_REASONS:
        # an unknown reason means the peer speaks a newer (or corrupted)
        # vocabulary — surface it as protocol drift, not a silent pass
        raise WireError(f"unknown Reject reason {rej.reason!r} "
                        f"(registered: {REJECT_REASONS})")
    return rej


# remote exception types this side re-raises as themselves; anything
# else comes back as RemoteError so the type name survives the wire
_ERROR_TYPES = {
    "LoadShedError": LoadShedError,
    "SlotMigrationError": SlotMigrationError,
    "ReplicaCrashed": ReplicaCrashed,
    "ReplicaUnavailable": ReplicaUnavailable,
    "ValueError": ValueError,
    "KeyError": KeyError,
    "TypeError": TypeError,
    "RuntimeError": RuntimeError,
    "NotImplementedError": NotImplementedError,
}


def error_to_wire(exc: BaseException) -> Dict[str, Any]:
    d: Dict[str, Any] = {"type": type(exc).__name__, "message": str(exc)}
    rej = getattr(exc, "reject", None)
    if isinstance(rej, Reject):
        d["reject"] = reject_to_wire(rej)
    return d


def error_from_wire(d: Dict[str, Any]) -> BaseException:
    t = d.get("type", "RemoteError")
    if t == "LoadShedError" and d.get("reject"):
        return LoadShedError(reject_from_wire(d["reject"]))
    cls = _ERROR_TYPES.get(t)
    if cls is not None:
        return cls(d.get("message", ""))
    return RemoteError(f"{t}: {d.get('message', '')}")
