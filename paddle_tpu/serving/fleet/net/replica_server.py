"""A serving replica in its own process, behind the wire protocol.

``ReplicaServer`` wraps one :class:`~paddle_tpu.serving.ServingEngine`
in a :class:`~paddle_tpu.serving.fleet.replica.LocalReplica` (reusing
its mutation lock, busy-time accounting and monotonic heartbeat) and
serves the full :class:`ReplicaHandle` surface as RPCs over a
``selectors`` event loop — single-threaded on purpose: every RPC is
serialized, so the engine sees exactly the interleaving an in-process
``LocalReplica`` would, and the byte-parity tests hold across the
socket.

Graceful shutdown follows the resilience preemption discipline
(:mod:`paddle_tpu.resilience.preempt`): SIGTERM/SIGINT flips the
replica to ``draining`` (the router stops routing to it and migrates
its queue), the server finishes what is in flight — self-stepping if
the router has already moved on — and exits with ``EXIT_DRAINED``.
``kill -9`` is the chaos case: the socket dies mid-frame, the client's
:class:`~paddle_tpu.serving.fleet.net.wire.WireError` feeds the
router's breaker/detector, and the redrive machinery takes over.

Run standalone (the process the fleet actually deploys)::

    python -m paddle_tpu.serving.fleet.net.replica_server \
        --config '{"vocab_size": 64, ...}' --engine '{"num_slots": 2}' \
        --seed 0 --port 0

The bound address is announced on stdout as ``PTNW_LISTENING host
port`` once warmup completes — :func:`spawn_replica_server` wraps the
spawn-and-wait dance for tests and the bench.
"""

from __future__ import annotations

import json
import os
import selectors
import signal
import socket
import sys
import time
from typing import Dict, Optional, Tuple

import numpy as np

from paddle_tpu.resilience.preempt import EXIT_DRAINED
from paddle_tpu.serving.fleet.net import wire
from paddle_tpu.serving.fleet.replica import LocalReplica


class _Conn:
    def __init__(self, sock, max_frame_bytes):
        self.sock = sock
        self.decoder = wire.MessageDecoder(max_frame_bytes)


class ReplicaServer:
    """Event-loop RPC server over one engine. ``serve_forever()`` runs
    the loop inline (the deployed process); ``serve_step()`` runs one
    poll iteration, which lets a test drive the server from a plain
    background thread and still join it deterministically."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 *, name: str = "net0",
                 max_frame_bytes: int = wire.DEFAULT_MAX_FRAME_BYTES,
                 codec: Optional[str] = None, clock=time.monotonic):
        self.replica = LocalReplica(engine, name=name, clock=clock)
        self.codec = codec or wire.default_codec()
        self.max_frame_bytes = int(max_frame_bytes)
        self._lsock = socket.create_server((host, int(port)))
        self._lsock.setblocking(False)
        self.address: Tuple[str, int] = self._lsock.getsockname()[:2]
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._lsock, selectors.EVENT_READ, None)
        self._conns: Dict[socket.socket, _Conn] = {}
        self.draining = False
        self._shutdown = False
        self.rpcs_total = 0

    # -- lifecycle ---------------------------------------------------------
    def install_signal_handlers(self):
        """SIGTERM/SIGINT → drain, not die: in-flight work finishes,
        the exit code says 'drained' so a launcher restarts without
        burning its crash budget."""
        signal.signal(signal.SIGTERM, self._on_term)
        signal.signal(signal.SIGINT, self._on_term)
        return self

    def _on_term(self, signum, frame):
        self.request_drain()

    def request_drain(self):
        self.draining = True
        self.replica.draining = True

    def serve_step(self, timeout: float = 0.05) -> int:
        """One poll iteration; returns the number of RPCs dispatched."""
        n = 0
        for key, _ in self._sel.select(timeout):
            if key.fileobj is self._lsock:
                self._accept()
            else:
                n += self._service(key.data)
        return n

    def serve_forever(self, poll_s: float = 0.05) -> int:
        """Loop until shutdown or drain-complete; returns the exit
        code (``EXIT_DRAINED`` after a graceful drain, 0 otherwise)."""
        while not self._shutdown:
            self.serve_step(poll_s)
            if self.draining:
                if not self.replica.idle() and not self._conns:
                    # the router is gone but work remains: self-step to
                    # completion rather than holding requests hostage
                    self.replica.step()
                if self.replica.idle():
                    self.close()
                    return EXIT_DRAINED
        self.close()
        return EXIT_DRAINED if self.draining else 0

    def close(self):
        for sock in list(self._conns):
            self._drop(sock)
        try:
            self._sel.unregister(self._lsock)
        except KeyError:
            pass
        self._lsock.close()
        self._sel.close()

    # -- socket plumbing ---------------------------------------------------
    def _accept(self):
        try:
            sock, _addr = self._lsock.accept()
        except OSError:
            return
        sock.setblocking(True)          # replies use blocking sendall
        sock.settimeout(30.0)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(sock, self.max_frame_bytes)
        self._conns[sock] = conn
        self._sel.register(sock, selectors.EVENT_READ, conn)

    def _drop(self, sock):
        try:
            self._sel.unregister(sock)
        except KeyError:
            pass
        self._conns.pop(sock, None)
        try:
            sock.close()
        except OSError:
            pass

    def _service(self, conn: _Conn) -> int:
        try:
            data = conn.sock.recv(1 << 16)
        except OSError:
            self._drop(conn.sock)
            return 0
        if not data:
            self._drop(conn.sock)
            return 0
        try:
            msgs = conn.decoder.feed(data)
        except wire.WireError:
            self._drop(conn.sock)   # a corrupt stream cannot be resynced
            return 0
        n = 0
        for msg in msgs:
            self._reply(conn, msg)
            n += 1
        return n

    def _reply(self, conn: _Conn, msg):
        mid = msg.get("id", 0) if isinstance(msg, dict) else 0
        try:
            if not isinstance(msg, dict) or "op" not in msg:
                raise ValueError(f"malformed request: {msg!r}")
            value = self._dispatch(msg["op"], msg.get("args") or {})
            resp = {"id": mid, "ok": True, "value": value}
        except Exception as e:      # the RPC failed, not the server
            resp = {"id": mid, "ok": False,
                    "error": wire.error_to_wire(e)}
        try:
            conn.sock.sendall(wire.encode_message(resp, codec=self.codec))
        except OSError:
            self._drop(conn.sock)

    # -- RPC surface: exactly ReplicaHandle --------------------------------
    def _dispatch(self, op: str, a: Dict):
        rep = self.replica
        self.rpcs_total += 1
        if op == "hello":
            return {"name": rep.name, "pid": os.getpid(),
                    "wire_version": wire.WIRE_VERSION,
                    "codec": self.codec,
                    "page_size": rep.page_size(),
                    "draining": self.draining}
        if op == "submit":
            if self.draining:
                # structurally refuse new work mid-drain; the router
                # reads this as a transport-unavailable and re-routes
                from paddle_tpu.serving.fleet.faults import \
                    ReplicaUnavailable
                raise ReplicaUnavailable(f"{rep.name} is draining")
            return rep.submit(
                np.asarray(a["prompt"], np.int32),
                int(a["max_new_tokens"]),
                None if a.get("eos_id") is None else int(a["eos_id"]),
                lane=a.get("lane", "default"),
                ttft_deadline_s=a.get("ttft_deadline_s"),
                trace_id=a.get("trace_id"))
        if op == "step":
            return {"results": rep.step()}
        if op == "health":
            # heartbeat_age_s inside is the replica's own MONOTONIC
            # delta — ages cross the wire as deltas, never timestamps
            h = dict(rep.health())
            h["draining"] = self.draining
            h["rpcs_total"] = self.rpcs_total
            return h
        if op == "prefix_digests":
            return sorted(rep.prefix_digests())
        if op == "can_accept":
            return bool(rep.can_accept(int(a["total_tokens"])))
        if op == "idle":
            return bool(rep.idle())
        if op == "result":
            return rep.result(int(a["rid"]))
        if op == "request_stats":
            return rep.request_stats(int(a["rid"]))
        if op == "progress":
            since = a.get("since")
            if since is not None:
                since = {int(k): int(v) for k, v in since.items()}
            return {"streams": rep.progress(since)}
        if op == "poll_checkpoints":
            return rep.poll_checkpoints()
        if op == "poll_handoffs":
            return rep.poll_handoffs()
        if op == "reject_reason":
            rej = rep.reject_reason(int(a["rid"]))
            return None if rej is None else wire.reject_to_wire(rej)
        if op == "drain_queue":
            return rep.drain_queue()
        if op == "snapshot_inflight":
            return rep.snapshot_inflight()
        if op == "restore":
            return rep.restore(a["snap"])
        if op == "export_prefix_pages":
            return rep.export_prefix_pages(
                [int(d) for d in a.get("digests", ())])
        if op == "import_prefix_pages":
            return rep.import_prefix_pages(a.get("bundle"))
        if op == "warmup":
            rep.warmup()
            return True
        if op == "postmortem":
            return rep.postmortem(a.get("reason", "remote"),
                                  trace_ids=tuple(a.get("trace_ids", ())))
        if op == "set_draining":
            if bool(a.get("draining", True)):
                self.request_drain()
            else:
                self.draining = False
                self.replica.draining = False
            return True
        if op == "shutdown":
            self._shutdown = True
            return True
        raise ValueError(f"unknown op {op!r}")


# -- standalone process entry ----------------------------------------------

def _build_engine(config: Dict, engine_kwargs: Dict, seed: int):
    import jax

    from paddle_tpu import observability as obs
    from paddle_tpu import serving
    from paddle_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig.tiny(**config)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(int(seed)))
    return serving.ServingEngine(model, params,
                                 registry=obs.MetricsRegistry(),
                                 **engine_kwargs)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--name", default="net0")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--config", default="{}",
                    help="GPTConfig.tiny(**...) overrides, JSON")
    ap.add_argument("--engine", default="{}",
                    help="ServingEngine kwargs, JSON")
    ap.add_argument("--codec", default=None,
                    choices=(None, "json", "msgpack"))
    ap.add_argument("--no-warmup", action="store_true")
    args = ap.parse_args(argv)

    engine = _build_engine(json.loads(args.config),
                           json.loads(args.engine), args.seed)
    server = ReplicaServer(engine, args.host, args.port, name=args.name,
                           codec=args.codec).install_signal_handlers()
    if not args.no_warmup:
        server.replica.warmup()     # announce only once routable
    print(f"PTNW_LISTENING {server.address[0]} {server.address[1]}",
          flush=True)
    return server.serve_forever()


def spawn_replica_server(*, config: Optional[Dict] = None,
                         engine: Optional[Dict] = None, seed: int = 0,
                         name: str = "net0", warmup: bool = True,
                         codec: Optional[str] = None,
                         env: Optional[Dict[str, str]] = None,
                         startup_timeout_s: float = 180.0):
    """Spawn ``replica_server`` as a real subprocess (CPU-pinned jax)
    and wait for its ``PTNW_LISTENING`` announcement; returns
    ``(subprocess.Popen, (host, port))``. The chaos battery gets its
    ``kill -9`` victims from here."""
    import select
    import subprocess

    cmd = [sys.executable, "-m",
           "paddle_tpu.serving.fleet.net.replica_server",
           "--config", json.dumps(config or {}),
           "--engine", json.dumps(engine or {}),
           "--seed", str(seed), "--name", name]
    if codec:
        cmd += ["--codec", codec]
    if not warmup:
        cmd += ["--no-warmup"]
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    if env:
        child_env.update(env)
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            env=child_env, text=True)
    deadline = time.monotonic() + startup_timeout_s
    line = ""
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica server {name} died during startup "
                f"(rc={proc.returncode})")
        ready, _, _ = select.select([proc.stdout], [], [], 0.5)
        if not ready:
            continue
        line = proc.stdout.readline()
        if line.startswith("PTNW_LISTENING"):
            _tag, host, port = line.split()
            return proc, (host, int(port))
    proc.kill()
    raise TimeoutError(
        f"replica server {name} never announced within "
        f"{startup_timeout_s}s (last line: {line!r})")


if __name__ == "__main__":
    sys.exit(main())
