"""Streaming front door: the client-facing edge of the fleet.

Clients connect over the :mod:`~paddle_tpu.serving.fleet.net.wire`
protocol and send ``generate`` requests; the front door routes them
through a :class:`~paddle_tpu.serving.fleet.router.FleetRouter` and
streams tokens back **incrementally** as they decode —
``FleetRouter.progress(frid)`` is the feed, which exists because the
router already polls every replica's emitted tokens each step for
crash redrive (``faults.enabled`` powers both; it is on by default).
One client request produces a frame sequence::

    accepted {rid}  →  tokens {rid, tokens[...]}*  →  finished {rid, tokens}
                    or  reject {rid?, reason, reject{...}}

Failure and overload are **structured, never a bare disconnect**:

- A router/engine shed surfaces as a ``reject`` frame carrying the
  full typed :class:`~paddle_tpu.serving.Reject` (reason, lane, queue
  depth, ``retry_after_s``).
- **Backpressure**: each connection's outbound buffer is bounded
  (``max_buffer_frames``). A reader that stops draining while decode
  keeps producing is *shed* — pending frames are dropped, one final
  ``reject(reason="slow_reader")`` frame is sent, and the connection
  closes. The fleet's decode slots are never held hostage by the
  slowest TCP receiver.

Every connection and request transition lands in a **crash-safe JSONL
netlog** (one line per event, flushed at the write): schema-tagged,
monotonic frame ids, and every accepted request terminated by exactly
one of ``finished`` / ``shed`` / ``redriven`` (``redriven`` = the
request outlived its connection or the front door's shutdown — it is
the router's redrive/replay machinery's responsibility from that line
on, not lost). ``tools/check_metrics_log.py --netlog`` validates the
log via :func:`validate_netlog_file`.

The loop is single-threaded and explicitly pumpable: ``pump()`` runs
one accept/read → ``router.step()`` → deliver cycle (the deterministic
test drive), ``start()``/``stop()`` wrap it in a daemon thread for the
bench and live serving.
"""

from __future__ import annotations

import json
import os
import selectors
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.analysis.concurrency import guarded_by
from paddle_tpu.serving.fleet.net import wire
from paddle_tpu.serving.scheduler import LoadShedError, Reject

NETLOG_SCHEMA = "paddle_tpu.netlog-v1"

NETLOG_EVENTS = frozenset({
    "listen", "conn_open", "conn_close", "accept", "reject",
    "stream", "finished", "shed", "redriven", "close"})

# netlog terminals: every accepted rid must hit exactly one
NETLOG_TERMINALS = frozenset({"finished", "shed", "redriven"})


class _ClientConn:
    def __init__(self, sock, cid: int, max_frame_bytes: int):
        self.sock = sock
        self.cid = cid
        self.decoder = wire.MessageDecoder(max_frame_bytes)
        self.outbox: "deque[bytes]" = deque()
        self.out_off = 0            # bytes of outbox[0] already sent
        self.rids: set = set()      # live frids owned by this conn
        self.tags: Dict[int, Any] = {}
        self.delivered: Dict[int, int] = {}   # frid -> tokens sent
        self.closing = False        # flush outbox, then close


@guarded_by("_netlog_lock", "_netlog", "_frame")
class FrontDoor:
    """Client-facing streaming server over one FleetRouter."""

    def __init__(self, router, host: str = "127.0.0.1", port: int = 0,
                 *, netlog_path: Optional[str] = None,
                 max_buffer_frames: int = 64,
                 max_frame_bytes: int = wire.DEFAULT_MAX_FRAME_BYTES,
                 codec: Optional[str] = None, registry=None):
        self.router = router
        self.codec = codec or wire.default_codec()
        self.max_buffer_frames = int(max_buffer_frames)
        self.max_frame_bytes = int(max_frame_bytes)
        from paddle_tpu import observability as obs
        self._reg = registry or obs.default()
        self._lsock = socket.create_server((host, int(port)))
        self._lsock.setblocking(False)
        self.address: Tuple[str, int] = self._lsock.getsockname()[:2]
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._lsock, selectors.EVENT_READ, None)
        self._conns: Dict[socket.socket, _ClientConn] = {}
        self._owner: Dict[int, _ClientConn] = {}   # frid -> conn
        self._conn_seq = 0
        self._netlog_lock = threading.Lock()
        self._frame = 0
        self._netlog = None
        self.netlog_path = netlog_path
        if netlog_path:
            d = os.path.dirname(os.path.abspath(netlog_path))
            os.makedirs(d, exist_ok=True)
            self._netlog = open(netlog_path, "a", encoding="utf-8")
        self.accepted_total = 0
        self.finished_total = 0
        self.shed_total = 0
        self.stream_frames_total = 0
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._closed = False
        self._log("listen", host=self.address[0], port=self.address[1])

    # -- netlog ------------------------------------------------------------
    def _log(self, event: str, **fields):
        """One JSONL line, flushed at the write — a ``kill -9`` of this
        process tears at most the line being written, never a committed
        one (the validator tolerates a torn FINAL line only). The lock
        makes a line and its frame id atomic across threads (pump loop
        vs. a closing owner): interleaved writers would tear interior
        lines and duplicate frame ids, both of which the validator
        treats as corruption."""
        with self._netlog_lock:
            if self._netlog is None:
                return
            rec = {"schema": NETLOG_SCHEMA, "frame": self._frame,
                   "ts": time.time(), "event": event}
            rec.update(fields)
            self._frame += 1
            self._netlog.write(json.dumps(rec, sort_keys=True) + "\n")
            self._netlog.flush()

    # -- health / exposition ----------------------------------------------
    def health(self) -> Dict[str, object]:
        return {"connections": len(self._conns),
                "accepted_total": self.accepted_total,
                "finished_total": self.finished_total,
                "shed_total": self.shed_total,
                "stream_frames_total": self.stream_frames_total,
                "live_requests": len(self._owner),
                "address": list(self.address)}

    def start_exposition(self, port: int = 0, host: str = "127.0.0.1"):
        """Operator plane for the whole edge: ``/healthz`` aggregates
        the front door and the fleet (degraded fleet → 503, as usual),
        ``/debug/postmortem`` serves the router's bundle ring."""
        from paddle_tpu import observability as obs
        srv = obs.ExpositionServer(registry=self._reg,
                                   tracer=self.router.tracer,
                                   port=port, host=host)
        srv.add_health("frontdoor", self.health)
        srv.add_health("fleet", self.router.health)
        srv.add_postmortem("fleet", self.router.postmortems)
        srv.add_json("/debug/netlog",
                     lambda: dict(self.health(),
                                  netlog_path=self.netlog_path))
        return srv.start()

    # -- lifecycle ---------------------------------------------------------
    def start(self, poll_s: float = 0.005) -> "FrontDoor":
        if self._thread is not None:
            raise RuntimeError("front door already started")
        self._stop_evt.clear()

        def loop():
            while not self._stop_evt.is_set():
                if not self.pump():
                    time.sleep(poll_s)

        self._thread = threading.Thread(target=loop, name="frontdoor",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._thread is not None:
            self._stop_evt.set()
            self._thread.join()
            self._thread = None

    def close(self):
        if self._closed:
            return
        self.stop()
        # requests still live at shutdown are the ROUTER's from here on
        # (its replay records and redrive machinery own them); the
        # netlog terminal says so explicitly — detached, not lost
        for conn in list(self._conns.values()):
            self._orphan(conn, "frontdoor_close")
            self._drop(conn)
        try:
            self._sel.unregister(self._lsock)
        except KeyError:
            pass
        self._lsock.close()
        self._sel.close()
        self._log("close")
        with self._netlog_lock:
            if self._netlog is not None:
                self._netlog.close()
                self._netlog = None
        self._closed = True

    # -- the pump ----------------------------------------------------------
    def pump(self) -> int:
        """One full cycle: accept/read sockets, step the fleet once if
        work is pending, deliver tokens/finishes/rejects, flush
        outboxes. Returns the number of frames delivered + requests
        accepted (0 = completely idle)."""
        work = self._pump_io()
        finished: Dict[int, np.ndarray] = {}
        if not self.router.idle():
            finished = self.router.step()
            work += 1
        work += self._deliver(finished)
        self._flush_all()
        return work

    def _pump_io(self) -> int:
        n = 0
        for key, _ in self._sel.select(0):
            if key.fileobj is self._lsock:
                self._accept()
            else:
                n += self._read(key.data)
        return n

    def _accept(self):
        try:
            sock, _addr = self._lsock.accept()
        except OSError:
            return
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._conn_seq += 1
        conn = _ClientConn(sock, self._conn_seq, self.max_frame_bytes)
        self._conns[sock] = conn
        self._sel.register(sock, selectors.EVENT_READ, conn)
        self._log("conn_open", conn=conn.cid)
        self._reg.gauge("frontdoor_connections",
                        "open front-door client connections").set(
                            len(self._conns))

    def _drop(self, conn: _ClientConn):
        try:
            self._sel.unregister(conn.sock)
        except KeyError:
            pass
        if self._conns.pop(conn.sock, None) is not None:
            self._log("conn_close", conn=conn.cid)
        for frid in list(conn.rids):
            self._owner.pop(frid, None)
        conn.rids.clear()
        try:
            conn.sock.close()
        except OSError:
            pass
        self._reg.gauge("frontdoor_connections",
                        "open front-door client connections").set(
                            len(self._conns))

    def _orphan(self, conn: _ClientConn, why: str):
        """Terminal-log every live request of a vanishing connection:
        the router keeps decoding it (and would redrive it through a
        crash), but nobody is listening — ``redriven`` in the netlog
        marks the handoff so the accounting never shows a lost rid."""
        for frid in list(conn.rids):
            self._log("redriven", rid=frid, conn=conn.cid, cause=why)
            self._owner.pop(frid, None)
        conn.rids.clear()

    def _read(self, conn: _ClientConn) -> int:
        try:
            data = conn.sock.recv(1 << 16)
        except BlockingIOError:
            return 0
        except OSError:
            self._orphan(conn, "conn_error")
            self._drop(conn)
            return 0
        if not data:
            self._orphan(conn, "conn_closed")
            self._drop(conn)
            return 0
        try:
            msgs = conn.decoder.feed(data)
        except wire.WireError:
            self._orphan(conn, "wire_error")
            self._drop(conn)
            return 0
        n = 0
        for msg in msgs:
            n += self._handle(conn, msg)
        return n

    def _handle(self, conn: _ClientConn, msg) -> int:
        if not isinstance(msg, dict) or msg.get("op") != "generate":
            self._send(conn, {"event": "reject", "rid": None,
                              "tag": None, "reason": "bad_request",
                              "detail": f"unsupported message {msg!r}"
                                        [:200]})
            return 1
        tag = msg.get("tag")
        lane = msg.get("lane", "default")
        try:
            prompt = np.asarray(msg["prompt"], np.int32).reshape(-1)
            frid = self.router.submit(
                prompt, int(msg.get("max_new_tokens", 32)),
                None if msg.get("eos_id") is None
                else int(msg["eos_id"]),
                lane=lane,
                ttft_deadline_s=msg.get("ttft_deadline_s"))
        except LoadShedError as e:
            # overload is an ANSWER, not a hangup: the typed verdict
            # (reason, queue depth, retry_after_s) goes to the client
            self._log("reject", conn=conn.cid, tag=tag,
                      reason=e.reject.reason)
            self._reg.counter(
                "frontdoor_rejects_total",
                "generate requests rejected at the front door").inc(
                    reason=e.reject.reason)
            self._send(conn, {"event": "reject", "rid": None,
                              "tag": tag, "reason": e.reject.reason,
                              "reject": wire.reject_to_wire(e.reject)})
            return 1
        except (ValueError, KeyError, TypeError) as e:
            self._send(conn, {"event": "reject", "rid": None,
                              "tag": tag, "reason": "bad_request",
                              "detail": f"{type(e).__name__}: {e}"})
            return 1
        conn.rids.add(frid)
        conn.tags[frid] = tag
        conn.delivered[frid] = 0
        self._owner[frid] = conn
        self.accepted_total += 1
        self._log("accept", rid=frid, conn=conn.cid, tag=tag, lane=lane,
                  prompt_tokens=int(prompt.shape[0]))
        self._reg.counter("frontdoor_requests_total",
                          "generate requests accepted").inc(lane=lane)
        self._send(conn, {"event": "accepted", "rid": frid, "tag": tag})
        return 1

    # -- delivery ----------------------------------------------------------
    def _deliver(self, finished: Dict[int, np.ndarray]) -> int:
        n = 0
        for frid, toks in finished.items():
            conn = self._owner.pop(frid, None)
            if conn is None:
                continue            # orphaned earlier; router owns it
            toks = [int(t) for t in np.asarray(toks).reshape(-1)]
            self.finished_total += 1
            self._log("finished", rid=frid, conn=conn.cid,
                      tokens=len(toks))
            self._send(conn, {"event": "finished", "rid": frid,
                              "tag": conn.tags.pop(frid, None),
                              "tokens": toks})
            conn.rids.discard(frid)
            conn.delivered.pop(frid, None)
            n += 1
        # post-acceptance sheds (redrive budget, deadline, engine TTFT
        # shed lifted by the router) — pop-on-read, typed all the way
        for frid, conn in list(self._owner.items()):
            rej = self.router.reject_reason(frid)
            if rej is None:
                continue
            self._owner.pop(frid, None)
            self.shed_total += 1
            self._log("shed", rid=frid, conn=conn.cid,
                      reason=rej.reason)
            self._reg.counter(
                "frontdoor_shed_total",
                "accepted requests shed, by reason").inc(
                    reason=rej.reason)
            self._send(conn, {"event": "reject", "rid": frid,
                              "tag": conn.tags.pop(frid, None),
                              "reason": rej.reason,
                              "reject": wire.reject_to_wire(rej)})
            conn.rids.discard(frid)
            conn.delivered.pop(frid, None)
            n += 1
        # incremental tokens for everything still decoding
        for frid, conn in list(self._owner.items()):
            obs = self.router.progress(frid)
            if obs is None:
                continue
            done = conn.delivered.get(frid, 0)
            if len(obs) <= done:
                continue
            tail = [int(t) for t in obs[done:]]
            conn.delivered[frid] = len(obs)
            self.stream_frames_total += 1
            self._log("stream", rid=frid, conn=conn.cid,
                      tokens=len(tail), total=len(obs))
            self._send(conn, {"event": "tokens", "rid": frid,
                              "tag": conn.tags.get(frid),
                              "tokens": tail})
            n += 1
        return n

    # -- outbound / backpressure ------------------------------------------
    def _send(self, conn: _ClientConn, payload: Dict):
        if conn.closing:
            return
        conn.outbox.append(wire.encode_message(payload, codec=self.codec))
        if len(conn.outbox) > self.max_buffer_frames:
            self._shed_slow_reader(conn)

    def _shed_slow_reader(self, conn: _ClientConn):
        """The reader stopped draining while decode kept producing:
        drop its queued frames, terminal-log every live request, send
        one final structured reject, close. Dropping BEFORE the final
        frame keeps the shed itself from blocking on the same full
        socket that caused it."""
        conn.outbox.clear()
        conn.out_off = 0
        rids = sorted(conn.rids)
        for frid in rids:
            self._owner.pop(frid, None)
            self.shed_total += 1
            self._log("shed", rid=frid, conn=conn.cid,
                      reason="slow_reader")
        self._reg.counter(
            "frontdoor_shed_total",
            "accepted requests shed, by reason").inc(
                reason="slow_reader", n=max(1, len(rids)))
        conn.rids.clear()
        conn.delivered.clear()
        rej = Reject("slow_reader", "default", len(rids), 0.0, 0.05)
        conn.outbox.append(wire.encode_message(
            {"event": "reject", "rid": None, "tag": None,
             "reason": "slow_reader", "rids": rids,
             "reject": wire.reject_to_wire(rej)}, codec=self.codec))
        conn.closing = True         # flush the verdict, then hang up

    def _flush_all(self):
        for conn in list(self._conns.values()):
            self._flush(conn)

    def _flush(self, conn: _ClientConn):
        while conn.outbox:
            buf = conn.outbox[0]
            try:
                sent = conn.sock.send(
                    memoryview(buf)[conn.out_off:])
            except BlockingIOError:
                return              # kernel buffer full: try next pump
            except OSError:
                self._orphan(conn, "conn_error")
                self._drop(conn)
                return
            conn.out_off += sent
            if conn.out_off >= len(buf):
                conn.outbox.popleft()
                conn.out_off = 0
        if conn.closing:
            self._drop(conn)


class FrontDoorClient:
    """Minimal blocking client for tests and the bench. Frames arrive
    as events; :meth:`generate` runs one request to completion and
    reports how many partial (``tokens``) deliveries it observed —
    the streaming acceptance number."""

    def __init__(self, address: Tuple[str, int], *,
                 timeout_s: float = 60.0, codec: Optional[str] = None):
        self.sock = socket.create_connection(
            (address[0], int(address[1])), timeout=timeout_s)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.codec = codec or wire.default_codec()
        self._decoder = wire.MessageDecoder()
        self._pending: list = []

    def send_generate(self, prompt, max_new_tokens: int = 32,
                      eos_id: Optional[int] = None, *,
                      lane: str = "default",
                      ttft_deadline_s: Optional[float] = None,
                      tag=None):
        self.sock.sendall(wire.encode_message(
            {"op": "generate",
             "prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
             "max_new_tokens": int(max_new_tokens),
             "eos_id": None if eos_id is None else int(eos_id),
             "lane": lane, "ttft_deadline_s": ttft_deadline_s,
             "tag": tag}, codec=self.codec))

    def next_event(self, timeout: Optional[float] = None) -> Dict:
        if timeout is not None:
            self.sock.settimeout(timeout)
        return wire.recv_message(self.sock, self._decoder, self._pending)

    def generate(self, prompt, max_new_tokens: int = 32,
                 eos_id: Optional[int] = None, *, lane: str = "default",
                 ttft_deadline_s: Optional[float] = None, tag=None,
                 timeout_s: float = 120.0) -> Dict[str, Any]:
        """Send one request; block until it finishes or rejects.
        Returns ``{"rid", "tokens", "partials", "ttft_s", "reject"}``
        (``tokens`` is None on reject; ``ttft_s`` is wall time from
        send to the first streamed token)."""
        self.send_generate(prompt, max_new_tokens, eos_id, lane=lane,
                           ttft_deadline_s=ttft_deadline_s, tag=tag)
        t0 = time.monotonic()
        rid, partials, ttft = None, 0, None
        streamed: List[int] = []
        deadline = t0 + timeout_s
        while True:
            ev = self.next_event(timeout=max(0.01,
                                             deadline - time.monotonic()))
            kind = ev.get("event")
            if kind == "accepted":
                rid = ev["rid"]
            elif kind == "tokens":
                if ttft is None:
                    ttft = time.monotonic() - t0
                partials += 1
                streamed.extend(int(t) for t in ev["tokens"])
            elif kind == "finished":
                return {"rid": ev["rid"], "tag": ev.get("tag"),
                        "tokens": [int(t) for t in ev["tokens"]],
                        "streamed": streamed, "partials": partials,
                        "ttft_s": ttft, "reject": None}
            elif kind == "reject":
                return {"rid": ev.get("rid"), "tag": ev.get("tag"),
                        "tokens": None, "streamed": streamed,
                        "partials": partials, "ttft_s": ttft,
                        "reject": ev.get("reject")
                        or {"reason": ev.get("reason")}}
            else:
                raise wire.WireError(f"unexpected event {ev!r}")

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


# -- netlog validation ------------------------------------------------------

def validate_netlog_file(path: str, *, require_requests: int = 0
                         ) -> Dict[str, int]:
    """Validate a front-door netlog: schema tag on every line, strictly
    monotonic frame ids, known events, and the no-silent-loss ledger —
    every ``accept``ed rid terminated by exactly one of ``finished`` /
    ``shed`` / ``redriven``. A torn FINAL line (the process died mid-
    write) is tolerated; a torn interior line is corruption. Raises
    ``ValueError`` with a precise message; returns a summary dict."""

    def fail(msg):
        raise ValueError(f"netlog {path}: {msg}")

    with open(path, "r", encoding="utf-8") as f:
        raw = f.read().split("\n")
    if raw and raw[-1] == "":
        raw.pop()
    recs: List[Dict] = []
    for i, line in enumerate(raw):
        try:
            recs.append(json.loads(line))
        except ValueError:
            if i == len(raw) - 1:
                break               # torn final line: crash mid-write
            fail(f"line {i + 1} is not JSON: {line[:80]!r}")
    if not recs:
        fail("empty log")
    last_frame = -1
    accepted: Dict[int, int] = {}   # rid -> terminal count
    counts = {"accept": 0, "finished": 0, "shed": 0, "redriven": 0,
              "reject": 0, "stream": 0}
    for i, r in enumerate(recs):
        if not isinstance(r, dict):
            fail(f"line {i + 1} is {type(r).__name__}, not an object")
        if r.get("schema") != NETLOG_SCHEMA:
            fail(f"line {i + 1} schema is {r.get('schema')!r}, "
                 f"expected {NETLOG_SCHEMA!r}")
        ev = r.get("event")
        if ev not in NETLOG_EVENTS:
            fail(f"line {i + 1} has unknown event {ev!r}")
        frame = r.get("frame")
        if not isinstance(frame, int) or isinstance(frame, bool):
            fail(f"line {i + 1} frame is {frame!r}, want int")
        if frame <= last_frame:
            fail(f"line {i + 1} frame {frame} not monotonic "
                 f"(previous {last_frame})")
        last_frame = frame
        if not isinstance(r.get("ts"), (int, float)):
            fail(f"line {i + 1} missing numeric ts")
        if ev in counts:
            counts[ev] += 1
        if ev == "accept":
            rid = r.get("rid")
            if not isinstance(rid, int):
                fail(f"line {i + 1} accept without int rid")
            if rid in accepted:
                fail(f"line {i + 1} rid {rid} accepted twice")
            accepted[rid] = 0
        elif ev in NETLOG_TERMINALS:
            rid = r.get("rid")
            if not isinstance(rid, int):
                fail(f"line {i + 1} {ev} without int rid")
            if rid not in accepted:
                fail(f"line {i + 1} {ev} for rid {rid} never accepted")
            accepted[rid] += 1
            if accepted[rid] > 1:
                fail(f"line {i + 1} rid {rid} terminated twice")
    dangling = sorted(r for r, n in accepted.items() if n == 0)
    if dangling:
        fail(f"accepted rids with no terminal: {dangling[:8]}"
             f"{'...' if len(dangling) > 8 else ''} "
             f"({len(dangling)} total)")
    if len(accepted) < require_requests:
        fail(f"only {len(accepted)} accepted requests, "
             f"required >= {require_requests}")
    counts["accepted_requests"] = len(accepted)
    counts["lines"] = len(recs)
    return counts
