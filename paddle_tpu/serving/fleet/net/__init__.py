"""Network serving: process-isolated replicas + a streaming front door.

The fleet's PR 9 contract made the router speak only
:class:`~paddle_tpu.serving.fleet.replica.ReplicaHandle`; this package
cashes that in. :mod:`wire` is a length-prefixed framed protocol
(msgpack/JSON envelopes, sha256-checksummed binary frames);
:mod:`replica_server` runs one ``ServingEngine`` in its own process
behind that protocol; :class:`NetReplica` is the client-side handle
the router drives exactly like a ``LocalReplica`` — breakers, redrive
and migration included, zero router forks. :class:`FrontDoor` is the
client-facing edge: it routes ``generate`` requests through a
``FleetRouter`` and streams tokens incrementally with bounded
per-connection buffers and structured rejects.
"""

from paddle_tpu.serving.fleet.net.frontdoor import (NETLOG_SCHEMA,
                                                    FrontDoor,
                                                    FrontDoorClient,
                                                    validate_netlog_file)
from paddle_tpu.serving.fleet.net.replica import (DEFAULT_CONNECT_RETRY,
                                                  NetReplica)
from paddle_tpu.serving.fleet.net.replica_server import (
    ReplicaServer, spawn_replica_server)
from paddle_tpu.serving.fleet.net.wire import (MessageDecoder, RemoteError,
                                               WireError, decode_payload,
                                               default_codec,
                                               encode_message,
                                               encode_payload,
                                               error_from_wire,
                                               error_to_wire,
                                               reject_from_wire,
                                               reject_to_wire)

__all__ = [
    "NETLOG_SCHEMA",
    "FrontDoor",
    "FrontDoorClient",
    "validate_netlog_file",
    "DEFAULT_CONNECT_RETRY",
    "NetReplica",
    "ReplicaServer",
    "spawn_replica_server",
    "MessageDecoder",
    "RemoteError",
    "WireError",
    "decode_payload",
    "default_codec",
    "encode_message",
    "encode_payload",
    "error_from_wire",
    "error_to_wire",
    "reject_from_wire",
    "reject_to_wire",
]
