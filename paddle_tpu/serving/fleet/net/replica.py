"""NetReplica: the ReplicaHandle a socket implements.

The PR 9 contract — "a process/HTTP transport can slot in without
touching the router" — cashes out here: :class:`NetReplica` speaks the
:mod:`~paddle_tpu.serving.fleet.net.wire` protocol to a
:class:`~paddle_tpu.serving.fleet.net.replica_server.ReplicaServer`
in another process and presents *exactly* the
:class:`~paddle_tpu.serving.fleet.replica.ReplicaHandle` surface. The
router cannot tell it apart from a :class:`LocalReplica`, so every
fleet behavior (routing, breakers, redrive, migration) works over the
socket with zero router forks.

Failure discipline:

- **Connect/reconnect** goes through ``resilience.retry_call`` with
  exponential backoff — a replica process still warming up is a
  retryable condition, not an error.
- **Calls** are covered by a per-call deadline (``settimeout``); a
  timeout or any socket error **drops the connection** before raising.
  Dropping is load-bearing: a late response to a timed-out call would
  otherwise be mis-paired with the next request — killing the socket
  kills the stale stream, and request/response ids are checked anyway.
- Raised transport failures are ``OSError``/``TimeoutError`` shaped
  (``WireError`` subclasses ``ConnectionError``), which is precisely
  the router's ``TRANSPORT_ERRORS`` tuple — a refused connect or a
  ``kill -9``'d peer feeds the PR 12 ``FailureDetector`` /
  ``CircuitBreaker`` as one more consecutive transport failure,
  unchanged.
- **Postmortem** falls back to a client-side flight recorder: the
  usual dump trigger is the *remote end dying*, when the RPC cannot
  succeed. Health snapshots are noted on every successful ``health()``
  call, so the client-side bundle carries the victim's last-known
  trajectory plus the transport error that ended it.
"""

from __future__ import annotations

import socket
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.observability.flight import FlightRecorder
from paddle_tpu.resilience.retry import RetryPolicy, retry_call
from paddle_tpu.serving.fleet.net import wire
from paddle_tpu.serving.fleet.replica import ReplicaHandle

DEFAULT_CONNECT_RETRY = RetryPolicy(
    max_attempts=6, base_delay_s=0.05, max_delay_s=1.0,
    deadline_s=30.0, retry_on=(OSError, TimeoutError))


class NetReplica(ReplicaHandle):
    """Client-side ReplicaHandle over one socket connection."""

    def __init__(self, address: Tuple[str, int], *,
                 name: Optional[str] = None,
                 connect_timeout_s: float = 5.0,
                 call_timeout_s: float = 60.0,
                 retry: RetryPolicy = DEFAULT_CONNECT_RETRY,
                 codec: Optional[str] = None,
                 registry=None, sleep=time.sleep):
        self.address = (address[0], int(address[1]))
        self.connect_timeout_s = float(connect_timeout_s)
        self.call_timeout_s = float(call_timeout_s)
        self.retry = retry
        self.codec = codec or wire.default_codec()
        self._sleep = sleep
        self._sock: Optional[socket.socket] = None
        self._decoder = wire.MessageDecoder()
        self._pending: list = []
        self._next_id = 0
        self.draining = False
        self.calls_total = 0
        self.reconnects_total = 0
        self._page_size: Optional[int] = None
        self.remote_pid: Optional[int] = None
        # the client-side black box: health trajectories noted here are
        # all that survives the remote process being kill -9'd
        self.flight = FlightRecorder(
            name=name or f"net:{self.address[0]}:{self.address[1]}",
            registry=registry)
        self._last_transport_error: Optional[str] = None
        self.name = name or self.flight.name
        self.connect()

    # -- transport ---------------------------------------------------------
    def connect(self) -> "NetReplica":
        """(Re)connect with backoff and re-run the hello handshake."""
        self._drop()

        def _dial():
            s = socket.create_connection(self.address,
                                         timeout=self.connect_timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return s

        self._sock = retry_call(_dial, policy=self.retry,
                                op=f"net_connect:{self.name}",
                                sleep=self._sleep)
        self._decoder = wire.MessageDecoder()
        self._pending = []
        self.reconnects_total += 1
        hello = self._call("hello", {})
        if hello.get("wire_version") != wire.WIRE_VERSION:
            self._drop()
            raise wire.WireError(
                f"server wire version {hello.get('wire_version')!r}, "
                f"client speaks {wire.WIRE_VERSION}")
        self._page_size = int(hello["page_size"])
        self.remote_pid = hello.get("pid")
        self.draining = bool(hello.get("draining", False))
        if self.name.startswith("net:") and hello.get("name"):
            self.name = self.flight.name = str(hello["name"])
        return self

    def connected(self) -> bool:
        return self._sock is not None

    def _drop(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._pending = []

    def _call(self, op: str, args: Dict,
              timeout: Optional[float] = None):
        """One RPC. Transport failures close the socket then raise —
        the caller (usually the router) sees an ``OSError``-shaped
        exception and charges it to the breaker."""
        if self._sock is None:
            self.connect()      # lazy reconnect after a failed call
        sock = self._sock
        self.calls_total += 1
        mid = self._next_id = self._next_id + 1
        try:
            sock.settimeout(self.call_timeout_s
                            if timeout is None else timeout)
            sock.sendall(wire.encode_message(
                {"id": mid, "op": op, "args": args}, codec=self.codec))
            resp = wire.recv_message(sock, self._decoder, self._pending)
        except (OSError, TimeoutError) as e:
            # the connection is now ambiguous (a late reply would pair
            # with the wrong request) — kill it so reconnect starts clean
            self._last_transport_error = f"{type(e).__name__}: {e}"
            self._drop()
            raise
        if resp.get("id") != mid:
            self._last_transport_error = (
                f"response id {resp.get('id')!r} != request {mid}")
            self._drop()
            raise wire.WireError(self._last_transport_error)
        if resp.get("ok"):
            return resp.get("value")
        raise wire.error_from_wire(resp.get("error") or {})

    # -- ReplicaHandle surface ---------------------------------------------
    def page_size(self) -> int:
        if self._page_size is None:
            self.connect()
        return int(self._page_size)

    def submit(self, prompt, max_new_tokens: int,
               eos_id: Optional[int] = None, *, lane: str = "default",
               ttft_deadline_s: Optional[float] = None,
               trace_id: Optional[int] = None) -> int:
        return int(self._call("submit", {
            "prompt": np.asarray(prompt, np.int32),
            "max_new_tokens": int(max_new_tokens),
            "eos_id": None if eos_id is None else int(eos_id),
            "lane": lane, "ttft_deadline_s": ttft_deadline_s,
            "trace_id": trace_id}))

    def step(self) -> Dict[int, np.ndarray]:
        out = self._call("step", {})
        return {int(r): np.asarray(a) for r, a in out["results"].items()}

    def health(self) -> Dict[str, object]:
        h = self._call("health", {})
        # heartbeat_age_s arrived as the REMOTE host's monotonic delta;
        # pass it through untouched (never re-derive from local clocks)
        self.draining = bool(h.get("draining", False))
        self.flight.note(h)
        return h

    def prefix_digests(self) -> frozenset:
        return frozenset(int(d) for d in self._call("prefix_digests", {}))

    def can_accept(self, total_tokens: int) -> bool:
        if self.draining:
            return False
        return bool(self._call("can_accept",
                               {"total_tokens": int(total_tokens)}))

    def idle(self) -> bool:
        return bool(self._call("idle", {}))

    def result(self, rid: int) -> Optional[np.ndarray]:
        out = self._call("result", {"rid": int(rid)})
        return None if out is None else np.asarray(out)

    def request_stats(self, rid: int) -> Optional[Dict[str, float]]:
        return self._call("request_stats", {"rid": int(rid)})

    def progress(self, since: Optional[Dict[int, int]] = None
                 ) -> Dict[int, List[int]]:
        out = self._call("progress", {"since": since})
        # FullReplay markers survive decode_payload; keep them intact
        return {int(r): v for r, v in out["streams"].items()}

    def poll_checkpoints(self) -> List[Tuple[int, Dict]]:
        return [(int(r), snap)
                for r, snap in self._call("poll_checkpoints", {})]

    def poll_handoffs(self) -> List[Tuple[int, Dict]]:
        return [(int(r), snap)
                for r, snap in self._call("poll_handoffs", {})]

    def reject_reason(self, rid: int):
        out = self._call("reject_reason", {"rid": int(rid)})
        return None if out is None else wire.reject_from_wire(out)

    def drain_queue(self) -> List[Tuple]:
        return [tuple(item) for item in self._call("drain_queue", {})]

    def snapshot_inflight(self) -> List[Tuple[int, Dict]]:
        return [(int(r), snap)
                for r, snap in self._call("snapshot_inflight", {})]

    def restore(self, snap: Dict, *, parent_span=None) -> int:
        # parent_span is a live tracer handle — process-local by nature,
        # so it does not cross the wire
        return int(self._call("restore", {"snap": snap}))

    def export_prefix_pages(self, digests) -> Optional[Dict]:
        return self._call("export_prefix_pages",
                          {"digests": [int(d) for d in digests]})

    def import_prefix_pages(self, bundle) -> int:
        return int(self._call("import_prefix_pages", {"bundle": bundle}))

    def warmup(self):
        # warmup compiles every (bucket, batch) shape — minutes on a
        # real accelerator, so it gets its own generous deadline
        self._call("warmup", {},
                   timeout=max(self.call_timeout_s, 600.0))
        return self

    def postmortem(self, reason: str, trace_ids=()) -> Optional[Dict]:
        try:
            bundle = self._call("postmortem",
                                {"reason": reason,
                                 "trace_ids": list(trace_ids)})
            if bundle is not None:
                return bundle
        except (OSError, TimeoutError, wire.RemoteError):
            pass        # the usual case: we are here BECAUSE it died
        # client-side testimony: last noted health trajectory + the
        # transport error that ended the relationship
        return self.flight.dump(
            reason, trace_ids=trace_ids,
            extra={"remote": False, "address": list(self.address),
                   "transport_error": self._last_transport_error or ""})

    # -- remote lifecycle --------------------------------------------------
    def request_drain(self, draining: bool = True) -> bool:
        """Flip the remote server's draining flag (the soft half of the
        SIGTERM discipline, reachable without process signals)."""
        ok = bool(self._call("set_draining", {"draining": draining}))
        self.draining = draining
        return ok

    def shutdown_server(self) -> bool:
        """Ask the remote process to exit its serve loop."""
        try:
            return bool(self._call("shutdown", {}))
        finally:
            self._drop()

    def close(self):
        # closes the CLIENT socket only — the remote replica keeps
        # serving (other routers may hold connections); use
        # shutdown_server() to take the process down
        self._drop()
