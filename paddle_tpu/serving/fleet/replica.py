"""Replica handles: the engine-as-cattle interface the fleet fronts.

The router never touches a :class:`~paddle_tpu.serving.ServingEngine`
directly — it speaks :class:`ReplicaHandle`, a small surface (submit /
step / health / prefix digests / snapshot / restore) that an
in-process threaded replica implements today and a process- or
HTTP-backed transport can implement later without the router changing.

:class:`LocalReplica` is the CI transport: it owns one engine, steps it
either synchronously (the router's deterministic drive mode — the
migration byte-parity tests need reproducible interleavings) or on its
own background thread (``start()``/``stop()``), and tracks per-replica
busy time so the bench can compute the fleet's critical path as if
every replica had its own accelerator.

Draining a replica is **migration, not kill**: ``drain_queue()`` hands
back the not-yet-admitted requests for resubmission elsewhere, and
``snapshot_inflight()`` walks the active slots through
``engine.snapshot_slot`` (sha256-per-page shard manifests — the
resilience transfer discipline) so peers can
``restore()`` them and resume decode byte-identically.
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.analysis.concurrency import guarded_by

if TYPE_CHECKING:       # annotation only — no runtime import cycle
    from paddle_tpu.serving.engine import ServingEngine


class FullReplay(list):
    """A progress stream re-sent from offset 0.

    ``progress(since=)`` normally returns only the tokens past the
    caller's cursor. When the cursor is stale — negative, or past the
    end of what the replica actually holds (a restore rewound the
    stream, or the caller's bookkeeping desynced) — raising would turn
    one confused poll into a dead replica, and silently returning an
    empty (or negative-index!) slice would corrupt the router's replay
    record. Instead the replica answers with the FULL stream wrapped in
    this marker; a consumer REPLACES its record rather than extending
    it. The marker survives the wire protocol (``net.wire`` encodes it
    explicitly) so the semantics hold across a socket."""

    full_replay = True


class ReplicaHandle:
    """Transport interface between router and replica. Every method is
    host-side and cheap except ``step()`` (one engine iteration).
    Implementations must make ``health()`` safe to call from the
    router's thread while ``step()`` runs."""

    name: str = "replica"
    draining: bool = False

    def page_size(self) -> int:
        """KV page size — the router needs it to compute page-aligned
        prefix digests with the replicas' own alignment."""
        raise NotImplementedError

    def submit(self, prompt, max_new_tokens: int,
               eos_id: Optional[int] = None, *, lane: str = "default",
               ttft_deadline_s: Optional[float] = None,
               trace_id: Optional[int] = None) -> int:
        raise NotImplementedError

    def step(self) -> Dict[int, np.ndarray]:
        raise NotImplementedError

    def health(self) -> Dict[str, object]:
        raise NotImplementedError

    def prefix_digests(self) -> frozenset:
        """Published full-page prefix digests this replica can map
        copy-free (the router's cache-locality signal)."""
        raise NotImplementedError

    def can_accept(self, total_tokens: int) -> bool:
        raise NotImplementedError

    def idle(self) -> bool:
        raise NotImplementedError

    def result(self, rid: int) -> Optional[np.ndarray]:
        raise NotImplementedError

    def request_stats(self, rid: int) -> Optional[Dict[str, float]]:
        raise NotImplementedError

    def progress(self, since: Optional[Dict[int, int]] = None
                 ) -> Dict[int, List[int]]:
        """Tokens emitted so far per in-flight request (``{rid:
        [tokens]}``). The router polls this every step so a crash
        never takes the emitted prefix with it — the cold-redrive path
        resubmits ``prompt + observed`` to a peer. ``since`` maps rid →
        token count the caller already holds; only the tokens past
        that index come back (the poll then costs O(new tokens) per
        step instead of re-copying whole streams). A stale or
        out-of-range ``since`` cursor gets the full stream back as a
        :class:`FullReplay` (replace, don't extend) instead of an
        exception or a bogus slice. Transports without progress export
        return ``{}`` (redrive then re-decodes from the prompt; greedy
        determinism keeps outputs identical)."""
        return {}

    def poll_checkpoints(self) -> List[Tuple[int, Dict]]:
        """Drain the replica's micro-checkpoint outbox (``(rid,
        snapshot)`` pairs — see ``ServingEngine.poll_micro_snapshots``).
        The router keeps the newest per request as the warm-restore
        seed that bounds re-decode work after a crash."""
        return []

    def poll_handoffs(self) -> List[Tuple[int, Dict]]:
        """Drain a prefill-tier replica's handoff outbox (``(rid,
        snapshot)`` pairs — see ``ServingEngine.poll_handoffs``): every
        parked prefill-done slot, snapshotted in the migration transfer
        format and already released. The two-tier router streams each
        snapshot to a decode-tier peer's ``restore``. Empty on
        non-prefill replicas."""
        return []

    def reject_reason(self, rid: int):
        """Structured reject for a request the replica's own engine
        shed after queueing (TTFT deadline expired before admission);
        None otherwise. The router polls this so an engine-side shed
        surfaces as a fleet-level structured reject instead of a
        silently-lost request."""
        return None

    def drain_queue(self) -> List[Tuple]:
        """Pop every queued (not yet admitted) request; returns
        ``(rid, prompt, max_new_tokens, eos_id, lane, ttft_deadline_s)``
        tuples for the router to resubmit on peers."""
        raise NotImplementedError

    def snapshot_inflight(self) -> List[Tuple[int, Dict]]:
        """Snapshot-and-release every active slot; returns
        ``(old_rid, snapshot)`` pairs ready for a peer's ``restore``."""
        raise NotImplementedError

    def restore(self, snap: Dict, *, parent_span=None) -> int:
        raise NotImplementedError

    def export_prefix_pages(self, digests) -> Optional[Dict]:
        """Package the leading run of ``digests`` this replica holds as
        a prefix-page bundle (hash-chained, per-(page, tp-shard) sha256
        shards) for a peer's :meth:`import_prefix_pages`. Transports
        without page export return None — the router degrades to local
        re-prefill, never an error."""
        return None

    def import_prefix_pages(self, bundle) -> int:
        """Install a peer's exported prefix pages into this replica's
        published index (verified all-or-nothing). Returns pages
        installed; transports without page import install nothing."""
        return 0

    def warmup(self):
        raise NotImplementedError

    def postmortem(self, reason: str, trace_ids=()) -> Optional[Dict]:
        """Dump the replica's flight-recorder black box as a postmortem
        bundle (``observability.flight``). Called by the router on
        eject / breaker-open / shed spikes — AFTER the failure, so
        implementations must not require a live engine loop. Transports
        without a flight recorder return None."""
        return None

    def close(self):
        pass


@guarded_by("_lock", "engine")
class LocalReplica(ReplicaHandle):
    """In-process replica over one :class:`ServingEngine`.

    Synchronous mode (default): the router calls :meth:`step` — fully
    deterministic, the mode every parity test runs. Threaded mode:
    :meth:`start` spawns a loop calling ``step()`` whenever work is
    pending (idle-backoff otherwise); finished results accumulate in a
    bounded engine-side store exactly as in synchronous mode, and
    ``health()`` stays safe because the engine publishes snapshots.

    ``engine`` is ``@guarded_by("_lock")``: in threaded mode the router
    submits/polls from its thread while the loop steps, so every engine
    access that can mutate or observe mutable engine state goes through
    ``self._lock``. The deliberate lock-free exceptions — ``health()``
    (engine-published snapshots), ``page_size()``/``can_accept()``
    (immutable config), ``postmortem()`` (must testify after the loop
    died) — are committed with rationale in the suppression file.
    """

    def __init__(self, engine: "ServingEngine", name: str = "replica0",
                 clock=time.monotonic):
        self.engine = engine
        self.name = name
        # the black box carries the replica's fleet name so a fleet-wide
        # /debug/postmortem endpoint can attribute bundles
        flight = getattr(engine, "flight", None)
        if flight is not None:
            flight.name = name
        self.busy_s = 0.0           # wall time inside step() — the
        self.steps = 0              # bench's per-accelerator cost model
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.draining = False
        # involuntary-failure surface: the background loop records its
        # own death here (health()/running() expose it, the router's
        # detector acts on it), and every step beats the heartbeat the
        # hang detector ages. The clock MUST be monotonic-shaped: the
        # age is a delta, and a wall clock here would let an NTP step
        # fabricate (or hide) a hang — load-bearing once the age
        # crosses a socket, where the remote host's wall clock is not
        # even the same clock.
        self.failed = False
        self.last_error: Optional[str] = None
        self._clock = clock
        self._last_beat = clock()
        # serializes engine MUTATIONS (submit vs step vs migration)
        # for threaded mode — a router-thread submit must not mutate
        # the scheduler queue mid-iteration. health() stays lock-free:
        # the engine publishes snapshots for exactly that reason.
        self._lock = threading.RLock()

    # -- request surface ---------------------------------------------------
    def submit(self, prompt, max_new_tokens: int,
               eos_id: Optional[int] = None, *, lane: str = "default",
               ttft_deadline_s: Optional[float] = None,
               trace_id: Optional[int] = None) -> int:
        with self._lock:
            # answering a submit IS a heartbeat: a sync-mode replica
            # only beats when stepped, and the first probe after a
            # long warmup must not read the gap as a hang
            self._last_beat = self._clock()
            return self.engine.submit(prompt, max_new_tokens, eos_id,
                                      lane=lane,
                                      ttft_deadline_s=ttft_deadline_s,
                                      trace_id=trace_id)

    def step(self) -> Dict[int, np.ndarray]:
        t0 = self._clock()
        with self._lock:
            out = self.engine.step()
        now = self._clock()
        self.busy_s += now - t0
        self.steps += 1
        self._last_beat = now
        return out

    def health(self) -> Dict[str, object]:
        h = dict(self.engine.health())
        h["heartbeat_age_s"] = self._clock() - self._last_beat
        h["failed"] = self.failed
        if self.last_error is not None:
            h["last_error"] = self.last_error
        return h

    def page_size(self) -> int:
        return self.engine.cache.config.page_size

    def prefix_digests(self) -> frozenset:
        with self._lock:
            # advertised_digests walks the cache's digest map AND the
            # host spill pool, which step()'s page commits mutate —
            # same race as result(). Spilled pages count: they restore
            # on the next local hit and export to fetching peers
            return self.engine.cache.advertised_digests()

    def can_accept(self, total_tokens: int) -> bool:
        return (not self.draining
                and self.engine.cache.config.pages_for(total_tokens)
                <= self.engine.cache.config.max_pages_per_slot)

    def idle(self) -> bool:
        # reads the scheduler's queue/slot state, which a concurrent
        # step() mutates — cheap enough to take the lock every poll
        with self._lock:
            return self.engine.scheduler.idle()

    def result(self, rid: int) -> Optional[np.ndarray]:
        with self._lock:
            # pop-on-read from the engine's bounded result store — a
            # mutation, not a snapshot read, so it needs the lock
            return self.engine.result(rid)

    def request_stats(self, rid: int) -> Optional[Dict[str, float]]:
        with self._lock:
            return self.engine.request_stats(rid)

    def warmup(self):
        with self._lock:
            self.engine.warmup()
        self._last_beat = self._clock()
        return self

    def postmortem(self, reason: str, trace_ids=()) -> Optional[Dict]:
        # deliberately lock-free AND loop-free: the flight recorder's
        # ring is host-side state, so a replica whose step loop already
        # died can still testify
        flight = getattr(self.engine, "flight", None)
        if flight is None:
            return None
        return flight.dump(reason, trace_ids=trace_ids)

    def progress(self, since: Optional[Dict[int, int]] = None
                 ) -> Dict[int, List[int]]:
        with self._lock:
            eng = self.engine
            out = {}
            for i in eng.scheduler.active_slots():
                st = eng.scheduler.slots[i]
                rid = st.request.rid
                lo = since.get(rid, 0) if since else 0
                if lo < 0 or lo > len(st.generated):
                    # stale cursor (restore rewound the stream, or the
                    # caller desynced): a raw slice would be empty or
                    # negative-indexed garbage — answer with the full
                    # stream, marked so the caller REPLACES its record
                    out[rid] = FullReplay(st.generated)
                else:
                    # tail-only slice: O(new tokens) per poll, not O(all)
                    out[rid] = list(st.generated[lo:]) if lo \
                        else list(st.generated)
            return out

    def poll_checkpoints(self) -> List[Tuple[int, Dict]]:
        with self._lock:
            return list(self.engine.poll_micro_snapshots().items())

    def poll_handoffs(self) -> List[Tuple[int, Dict]]:
        with self._lock:
            return list(self.engine.poll_handoffs())

    def reject_reason(self, rid: int):
        with self._lock:
            return self.engine.reject_reason(rid)

    # -- drain / migration -------------------------------------------------
    def drain_queue(self) -> List[Tuple]:
        with self._lock:
            # engine-owned cancellation: spans finish as "requeued" and
            # the per-request maps are cleaned — popping the scheduler
            # queue raw would leak them for the life of the process
            return [(r.rid, r.prompt, r.max_new_tokens, r.eos_id,
                     r.lane, r.ttft_deadline_s)
                    for r in self.engine.cancel_queued()]

    def snapshot_inflight(self) -> List[Tuple[int, Dict]]:
        with self._lock:
            eng = self.engine
            out = []
            for slot in list(eng.scheduler.active_slots()):
                rid = eng.scheduler.slots[slot].request.rid
                out.append((rid, eng.snapshot_slot(slot)))
                eng.release_slot(slot)
            return out

    def restore(self, snap: Dict, *, parent_span=None) -> int:
        with self._lock:
            return self.engine.restore_slot(snap, parent_span=parent_span)

    def export_prefix_pages(self, digests) -> Optional[Dict]:
        with self._lock:
            return self.engine.export_prefix_pages(digests)

    def import_prefix_pages(self, bundle) -> int:
        with self._lock:
            return self.engine.import_prefix_pages(bundle)

    # -- threaded mode -----------------------------------------------------
    def start(self, idle_sleep_s: float = 0.001) -> "LocalReplica":
        """Background step loop: steps whenever the engine has queued
        or in-flight work, sleeps briefly otherwise. The router keeps
        submitting from its own thread; ``health()`` polls stay safe
        (engine-published snapshots). A raising ``step()`` must NOT
        die silently (the replica would stay routable while its queue
        rots forever): the loop records ``last_error``, marks the
        replica ``failed`` — visible through ``health()`` and
        ``running()`` — and exits, so the router's failure detector
        ejects and redrives."""
        if self._thread is not None:
            raise RuntimeError(f"{self.name} already started")
        if self.failed:
            raise RuntimeError(
                f"{self.name} failed earlier ({self.last_error}); "
                "build a fresh replica instead of restarting this one")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    # locked idle() — the peek races a router-thread
                    # submit otherwise (mid-mutation queue iteration)
                    if self.idle():
                        self._last_beat = self._clock()
                        time.sleep(idle_sleep_s)
                        continue
                    self.step()
                except Exception as e:     # surface, never rot silently
                    self.last_error = f"{type(e).__name__}: {e}"
                    self.failed = True
                    return

        self._thread = threading.Thread(
            target=loop, name=f"fleet-{self.name}", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join()
        self._thread = None

    def running(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self.failed)

    def close(self):
        self.stop()
