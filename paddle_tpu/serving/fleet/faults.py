"""Fleet fault machinery: chaos injection, circuit breakers, detection.

PR 9's fleet only survives *voluntary* departure — ``drain_replica``
live-migrates a healthy replica's slots. A replica that crashes, hangs,
or starts throwing mid-step is routine at production scale ("engines as
cattle" is only half-true until involuntary death is survivable), and
nothing in the repo could provoke one on demand. This module makes
those failures first-class, in the ``resilience.faults`` tradition of
deterministic, unit-testable injection:

- :class:`ChaosReplica` — a wrapper over any
  :class:`~paddle_tpu.serving.fleet.ReplicaHandle` that injects
  *scheduled* faults: crash on step N (every call after raises, dead-
  host semantics like ``TornWriteFS``), hang after step N (steps stop
  progressing and ``health()`` reports an infinitely stale heartbeat —
  what a hung probe looks like from the router), the first K submits
  failing (a flaky transport), the first K health probes failing
  (corrupt health endpoint), and crash-on-snapshot (death *mid-drain*,
  after the queue is handed over but before migration completes).
  :func:`chaos_schedule` derives a seeded, reproducible fault schedule
  for property tests.
- :class:`CircuitBreaker` — per-replica closed → open on a failure
  threshold, half-open probe after a cooldown, closed again on probe
  success. The router stops routing to an open breaker (transient
  sickness pauses traffic without the terminal verdict of ejection)
  and deliberately routes ONE probe request when the breaker
  half-opens; transitions surface as a gauge, a counter, and trace
  events carrying the triggering request's original trace id.
- :class:`FailureDetector` — turns raw failure signals into a death
  verdict: a :class:`ReplicaCrashed` is immediately terminal; other
  step/submit/probe exceptions count toward a consecutive-failure
  threshold (transient flakes are the breaker's job, not death); a
  replica-surfaced background-loop crash (``health()["failed"]``) and
  a stale heartbeat with work pending (``heartbeat_age_s`` past the
  probe timeout) are terminal. The router acts on a verdict with
  :meth:`~paddle_tpu.serving.fleet.FleetRouter.eject_replica` — the
  hard counterpart of drain: KV is gone, so queued requests re-route
  and in-flight requests are *redriven* exactly-once.
- :class:`FaultPolicy` — one knob bundle (thresholds, timeouts,
  redrive budget) the router takes as ``faults=``.

Everything is host-side and clock-injectable: the chaos battery runs
with zero real sleeping and zero steady-state recompiles.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Dict, List, Optional, Tuple


class ReplicaCrashed(RuntimeError):
    """Terminal replica failure: the process/transport is gone. The
    detector treats this as immediately fatal (no consecutive-failure
    grace) — a dead host does not come back to finish its step."""


class ReplicaUnavailable(RuntimeError):
    """Transient replica failure (flaky transport, overloaded process):
    retryable on a peer, counted by the breaker and the detector's
    consecutive-failure threshold, but not terminal by itself."""


# ---------------------------------------------------------------------------
# chaos injection


@dataclasses.dataclass
class ChaosSpec:
    """One replica's fault schedule. Steps are 1-based counts of
    ``step()`` calls on the wrapper."""

    crash_on_step: Optional[int] = None     # step N raises; dead after
    hang_after_step: Optional[int] = None   # steps stop progressing
    submit_failures: int = 0                # first K submits raise
    health_failures: int = 0                # first K health probes raise
    crash_on_snapshot: bool = False         # dies mid-drain
    crash_on_handoff: bool = False          # prefill dies mid-handoff
    crash_on_restore: bool = False          # decode dies mid-restore
    crash_on_export: bool = False           # dies mid-prefix-page-fetch


def chaos_schedule(seed: int, n_replicas: int, *,
                   max_crash_step: int = 16,
                   p_crash: float = 0.5, p_hang: float = 0.25,
                   max_submit_failures: int = 3) -> List[ChaosSpec]:
    """Seeded, reproducible fault schedule for ``n_replicas`` — the
    property-test driver: same seed, same chaos, byte-for-byte."""
    rng = random.Random(seed)
    specs = []
    for _ in range(n_replicas):
        roll = rng.random()
        if roll < p_crash:
            specs.append(ChaosSpec(
                crash_on_step=rng.randint(1, max_crash_step)))
        elif roll < p_crash + p_hang:
            specs.append(ChaosSpec(
                hang_after_step=rng.randint(1, max_crash_step)))
        else:
            specs.append(ChaosSpec(
                submit_failures=rng.randint(0, max_submit_failures)))
    return specs


class ChaosReplica:
    """Deterministic fault-injecting wrapper over a ``ReplicaHandle``.

    The router only ever sees the wrapper, so injected faults look
    exactly like a failing transport: ``step()`` raising, ``submit()``
    raising, ``health()`` raising or reporting a stale heartbeat. After
    a crash fires, EVERY subsequent operation raises
    :class:`ReplicaCrashed` (dead-host semantics — the
    ``TornWriteFS`` discipline); a hung replica keeps answering
    ``health()`` but stops making progress and its heartbeat age reads
    infinite. ``spec`` fields can also be given as keyword arguments.
    """

    def __init__(self, inner, spec: Optional[ChaosSpec] = None, **kw):
        self.inner = inner
        self.spec = spec or ChaosSpec(**kw)
        self.name = inner.name
        self.draining = False
        self.dead = False
        self.hung = False
        self.steps_seen = 0
        self.submit_failures_injected = 0
        self.health_failures_injected = 0

    # -- fault gates -------------------------------------------------------

    def _check(self):
        if self.dead:
            raise ReplicaCrashed(f"chaos: {self.name} is dead")

    # -- ReplicaHandle surface --------------------------------------------

    def step(self):
        self._check()
        self.steps_seen += 1
        s = self.spec
        if s.crash_on_step is not None and self.steps_seen >= s.crash_on_step:
            self.dead = True
            raise ReplicaCrashed(
                f"chaos: {self.name} crashed at step {self.steps_seen}")
        if (s.hang_after_step is not None
                and self.steps_seen >= s.hang_after_step):
            self.hung = True
            return {}               # no progress, no error: a hang
        return self.inner.step()

    def submit(self, prompt, max_new_tokens, eos_id=None, *,
               lane="default", ttft_deadline_s=None, trace_id=None):
        self._check()
        if self.submit_failures_injected < self.spec.submit_failures:
            self.submit_failures_injected += 1
            raise ReplicaUnavailable(
                f"chaos: {self.name} submit failure "
                f"#{self.submit_failures_injected}")
        return self.inner.submit(prompt, max_new_tokens, eos_id,
                                 lane=lane,
                                 ttft_deadline_s=ttft_deadline_s,
                                 trace_id=trace_id)

    def health(self):
        self._check()
        if self.health_failures_injected < self.spec.health_failures:
            self.health_failures_injected += 1
            raise ReplicaUnavailable(
                f"chaos: {self.name} health probe failure "
                f"#{self.health_failures_injected}")
        h = dict(self.inner.health())
        if self.hung:
            # what a hung replica looks like from outside: the probe
            # answers (cached state) but the loop stopped beating
            h["heartbeat_age_s"] = float("inf")
        return h

    def idle(self):
        # a hang does not change idleness: the work is still there, it
        # just never finishes — the router's heartbeat probe (not this
        # predicate) is what declares the replica dead
        self._check()
        return self.inner.idle()

    def snapshot_inflight(self):
        self._check()
        if self.spec.crash_on_snapshot:
            self.dead = True
            raise ReplicaCrashed(
                f"chaos: {self.name} crashed mid-drain (snapshot)")
        return self.inner.snapshot_inflight()

    def page_size(self):
        self._check()
        return self.inner.page_size()

    def prefix_digests(self):
        self._check()
        return self.inner.prefix_digests()

    def export_prefix_pages(self, digests):
        self._check()
        if self.spec.crash_on_export:
            self.dead = True
            raise ReplicaCrashed(
                f"chaos: {self.name} crashed mid-prefix-export")
        return self.inner.export_prefix_pages(digests)

    def import_prefix_pages(self, bundle):
        self._check()
        return self.inner.import_prefix_pages(bundle)

    def can_accept(self, total_tokens):
        self._check()
        return not self.draining and self.inner.can_accept(total_tokens)

    def result(self, rid):
        self._check()
        return self.inner.result(rid)

    def request_stats(self, rid):
        self._check()
        return self.inner.request_stats(rid)

    def progress(self, since=None):
        self._check()
        return self.inner.progress(since)

    def poll_checkpoints(self):
        self._check()
        return self.inner.poll_checkpoints()

    def poll_handoffs(self):
        self._check()
        if self.spec.crash_on_handoff:
            # the prefill-tier chaos leg: the replica dies while the
            # router is draining its handoff outbox — parked slots go
            # down with it and must redrive through the replay records
            self.dead = True
            raise ReplicaCrashed(
                f"chaos: {self.name} crashed mid-handoff")
        return self.inner.poll_handoffs()

    def reject_reason(self, rid):
        self._check()
        return self.inner.reject_reason(rid)

    def drain_queue(self):
        self._check()
        return self.inner.drain_queue()

    def restore(self, snap, *, parent_span=None):
        self._check()
        if self.spec.crash_on_restore:
            # the decode-tier chaos leg: the replica dies mid-restore —
            # the router still holds the snapshot and must place it
            # elsewhere (or fall back to the source) with nothing lost
            self.dead = True
            raise ReplicaCrashed(
                f"chaos: {self.name} crashed mid-restore")
        return self.inner.restore(snap, parent_span=parent_span)

    def warmup(self):
        self.inner.warmup()
        return self

    def postmortem(self, reason, trace_ids=()):
        # NO _check(): the whole point of a flight recorder is that a
        # dead replica still hands over its black box
        return self.inner.postmortem(reason, trace_ids=trace_ids)

    def running(self):
        return (not self.dead and not self.hung
                and getattr(self.inner, "running", lambda: False)())

    def close(self):
        # best-effort: ejecting a dead replica must not raise again
        try:
            self.inner.close()
        except Exception:
            pass

    # convenience pass-throughs the bench/tests read and write
    @property
    def engine(self):
        return self.inner.engine

    @property
    def busy_s(self):
        return self.inner.busy_s

    @busy_s.setter
    def busy_s(self, v):
        self.inner.busy_s = v


# ---------------------------------------------------------------------------
# circuit breaker


class CircuitBreaker:
    """Per-replica request gate: ``closed`` (healthy) → ``open`` after
    ``threshold`` consecutive failures (no traffic) → ``half_open``
    after ``cooldown_s`` (exactly one probe request allowed) → back to
    ``closed`` on probe success or ``open`` on probe failure.

    ``on_transition(old, new, trace_id)`` fires on every state change —
    the router wires it to the ``fleet_breaker_state`` gauge, the
    transition counter, and a ``fleet.breaker`` trace event on the
    triggering request's original trace id.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, *, threshold: int = 5, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable] = None):
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._on_transition = on_transition
        self.state = self.CLOSED
        self.failures = 0
        self.opened_at: Optional[float] = None
        self.probe_inflight = False
        self.transitions: List[Tuple[str, str]] = []

    def _move(self, new: str, trace_id: int = 0):
        old, self.state = self.state, new
        if old != new:
            self.transitions.append((old, new))
            if self._on_transition is not None:
                self._on_transition(old, new, trace_id)

    def poll(self):
        """Advance open → half_open once the cooldown has elapsed.
        Called by the router on every routing pass."""
        if (self.state == self.OPEN and self.opened_at is not None
                and self._clock() - self.opened_at >= self.cooldown_s):
            self.probe_inflight = False
            self._move(self.HALF_OPEN)

    def allow(self) -> bool:
        """May a request be routed here right now? Half-open allows
        exactly one in-flight probe at a time."""
        self.poll()
        if self.state == self.CLOSED:
            return True
        if self.state == self.HALF_OPEN:
            return not self.probe_inflight
        return False

    def note_probe(self):
        """The router is sending the half-open probe request."""
        if self.state == self.HALF_OPEN:
            self.probe_inflight = True

    def record_success(self, trace_id: int = 0):
        self.failures = 0
        self.probe_inflight = False
        if self.state != self.CLOSED:
            self.opened_at = None
            self._move(self.CLOSED, trace_id)

    def record_failure(self, trace_id: int = 0):
        self.failures += 1
        self.probe_inflight = False
        if self.state == self.HALF_OPEN:
            self.opened_at = self._clock()     # probe failed: re-open
            self._move(self.OPEN, trace_id)
        elif self.state == self.CLOSED and self.failures >= self.threshold:
            self.opened_at = self._clock()
            self._move(self.OPEN, trace_id)

    def status(self) -> Dict[str, object]:
        return {"state": self.state, "failures": self.failures,
                "cooldown_s": self.cooldown_s,
                "open_age_s": (None if self.opened_at is None
                               else self._clock() - self.opened_at)}


# numeric encoding for the fleet_breaker_state gauge
BREAKER_GAUGE = {CircuitBreaker.CLOSED: 0.0,
                 CircuitBreaker.HALF_OPEN: 1.0,
                 CircuitBreaker.OPEN: 2.0}


# ---------------------------------------------------------------------------
# failure detection


@dataclasses.dataclass(frozen=True)
class FaultPolicy:
    """How the router reacts to involuntary failure. ``enabled=False``
    restores the PR 9 router byte-for-byte (no probes, no breakers, a
    replica exception propagates).

    Keep ``breaker_threshold`` BELOW ``max_consecutive_failures``: the
    breaker must trip first so a transiently flaky transport stops
    receiving submits (freezing its failure count) *before* the
    detector's consecutive-failure verdict ejects it — ejection is for
    the genuinely dead. With the order inverted, every flaky replica
    is ejected before its breaker ever opens and half-open recovery
    never happens."""

    enabled: bool = True
    max_consecutive_failures: int = 5   # step/submit/probe raises → dead
    probe_timeout_s: float = 30.0       # stale heartbeat w/ work → dead
    breaker_threshold: int = 3          # failures → breaker opens
    breaker_cooldown_s: float = 30.0    # open → half-open probe delay
    max_redrives: int = 3               # per-request redrive budget


class FailureDetector:
    """Failure signals → death verdicts, per replica (keyed by name).

    Terminal immediately: :class:`ReplicaCrashed`, a replica-surfaced
    background-loop crash (``health()["failed"]``), a heartbeat older
    than ``probe_timeout_s`` while the replica holds queued or
    in-flight work. Everything else (transient exceptions from step /
    submit / the health probe) counts toward
    ``max_consecutive_failures``; any success resets the count.
    """

    def __init__(self, *, max_consecutive_failures: int = 3,
                 probe_timeout_s: float = 30.0):
        self.max_consecutive_failures = int(max_consecutive_failures)
        self.probe_timeout_s = float(probe_timeout_s)
        self._fails: Dict[str, int] = {}

    def observe_success(self, name: str):
        self._fails[name] = 0

    def observe_failure(self, name: str, exc: BaseException
                        ) -> Optional[str]:
        """Returns a death reason, or None (still within grace)."""
        if isinstance(exc, ReplicaCrashed):
            return "crashed"
        n = self._fails.get(name, 0) + 1
        self._fails[name] = n
        if n >= self.max_consecutive_failures:
            return f"consecutive_failures:{n}"
        return None

    def check_health(self, name: str, health: Dict[str, object]
                     ) -> Optional[str]:
        """Death verdict from a successful probe's payload: the replica
        surfacing its own loop crash, or a hang (stale heartbeat while
        work is pending)."""
        if health.get("failed"):
            return f"replica_failed:{health.get('last_error', '?')}"
        age = health.get("heartbeat_age_s")
        has_work = (int(health.get("queue_depth", 0) or 0)
                    + int(health.get("requests_in_flight", 0) or 0)) > 0
        if age is not None and has_work and float(age) > self.probe_timeout_s:
            return f"heartbeat_timeout:{float(age):.3f}s"
        return None

    def consecutive_failures(self, name: str) -> int:
        return self._fails.get(name, 0)
