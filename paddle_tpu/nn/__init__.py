"""Module/Layer system + standard layers."""

from paddle_tpu.nn import initializer
from paddle_tpu.nn import distributions
from paddle_tpu.nn import nets
from paddle_tpu.nn.nets import (ImgConvGroup, SequenceConvPool,
                                SimpleImgConvPool, glu)
from paddle_tpu.nn.distributions import (Categorical, Distribution,
                                         MultivariateNormalDiag, Normal,
                                         Uniform)
from paddle_tpu.nn.module import (Layer, LayerList, ParamSpec, Sequential,
                                  apply_state_updates, capture_state,
                                  report_state)
from paddle_tpu.nn.layers import (FC, BatchNorm, Conv2D, Dropout, Embedding,
                                  LayerNorm, Linear, Pool2D)
from paddle_tpu.nn.transformer import (FeedForward, MultiHeadAttention,
                                       TransformerDecoderLayer,
                                       TransformerEncoderLayer)
from paddle_tpu.nn.moe import MoEFeedForward
from paddle_tpu.nn.rnn import (BiRNN, GRUCell, LSTM, LSTMCell, LSTMPCell,
                               RNN, SimpleRNNCell)

__all__ = [
    "initializer", "distributions", "Categorical", "Distribution",
    "MultivariateNormalDiag", "Normal", "Uniform",
    "nets", "ImgConvGroup", "SequenceConvPool", "SimpleImgConvPool", "glu",
    "Layer", "LayerList", "ParamSpec", "Sequential",
    "apply_state_updates", "capture_state", "report_state",
    "FC", "BatchNorm", "Conv2D", "Dropout", "Embedding", "LayerNorm",
    "Linear", "Pool2D",
    "FeedForward", "MultiHeadAttention", "TransformerDecoderLayer",
    "TransformerEncoderLayer",
    "MoEFeedForward", "BiRNN", "GRUCell", "LSTM", "LSTMCell", "LSTMPCell",
    "RNN", "SimpleRNNCell",
]
