"""Composite network helpers (``fluid.nets`` parity).

Reference: ``python/paddle/fluid/nets.py:1-533`` — ``simple_img_conv_pool``
(:28), ``img_conv_group`` (:136), ``sequence_conv_pool`` (:249), ``glu``
(:405). (``scaled_dot_product_attention``, :444, lives in
``paddle_tpu.ops.attention``.)

The reference's helpers are graph-building functions; here they are
``Layer`` composites (this framework's module idiom) built from the same
primitives — ``Conv2D``/``Pool2D``/``BatchNorm``/``Dropout`` and the
``sequence_conv``/``sequence_pool`` ops — plus functional ``glu``.
Input layout is NHWC (TPU-native), not the reference's NCHW.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.nn.layers import BatchNorm, Conv2D, Dropout, Pool2D
from paddle_tpu.nn.module import Layer
from paddle_tpu.ops import activation as A
from paddle_tpu.ops import sequence as S

__all__ = ["glu", "SimpleImgConvPool", "ImgConvGroup", "SequenceConvPool"]


@register_op("glu", has_grad=True)
def glu(x, axis: int = -1):
    """Gated Linear Unit: split ``x`` in two along ``axis``, gate the first
    half with the sigmoid of the second (reference ``nets.py:405`` — split +
    sigmoid + elementwise_mul; one fused XLA expression here)."""
    if x.shape[axis] % 2:
        raise ValueError(f"glu axis dim must be even, got {x.shape[axis]}")
    a, b = jnp.split(x, 2, axis=axis)
    return a * A.sigmoid(b)


_ACTS = {None: lambda x: x, "relu": A.relu, "sigmoid": A.sigmoid,
         "tanh": A.tanh, "gelu": A.gelu, "swish": A.swish}


def _act(name):
    if callable(name):
        return name
    try:
        return _ACTS[name]
    except KeyError:
        raise ValueError(f"unknown activation {name!r}") from None


class SimpleImgConvPool(Layer):
    """One Conv2D + one Pool2D (reference ``nets.py:28``
    ``simple_img_conv_pool``). NHWC input."""

    def __init__(self, in_channels, num_filters, filter_size, pool_size,
                 pool_stride, pool_padding=0, pool_type="max",
                 global_pooling=False, conv_stride=1, conv_padding=0,
                 conv_dilation=1, conv_groups=1, act=None, bias=True):
        super().__init__()
        self.conv = Conv2D(in_channels, num_filters, filter_size,
                           stride=conv_stride, padding=conv_padding,
                           dilation=conv_dilation, groups=conv_groups,
                           bias=bias)
        self.pool = Pool2D(pool_size, pool_stride, pool_padding,
                           pool_type=pool_type,
                           global_pooling=global_pooling)
        self.act = _act(act)

    def forward(self, params, x):
        return self.pool(None, self.act(self.conv(params["conv"], x)))


def _extend(obj, n, what):
    if isinstance(obj, (list, tuple)):
        if len(obj) != n:
            raise ValueError(f"{what} length {len(obj)} != {n} conv layers")
        return list(obj)
    return [obj] * n


class ImgConvGroup(Layer):
    """Serial Conv2D[+BatchNorm][+Dropout] stack followed by one Pool2D
    (reference ``nets.py:136`` ``img_conv_group`` — the VGG building
    block). Per-layer settings broadcast like the reference's
    ``__extend_list__``. NHWC input."""

    def __init__(self, in_channels, conv_num_filter: Sequence[int],
                 pool_size, conv_padding=1, conv_filter_size=3,
                 conv_act=None, conv_with_batchnorm: Union[bool, list] = False,
                 conv_batchnorm_drop_rate: Union[float, list] = 0.0,
                 pool_stride=1, pool_type="max"):
        super().__init__()
        n = len(conv_num_filter)
        pad = _extend(conv_padding, n, "conv_padding")
        fs = _extend(conv_filter_size, n, "conv_filter_size")
        self.with_bn = _extend(conv_with_batchnorm, n, "conv_with_batchnorm")
        self.drop = _extend(conv_batchnorm_drop_rate, n,
                            "conv_batchnorm_drop_rate")
        self.act = _act(conv_act)
        c = in_channels
        for i, f in enumerate(conv_num_filter):
            self.add_sublayer(f"conv{i}",
                              Conv2D(c, f, fs[i], padding=pad[i]))
            if self.with_bn[i]:
                self.add_sublayer(f"bn{i}", BatchNorm(f))
                if abs(self.drop[i]) > 1e-5:
                    self.add_sublayer(f"dropout{i}", Dropout(self.drop[i]))
            c = f
        self.n = n
        self.pool = Pool2D(pool_size, pool_stride, pool_type=pool_type)

    def forward(self, params, x, *, training=False, dropout_key=None):
        import jax

        h = x
        for i in range(self.n):
            h = getattr(self, f"conv{i}")(params[f"conv{i}"], h)
            if self.with_bn[i]:
                # activation rides AFTER BN when BN is present (:225-230)
                h = getattr(self, f"bn{i}")(params[f"bn{i}"], h,
                                            training=training)
                h = self.act(h)
                if abs(self.drop[i]) > 1e-5:
                    # per-layer key: reusing one key across sublayers
                    # correlates their masks (same positions drop at
                    # equal rates), silently weakening regularization
                    layer_key = (jax.random.fold_in(dropout_key, i)
                                 if dropout_key is not None else None)
                    h = getattr(self, f"dropout{i}")(
                        None, h, key=layer_key, training=training)
            else:
                h = self.act(h)
        return self.pool(None, h)


class SequenceConvPool(Layer):
    """Context-window sequence conv + sequence pool (reference
    ``nets.py:249`` ``sequence_conv_pool`` — the text-CNN building block).
    Input is padded ``(B, T, D)`` + ``lengths``, the TPU-native packing of
    the reference's LoD rows."""

    def __init__(self, input_dim, num_filters, filter_size,
                 act="sigmoid", pool_type="max", bias=True):
        super().__init__()
        from paddle_tpu.nn import initializer as I
        self.filter_size = filter_size
        self.filter = self.create_parameter(
            "filter", (filter_size * input_dim, num_filters),
            initializer=I.xavier_uniform())
        self.has_bias = bias
        if bias:
            self.bias = self.create_parameter(
                "bias", (num_filters,), initializer=I.zeros)
        self.act = _act(act)
        self.pool_type = pool_type

    def forward(self, params, x, lengths):
        # center the context window on t for ANY filter size (reference
        # sequence_conv default: context_start = -ctx_len//2; the old
        # hardcoded -1 mis-aligned even filter sizes by one step)
        h = S.sequence_conv(x, lengths, params["filter"],
                            context_start=-(self.filter_size // 2))
        if self.has_bias:
            h = h + params["bias"]
        h = self.act(h)
        return S.sequence_pool(h, lengths, pool_type=self.pool_type)
