"""Transformer building blocks (MHA, encoder/decoder layers).

Reference mapping: the reference composes attention from primitive ops in
model zoos (no nn.MultiHeadAttention in fluid 1.5; e.g. PaddleNLP
transformer builds q/k/v with ``layers/nn.py`` fc:231 + matmul + softmax
:2333). Here attention is a first-class layer backed by the Pallas flash
kernel (``ops/attention.py``) with Megatron-style TP sharding hints:
qkv projections column-parallel over "tp", output projection row-parallel,
so a tp-sharded mesh runs each head group on its own shard with a single
psum at the block output (inserted by GSPMD).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.nn.layers import Dropout, LayerNorm, Linear
from paddle_tpu.nn.module import Layer
from paddle_tpu.ops import activation as ops_act
from paddle_tpu.ops import attention as ops_attn

# Activation-sharding convention for transformer blocks:
#   hidden activations (B, S, D): P(("dp","fsdp"), "sp", None)
ACT_SPEC = P(("dp", "fsdp"), "sp", None)
HEADS_SPEC = P(("dp", "fsdp"), "tp", None, None)       # (B, H, S, Dh)
RING_HEADS_SPEC = P(("dp", "fsdp"), "tp", "sp", None)  # seq stays sharded


def _constrain(x, spec):
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        # outside a mesh context (single-device eager) constraints are moot
        return x


class MultiHeadAttention(Layer):
    """Multi-head attention with fused-qkv option and flash-kernel backend.

    ``self_attention=True`` uses one fused qkv projection (better MXU
    utilisation than three thin matmuls); cross-attention keeps separate
    q and kv projections (decoder).
    """

    def __init__(self, embed_dim, num_heads, dropout=0.0, bias=True,
                 self_attention=True, causal=False, attn_impl="auto"):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError("num_heads must divide embed_dim")
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout
        self.causal = causal
        self.attn_impl = attn_impl
        if attn_impl == "ring" and dropout > 0.0:
            raise ValueError(
                "ring attention does not support attention-prob dropout; "
                "set attn_dropout=0 (residual dropout still applies)")
        self.self_attention = self_attention
        if self_attention:
            self.qkv_proj = Linear(embed_dim, 3 * embed_dim, bias=bias,
                                   sharding=P(None, "tp"))
        else:
            self.q_proj = Linear(embed_dim, embed_dim, bias=bias,
                                 sharding=P(None, "tp"))
            self.kv_proj = Linear(embed_dim, 2 * embed_dim, bias=bias,
                                  sharding=P(None, "tp"))
        self.out_proj = Linear(embed_dim, embed_dim, bias=bias,
                               sharding=P("tp", None))

    def _split_heads(self, x):
        b, s, _ = x.shape
        x = x.reshape(b, s, self.num_heads, self.head_dim)
        return x.transpose(0, 2, 1, 3)  # (B, H, S, Dh)

    def _merge_heads(self, x):
        b, h, s, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)

    def qkv_heads(self, params, x):
        """(B, S, D) -> (q, k, v) heads, each (B, H, S, Dh) — the serving
        engine's hook: it owns the attention itself (ragged paged decode
        over the shared page pool) and only needs the projections."""
        if self.self_attention:
            qkv = self.qkv_proj(params["qkv_proj"], x)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            q = self.q_proj(params["q_proj"], x)
            kv = self.kv_proj(params["kv_proj"], x)
            k, v = jnp.split(kv, 2, axis=-1)
        return tuple(self._split_heads(t) for t in (q, k, v))

    def proj_out(self, params, heads):
        """(B, H, S, Dh) attention output heads -> (B, S, D) through the
        output projection (the other half of the serving hook)."""
        return self.out_proj(params["out_proj"], self._merge_heads(heads))

    def cross_kv(self, params, memory):
        """Precompute cross-attention (k, v) heads from encoder memory —
        done ONCE per sequence; decode steps pass them as ``static_kv``
        (the reference's cached beam-search decoder keeps the same
        per-layer static caches)."""
        kv = self.kv_proj(params["kv_proj"], memory)
        k, v = jnp.split(kv, 2, axis=-1)
        return self._split_heads(k), self._split_heads(v)

    def forward(self, params, query, key_value=None, *, bias=None,
                key=None, training=False, cache=None, cache_pos=None,
                return_kv=False, static_kv=None):
        """query: (B, Sq, D); key_value: (B, Sk, D) for cross-attention.
        ``bias``: additive attention bias broadcastable to (B,H,Sq,Sk).

        Incremental decoding: ``cache=(k_cache, v_cache)`` with leaves
        (B, H, Smax, Dh) and ``cache_pos`` the write position makes this
        a single-token decode step (query Sq=1 attends over the filled
        prefix; O(S) per token instead of refeeding the whole sequence)
        returning (out, new_cache). ``return_kv=True`` on the normal
        path additionally returns this call's (k, v) heads — the
        prefill that seeds the cache. ``static_kv``: precomputed (k, v)
        heads (see :meth:`cross_kv`) — skips the kv projection entirely
        (cross-attention decode)."""
        if static_kv is not None:
            q = self._split_heads(self.q_proj(params["q_proj"], query))
            k, v = static_kv
            out = ops_attn.dot_product_attention(
                q, k, v, bias=bias, causal=False, impl="xla")
            out = self._merge_heads(out)
            return self.out_proj(params["out_proj"], out)
        if self.self_attention:
            qkv = self.qkv_proj(params["qkv_proj"], query)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            q = self.q_proj(params["q_proj"], query)
            kv = self.kv_proj(params["kv_proj"],
                              query if key_value is None else key_value)
            k, v = jnp.split(kv, 2, axis=-1)
        q, k, v = (self._split_heads(t) for t in (q, k, v))

        if cache is not None:
            ck, cv = cache
            ck = jax.lax.dynamic_update_slice(
                ck, k.astype(ck.dtype), (0, 0, cache_pos, 0))
            cv = jax.lax.dynamic_update_slice(
                cv, v.astype(cv.dtype), (0, 0, cache_pos, 0))
            # static shapes: attend over the whole cache, mask the unfilled
            # tail (positions > cache_pos)
            smax = ck.shape[2]
            mask = jnp.arange(smax)[None, None, None, :] <= cache_pos
            step_bias = jnp.where(mask, 0.0, -1e30).astype(q.dtype)
            if bias is not None:
                step_bias = step_bias + bias
            out = ops_attn.dot_product_attention(
                q, ck, cv, bias=step_bias, causal=False, impl="xla")
            out = self._merge_heads(out)
            out = self.out_proj(params["out_proj"], out)
            return out, (ck, cv)
        spec = RING_HEADS_SPEC if self.attn_impl == "ring" else HEADS_SPEC
        q = _constrain(q, spec)
        k = _constrain(k, spec)
        v = _constrain(v, spec)
        drop_rate = self.dropout_rate if training else 0.0
        if self.attn_impl == "ring":
            # sequence-parallel path: S sharded over "sp", k/v ride the ring
            from paddle_tpu.parallel.ring_attention import ring_attention
            out = ring_attention(q, k, v, bias=bias, causal=self.causal)
        else:
            out = ops_attn.dot_product_attention(
                q, k, v, bias=bias, causal=self.causal,
                dropout_rate=drop_rate, dropout_key=key, impl=self.attn_impl)
        out = self._merge_heads(out)
        out = self.out_proj(params["out_proj"], out)
        out = _constrain(out, ACT_SPEC)
        if return_kv:
            return out, (k, v)
        return out


class FeedForward(Layer):
    """Position-wise MLP: col-parallel fc1, row-parallel fc2."""

    def __init__(self, embed_dim, ffn_dim, activation="gelu", dropout=0.0):
        super().__init__()
        self.fc1 = Linear(embed_dim, ffn_dim, sharding=P(None, "tp"))
        self.fc2 = Linear(ffn_dim, embed_dim, sharding=P("tp", None))
        self.act = getattr(ops_act, activation)
        self.drop = Dropout(dropout)

    def forward(self, params, x, key=None, training=False):
        h = self.act(self.fc1(params["fc1"], x))
        h = self.drop(None, h, key=key, training=training)
        return _constrain(self.fc2(params["fc2"], h), ACT_SPEC)


class TransformerEncoderLayer(Layer):
    """Pre/post-LN encoder block (post-LN default: BERT convention)."""

    def __init__(self, embed_dim, num_heads, ffn_dim, dropout=0.1,
                 attn_dropout=None, activation="gelu", pre_ln=False,
                 attn_impl="auto"):
        super().__init__()
        self.attn = MultiHeadAttention(
            embed_dim, num_heads,
            dropout=attn_dropout if attn_dropout is not None else dropout,
            attn_impl=attn_impl)
        self.ffn = FeedForward(embed_dim, ffn_dim, activation, dropout)
        self.ln1 = LayerNorm(embed_dim)
        self.ln2 = LayerNorm(embed_dim)
        self.drop = Dropout(dropout)
        self.pre_ln = pre_ln

    def forward(self, params, x, *, bias=None, key=None, training=False):
        k1 = k2 = k3 = None
        if key is not None:
            k1, k2, k3 = jax.random.split(key, 3)
        if self.pre_ln:
            h = self.attn(params["attn"], self.ln1(params["ln1"], x),
                          bias=bias, key=k1, training=training)
            x = x + self.drop(None, h, key=k2, training=training)
            h = self.ffn(params["ffn"], self.ln2(params["ln2"], x),
                         key=k3, training=training)
            if key is not None:
                h = self.drop(None, h, key=jax.random.fold_in(k3, 1),
                              training=training)
            return x + h
        h = self.attn(params["attn"], x, bias=bias, key=k1, training=training)
        x = self.ln1(params["ln1"],
                     x + self.drop(None, h, key=k2, training=training))
        h = self.ffn(params["ffn"], x, key=k3, training=training)
        if key is not None:
            k4 = jax.random.fold_in(k3, 1)
            h = self.drop(None, h, key=k4, training=training)
        return self.ln2(params["ln2"], x + h)


class TransformerDecoderLayer(Layer):
    """Decoder block: causal self-attention + cross-attention + FFN."""

    def __init__(self, embed_dim, num_heads, ffn_dim, dropout=0.1,
                 attn_dropout=None, activation="relu", pre_ln=False,
                 attn_impl="auto"):
        super().__init__()
        if attn_dropout is None:
            attn_dropout = dropout
        self.self_attn = MultiHeadAttention(embed_dim, num_heads,
                                            dropout=attn_dropout,
                                            causal=True,
                                            attn_impl=attn_impl)
        self.cross_attn = MultiHeadAttention(embed_dim, num_heads,
                                             dropout=attn_dropout,
                                             self_attention=False,
                                             attn_impl=attn_impl)
        self.ffn = FeedForward(embed_dim, ffn_dim, activation, dropout)
        self.ln1 = LayerNorm(embed_dim)
        self.ln2 = LayerNorm(embed_dim)
        self.ln3 = LayerNorm(embed_dim)
        self.drop = Dropout(dropout)
        self.pre_ln = pre_ln

    def forward(self, params, x, memory, *, self_bias=None, cross_bias=None,
                key=None, training=False):
        ks = [None] * 3
        if key is not None:
            ks = list(jax.random.split(key, 3))

        def sub(x, ln_name, fn, drop_key):
            ln = getattr(self, ln_name)
            dk = (jax.random.fold_in(drop_key, 1)
                  if drop_key is not None else None)
            if self.pre_ln:
                h = fn(ln(params[ln_name], x))
                return x + self.drop(None, h, key=dk, training=training)
            h = self.drop(None, fn(x), key=dk, training=training)
            return ln(params[ln_name], x + h)

        x = sub(x, "ln1",
                lambda h: self.self_attn(params["self_attn"], h,
                                         bias=self_bias, key=ks[0],
                                         training=training), ks[0])
        x = sub(x, "ln2",
                lambda h: self.cross_attn(params["cross_attn"], h, memory,
                                          bias=cross_bias, key=ks[1],
                                          training=training), ks[1])
        x = sub(x, "ln3",
                lambda h: self.ffn(params["ffn"], h, key=ks[2],
                                   training=training), ks[2])
        return x

    def decode_step(self, params, x, pos, self_cache, cross_kv, *,
                    cross_bias=None):
        """Single-token cached decode (x (B, 1, D) at position ``pos``):
        self-attention through the KV cache, cross-attention over the
        precomputed memory heads. Inference only (no dropout). Returns
        (x, new_self_cache)."""
        def sub(x, ln_name, fn):
            ln = getattr(self, ln_name)
            if self.pre_ln:
                return x + fn(ln(params[ln_name], x))
            return ln(params[ln_name], x + fn(x))

        box = {}

        def self_fn(h):
            out, box["cache"] = self.self_attn(
                params["self_attn"], h, cache=self_cache, cache_pos=pos)
            return out

        x = sub(x, "ln1", self_fn)
        x = sub(x, "ln2",
                lambda h: self.cross_attn(params["cross_attn"], h,
                                          bias=cross_bias,
                                          static_kv=cross_kv))
        x = sub(x, "ln3", lambda h: self.ffn(params["ffn"], h))
        return x, box["cache"]
