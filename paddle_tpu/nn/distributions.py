"""Probability distributions (``fluid.layers.distributions`` parity).

Reference: ``python/paddle/fluid/layers/distributions.py:28-603`` —
``Distribution`` ABC plus ``Uniform`` (:113), ``Normal`` (:246),
``Categorical`` (:401) and ``MultivariateNormalDiag`` (:494), each exposing
``sample`` / ``entropy`` / ``log_prob`` / ``kl_divergence``.

TPU-native design notes
-----------------------
* Everything is pure ``jnp`` on broadcastable arrays — every method traces
  under ``jax.jit`` and ``vmap`` with static shapes.
* ``sample`` takes an explicit ``jax.random`` key (functional PRNG) instead
  of the reference's stateful ``seed=`` int; a ``seed`` kwarg is still
  accepted for API familiarity and folds into a key.
* The reference builds graph ops (``uniform_random_batch_size_like`` …) to
  handle unknown batch sizes; under JAX shapes are static at trace time so
  the two reference code paths collapse into one.
* Beyond the reference, ``Categorical`` gains ``sample``/``log_prob`` and
  ``MultivariateNormalDiag`` gains ``sample``/``log_prob`` (the reference
  leaves them unimplemented); shapes/semantics follow the same conventions.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = [
    "Distribution", "Uniform", "Normal", "Categorical",
    "MultivariateNormalDiag", "kl_divergence",
]

_LOG_2PI = math.log(2.0 * math.pi)

# eager-convenience PRNG stream for sample() calls that pass neither key nor
# seed: fresh draw per call, like the reference's seed=0 ("use a fresh engine
# seed", gaussian_random_op.cc semantics). Under jit, pass `key` explicitly —
# the counter advances at trace time only, so the implicit draw would be
# BAKED into the compiled function; _key refuses that case loudly.
_default_stream = iter(range(1 << 62))


def _tracing() -> bool:
    try:
        return not jax.core.trace_state_clean()
    except AttributeError:   # renamed/removed in some jax versions
        return False


def _key(key, seed):
    if key is not None:
        return key
    if seed is not None:
        return jax.random.PRNGKey(seed)
    if _tracing():
        raise ValueError(
            "Distribution.sample() called with neither key= nor seed= "
            "inside a jax trace (jit/grad/vmap/scan): the implicit fresh "
            "draw happens at TRACE time, so the compiled function would "
            "silently replay ONE fixed sample forever. Pass key= (split "
            "it per step) for independent draws, or seed= to make the "
            "fixed draw explicit.")
    return jax.random.PRNGKey(next(_default_stream))


class Distribution:
    """Abstract base class for probability distributions
    (reference ``distributions.py:28``)."""

    def sample(self, shape=(), *, key=None, seed=None):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))


class Uniform(Distribution):
    """Uniform distribution on ``[low, high)``
    (reference ``distributions.py:113``)."""

    def __init__(self, low, high):
        self.low = jnp.asarray(low, dtype=jnp.result_type(float))
        self.high = jnp.asarray(high, dtype=self.low.dtype)

    @property
    def batch_shape(self):
        return jnp.broadcast_shapes(self.low.shape, self.high.shape)

    def sample(self, shape=(), *, key=None, seed=None):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(_key(key, seed), shape, dtype=self.low.dtype)
        return self.low + u * (self.high - self.low)

    def log_prob(self, value):
        value = jnp.asarray(value, dtype=self.low.dtype)
        # log(in_support ? 1 : 0) - log(high-low): -inf outside the support
        # (the reference's lb*ub mask, distributions.py:221-233, but with an
        # inclusive lower bound — sample() can return exactly `low`)
        inside = (self.low <= value) & (value < self.high)
        return jnp.where(inside, 0.0, -jnp.inf) - jnp.log(self.high - self.low)

    def entropy(self):
        return jnp.broadcast_to(jnp.log(self.high - self.low),
                                self.batch_shape)

    def kl_divergence(self, other):
        if not isinstance(other, Uniform):
            raise TypeError("kl_divergence expects another Uniform")
        # KL(U[a,b] || U[c,d]) = log((d-c)/(b-a)) when [a,b] ⊆ [c,d], ∞ else
        contained = (other.low <= self.low) & (self.high <= other.high)
        kl = (jnp.log(other.high - other.low)
              - jnp.log(self.high - self.low))
        return jnp.where(contained, kl, jnp.inf)


class Normal(Distribution):
    """Normal(loc, scale) (reference ``distributions.py:246``)."""

    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, dtype=jnp.result_type(float))
        self.scale = jnp.asarray(scale, dtype=self.loc.dtype)

    @property
    def batch_shape(self):
        return jnp.broadcast_shapes(self.loc.shape, self.scale.shape)

    def sample(self, shape=(), *, key=None, seed=None):
        shape = tuple(shape) + self.batch_shape
        eps = jax.random.normal(_key(key, seed), shape, dtype=self.loc.dtype)
        return self.loc + eps * self.scale

    def entropy(self):
        # 0.5 + 0.5*log(2π) + log(σ)   (reference distributions.py:356-366)
        return jnp.broadcast_to(0.5 + 0.5 * _LOG_2PI + jnp.log(self.scale),
                                self.batch_shape)

    def log_prob(self, value):
        value = jnp.asarray(value, dtype=self.loc.dtype)
        var = self.scale * self.scale
        return (-((value - self.loc) ** 2) / (2.0 * var)
                - jnp.log(self.scale) - 0.5 * _LOG_2PI)

    def kl_divergence(self, other):
        if not isinstance(other, Normal):
            raise TypeError("kl_divergence expects another Normal")
        # 0.5*(σ²ratio + t1² - 1 - log σ²ratio)  (reference :384-398)
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))


class Categorical(Distribution):
    """Categorical over the trailing axis of ``logits``
    (reference ``distributions.py:401``)."""

    def __init__(self, logits):
        self.logits = jnp.asarray(logits, dtype=jnp.result_type(float))

    @property
    def _log_normalized(self):
        logits = self.logits - jnp.max(self.logits, axis=-1, keepdims=True)
        return logits - jnp.log(
            jnp.sum(jnp.exp(logits), axis=-1, keepdims=True))

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, shape=(), *, key=None, seed=None):
        # beyond-reference: fluid's Categorical has no sample (:401)
        return jax.random.categorical(_key(key, seed), self.logits,
                                      shape=tuple(shape) + self.logits.shape[:-1])

    def log_prob(self, value):
        value = jnp.asarray(value, dtype=jnp.int32)
        return jnp.take_along_axis(self._log_normalized, value[..., None],
                                   axis=-1)[..., 0]

    def entropy(self):
        # -Σ p·(logits - log z), computed max-shifted (reference :477-490).
        # p·log p is defined by continuity as 0 at p=0 — a saturated policy
        # has logp → -inf where exp(logp) → 0, and 0·(-inf) would be NaN.
        # Double-where: the -inf operand must be masked BEFORE the multiply,
        # or the 0·(-inf)=NaN inside the untaken branch poisons gradients
        # (action-masked policies carry -inf logits routinely).
        logp = self._log_normalized
        dead = jnp.isneginf(logp)
        plogp = jnp.exp(logp) * jnp.where(dead, 0.0, logp)
        return -jnp.sum(plogp, axis=-1, keepdims=True)

    def kl_divergence(self, other):
        if not isinstance(other, Categorical):
            raise TypeError("kl_divergence expects another Categorical")
        logp, logq = self._log_normalized, other._log_normalized
        # p=0 terms contribute 0 by continuity (q=0 with p>0 stays +inf);
        # double-where so -inf never meets the multiply (NaN-free grads)
        dead = jnp.isneginf(logp)
        term = jnp.exp(logp) * jnp.where(dead, 0.0, logp - logq)
        return jnp.sum(term, axis=-1, keepdims=True)


class MultivariateNormalDiag(Distribution):
    """Multivariate normal with diagonal covariance
    (reference ``distributions.py:494``).

    ``scale`` is the diagonal covariance matrix, as in the reference (a
    ``[k, k]`` matrix whose off-diagonal entries are ignored — the reference
    masks them with ``_det``/``_inv`` built from ``diag(ones)``).
    """

    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, dtype=jnp.result_type(float))
        self.scale = jnp.asarray(scale, dtype=self.loc.dtype)
        if self.scale.ndim < 2 or self.scale.shape[-1] != self.scale.shape[-2]:
            raise ValueError("scale must be a [k, k] diagonal covariance "
                             f"matrix, got {self.scale.shape}")

    @property
    def _diag(self):
        return jnp.diagonal(self.scale, axis1=-2, axis2=-1)

    def _log_det(self):
        return jnp.sum(jnp.log(self._diag), axis=-1)

    def sample(self, shape=(), *, key=None, seed=None):
        # beyond-reference; covariance diag = σ² ⇒ std = sqrt(diag)
        shape = tuple(shape) + self.loc.shape
        eps = jax.random.normal(_key(key, seed), shape, dtype=self.loc.dtype)
        return self.loc + eps * jnp.sqrt(self._diag)

    def entropy(self):
        k = self.loc.shape[-1]
        return 0.5 * (k * (1.0 + _LOG_2PI) + self._log_det())

    def log_prob(self, value):
        value = jnp.asarray(value, dtype=self.loc.dtype)
        k = self.loc.shape[-1]
        diff = value - self.loc
        maha = jnp.sum(diff * diff / self._diag, axis=-1)
        return -0.5 * (k * _LOG_2PI + self._log_det() + maha)

    def kl_divergence(self, other):
        if not isinstance(other, MultivariateNormalDiag):
            raise TypeError("kl_divergence expects another "
                            "MultivariateNormalDiag")
        # 0.5*(tr(Σq⁻¹Σp) + Δᵀ Σq⁻¹ Δ - k + ln|Σq|/|Σp|)  (reference :575-595)
        dp, dq = self._diag, other._diag
        diff = other.loc - self.loc
        tr = jnp.sum(dp / dq, axis=-1)
        maha = jnp.sum(diff * diff / dq, axis=-1)
        k = self.loc.shape[-1]
        return 0.5 * (tr + maha - k + self._log_det_other(other))

    def _log_det_other(self, other):
        return other._log_det() - self._log_det()


def kl_divergence(p: Distribution, q: Distribution):
    """Functional form: ``kl_divergence(p, q) == p.kl_divergence(q)``."""
    return p.kl_divergence(q)
