"""Stock datasets (parity: ``python/paddle/dataset/`` — mnist, cifar, imdb,
wmt14/16…). This environment has zero network egress, so these are
*synthetic but learnable* generators with the same sample schemas as the
reference loaders: models and tests exercise identical shapes/dtypes.
"""

from __future__ import annotations

import numpy as np


def synthetic_mnist(n=1024, seed=0, template_seed=0):
    """(image[28,28,1] float32, label int64) — mnist schema.

    Learnable structure: each class has a fixed random template (from
    ``template_seed`` — keep it constant across train/eval splits); samples
    are template + noise (from ``seed``), so a LeNet converges quickly.
    """
    rng = np.random.RandomState(template_seed)
    templates = rng.randn(10, 28, 28, 1).astype(np.float32)

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            label = r.randint(0, 10)
            img = templates[label] + 0.3 * r.randn(28, 28, 1).astype(np.float32)
            yield img.astype(np.float32), np.int64(label)

    return reader


def synthetic_imagenet(n=256, image_size=224, num_classes=1000, seed=0):
    """(image[H,W,3] float32, label int64) — flowers/imagenet schema."""
    rng = np.random.RandomState(seed)
    means = rng.randn(num_classes, 1, 1, 3).astype(np.float32)

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            label = r.randint(0, num_classes)
            img = means[label] + r.randn(image_size, image_size, 3).astype(np.float32)
            yield img.astype(np.float32), np.int64(label)

    return reader


def synthetic_lm(n=512, seq_len=128, vocab=1024, seed=0):
    """(token_ids[L] int32,) — language-model schema (wmt/imdb analog).
    Markov-chain structure so next-token prediction is learnable."""
    rng = np.random.RandomState(seed)
    # sparse transition preference: each token has 4 likely successors
    succ = rng.randint(0, vocab, (vocab, 4))

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            ids = np.empty(seq_len, np.int32)
            ids[0] = r.randint(0, vocab)
            for t in range(1, seq_len):
                if r.rand() < 0.8:
                    ids[t] = succ[ids[t - 1], r.randint(0, 4)]
                else:
                    ids[t] = r.randint(0, vocab)
            yield (ids,)

    return reader


def synthetic_ctr(n=2048, num_sparse_fields=26, num_dense=13,
                  vocab_per_field=1000, seed=0):
    """(dense[13] float32, sparse_ids[26] int64, label int64) — criteo/DeepFM
    schema (reference ctr_reader / dist_ctr.py)."""
    rng = np.random.RandomState(seed)
    field_w = rng.randn(num_sparse_fields).astype(np.float32)
    dense_w = rng.randn(num_dense).astype(np.float32)

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            dense = r.randn(num_dense).astype(np.float32)
            ids = r.randint(0, vocab_per_field, num_sparse_fields).astype(np.int64)
            logit = dense @ dense_w / 4 + ((ids % 7 == 0) * field_w).sum()
            label = np.int64(1 / (1 + np.exp(-logit)) > r.rand())
            yield dense, ids, label

    return reader
