"""Kernel-registry lint: contracts vs lowered HLO, and bypass detection.

Two rules, both wired into ``tools/graph_lint.py``'s framework preset
(so ``tools/run_ci.sh`` gates on them):

- ``kernel-contract`` — for every registered kernel, verify the
  *declared* contract against what actually lowers: the lax fallback
  and the Pallas body must agree on abstract output shape/dtype; sample
  inputs must match the declared layouts' ranks; kernels whose contract
  marks buffers donation-safe must really alias them in the lowered
  HLO (``tf.aliasing_output`` on the donation probe — the serving
  engine's page-donation contract, checked in real StableHLO, not by
  convention); single-device kernels must lower with ZERO collectives;
  and the autotuner's resolved blocks must come from the contract's
  candidate set.
- ``kernel-registry-bypass`` — an AST scan over ``paddle_tpu/ops``,
  ``paddle_tpu/parallel`` and ``paddle_tpu/serving``: every function
  containing a ``pallas_call`` must be a ``pallas_sites`` entry of some
  registered kernel. Deliberate exceptions live in
  ``tools/kernel_registry_allowlist.txt``; entries that match no
  Pallas site are themselves an error (stale allowlist entries rot
  exactly like stale suppressions).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence

import jax

from paddle_tpu.analysis.findings import Finding, Report
from paddle_tpu.kernels import autotune as _autotune
from paddle_tpu.kernels import registry as _registry

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_SCAN_ROOTS = ("ops", "parallel", "serving")
DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(_PKG_ROOT), "tools",
                                 "kernel_registry_allowlist.txt")


def _layout_rank(layout: str) -> Optional[int]:
    """``"(P,ps,H,Dh)" -> 4``; None when the layout is not dimensioned."""
    if "(" not in layout:
        return None
    body = layout[layout.index("(") + 1:layout.index(")")]
    return len([p for p in body.split(",") if p.strip()])


def _abstract(args):
    return tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                 if hasattr(a, "shape") else a for a in args)


def contract_findings(spec, tuner=None) -> List[Finding]:
    """Verify one kernel's declared contract (see module docstring)."""
    out: List[Finding] = []
    loc = f"kernels/{spec.name}"

    def bad(msg, fix=""):
        out.append(Finding("kernel-contract", "error", msg, location=loc,
                           fix=fix, engine="plan"))

    args, kwargs = spec.sample_inputs(0)

    # 1. declared layouts vs sample-input ranks (insertion order)
    for (arg_name, layout), a in zip(spec.contract.arg_layouts.items(),
                                     args):
        rank = _layout_rank(layout)
        if rank is not None and hasattr(a, "ndim") and a.ndim != rank:
            bad(f"arg {arg_name!r} declared {layout} (rank {rank}) but "
                f"sample input has rank {a.ndim}",
                fix="fix the contract's arg_layouts or the kernel's "
                    "sample_inputs — they are the same declared surface")

    # 2. autotuner blocks must come from the declared candidate set
    blocks = (tuner or _autotune.KernelTuner(path=None)).get(
        spec, args, kwargs)
    for bname, bval in blocks.items():
        cands = spec.contract.block_candidates.get(bname)
        if cands is None or bval not in cands:
            bad(f"autotuner resolved {bname}={bval}, outside the "
                f"contract's candidates {cands}",
                fix="extend block_candidates or fix the prior")

    if spec.parity_fn is not None:
        # mesh kernels: the parity battery orchestrates the numerics;
        # the donation contract and the DECLARED-collective lowering
        # (e.g. the tp wrappers' single attention-output all_reduce)
        # are still verified on the probe's real sharded lowering
        _donation_findings(spec, bad, check_collectives=True)
        return out

    # 3. lax fallback and Pallas body agree on abstract output
    abstract = _abstract(args)
    try:
        lax_shape = jax.eval_shape(
            lambda *a: spec.lax_fn(*a, **kwargs), *abstract)
        pal_shape = jax.eval_shape(
            lambda *a: spec.pallas_fn(*a, block_sizes=blocks,
                                      interpret=True, **kwargs),
            *abstract)
        lax_flat = [(s.shape, str(s.dtype))
                    for s in jax.tree_util.tree_leaves(lax_shape)]
        pal_flat = [(s.shape, str(s.dtype))
                    for s in jax.tree_util.tree_leaves(pal_shape)]
        if lax_flat != pal_flat:
            bad(f"lax fallback lowers to {lax_flat} but the Pallas body "
                f"lowers to {pal_flat}",
                fix="the two impls are one contract: align their "
                    "output layouts")
    except Exception as e:
        bad(f"abstract evaluation failed: {type(e).__name__}: {e}")

    # 4. single-device kernels must lower with zero collectives
    try:
        from paddle_tpu.analysis import estimate_cost
        cost = estimate_cost(lambda *a: spec.lax_fn(*a, **kwargs),
                             *abstract, name=spec.name)
        if cost.collectives:
            kinds = sorted(cost.collective_kinds())
            bad(f"single-device kernel lowers collectives {kinds}",
                fix="a kernel that syncs devices must be registered "
                    "requires_mesh with a declared collective set")
    except Exception as e:
        bad(f"cost lowering failed: {type(e).__name__}: {e}")

    # 5. donation contract vs real HLO aliasing
    _donation_findings(spec, bad, check_collectives=False)
    return out


def _donation_findings(spec, bad, *, check_collectives):
    """Lower the kernel's donation probe and verify (a) the contract's
    donatable buffers really alias in HLO — ``tf.aliasing_output`` on a
    single-device lowering, ``jax.buffer_donor`` under SPMD (the
    partitioner defers the aliasing decision, jax marks the donor) —
    and (b), for mesh kernels, that EXACTLY the contract's declared
    collective kinds lower (the tp wrappers' "one attention-output
    collective" assertion). A mesh probe returning None means the box
    cannot host the mesh: skipped, not failed."""
    if spec.contract.donatable and spec.donation_probe is None:
        bad("contract declares donatable buffers but registers no "
            "donation_probe to verify them against lowered HLO")
    if spec.donation_probe is None:
        return
    try:
        probe = spec.donation_probe()
    except Exception as e:
        bad(f"donation probe construction failed: "
            f"{type(e).__name__}: {e}")
        return
    if probe is None:      # mesh kernel on a too-small box
        return
    fn, pargs, donate = probe
    try:
        txt = jax.jit(fn, donate_argnums=donate).lower(
            *pargs).as_text()
        aliased = (txt.count("tf.aliasing_output")
                   + txt.count("jax.buffer_donor"))
        if aliased < len(donate):
            bad(f"contract marks {spec.contract.donatable} "
                f"donation-safe but the lowered probe aliases only "
                f"{aliased}/{len(donate)} donated buffers",
                fix="something in the kernel breaks XLA's aliasing "
                    "(e.g. a dtype round-trip); fix it or drop the "
                    "donatable declaration")
    except Exception as e:
        bad(f"donation probe failed to lower: "
            f"{type(e).__name__}: {e}")
        return
    if not check_collectives:
        return
    try:
        from paddle_tpu.analysis import estimate_cost
        cost = estimate_cost(fn, *_abstract(pargs), name=spec.name)
        kinds = sorted(cost.collective_kinds())
        declared = sorted(set(spec.contract.collectives))
        if kinds != declared:
            bad(f"probe lowers collective kinds {kinds}, contract "
                f"declares exactly {declared}",
                fix="a sharded kernel's collective set IS its contract: "
                    "fix the kernel or the declaration")
    except Exception as e:
        bad(f"probe collective lowering failed: "
            f"{type(e).__name__}: {e}")


# ---------------------------------------------------------------------------
# pallas_call bypass scan
# ---------------------------------------------------------------------------

def _pallas_sites_in_file(path: str, module: str) -> List[str]:
    """``module:function`` for every function in ``path`` whose body
    contains a ``pallas_call`` invocation."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    sites = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack: List[str] = []

        def _visit_fn(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = visit_AsyncFunctionDef = _visit_fn

        def visit_Call(self, node):
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else "")
            if name == "pallas_call" and self.stack:
                site = f"{module}:{self.stack[0]}"
                if site not in sites:
                    sites.append(site)
            self.generic_visit(node)

    V().visit(tree)
    return sites


def load_allowlist(path: str) -> List[str]:
    entries = []
    if path and os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if line:
                    entries.append(line)
    return entries


def bypass_findings(roots: Sequence[str] = DEFAULT_SCAN_ROOTS,
                    allowlist_path: Optional[str] = None
                    ) -> List[Finding]:
    """Every pallas_call site under ``roots`` must be registered (a
    spec's ``pallas_sites`` entry) or deliberately allowlisted.
    ``allowlist_path=None`` uses the committed default."""
    allowlist_path = allowlist_path or DEFAULT_ALLOWLIST
    _registry.load_all()
    registered = _registry.all_pallas_sites()
    allow = load_allowlist(allowlist_path)
    used_allow: set = set()
    out: List[Finding] = []
    for root in roots:
        base = os.path.join(_PKG_ROOT, root)
        for dirpath, _dirs, files in os.walk(base):
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, os.path.dirname(_PKG_ROOT))
                module = rel[:-3].replace(os.sep, ".")
                for site in _pallas_sites_in_file(path, module):
                    if site in registered:
                        continue
                    if site in allow:
                        used_allow.add(site)
                        continue
                    out.append(Finding(
                        "kernel-registry-bypass", "error",
                        f"pallas_call in {site} bypasses the kernel "
                        "registry: no registered kernel claims this "
                        "site", location=site,
                        fix="register the kernel in paddle_tpu/kernels "
                            "(pallas_sites=...) or add a justified "
                            "entry to tools/"
                            "kernel_registry_allowlist.txt",
                        engine="ast"))
    for entry in allow:
        if entry not in used_allow:
            out.append(Finding(
                "kernel-registry-bypass", "error",
                f"stale allowlist entry {entry!r} matches no pallas_call "
                "site", location=allowlist_path,
                fix="delete it — dead entries would silently re-accept "
                    "a future bypass", engine="ast"))
    return out


def lint_registry(suppressions=None,
                  allowlist_path: Optional[str] = None) -> Report:
    """The full kernel-registry report: per-kernel contract checks +
    the bypass scan (``tools/graph_lint.py`` preset surface)."""
    _registry.load_all()
    report = Report("kernel_registry", suppressions=suppressions)
    tuner = _autotune.KernelTuner(path=None)
    for name in _registry.names():
        report.extend(contract_findings(_registry.get(name), tuner=tuner))
    # the COMMITTED manifest production dispatch resolves from must be
    # valid too: stale versions, unknown kernels, or out-of-candidate
    # blocks (get() refuses them at runtime, but CI should say so)
    committed = _autotune.KernelTuner(_autotune.DEFAULT_CACHE_PATH)
    for key in committed.stale_entries():
        report.add(Finding(
            "kernel-contract", "error",
            f"committed tune-cache entry {key!r} is dead (stale "
            "contract version, unknown kernel, or blocks outside the "
            "candidate set)", location=_autotune.DEFAULT_CACHE_PATH,
            fix="reseed: python -m paddle_tpu.kernels.autotune --seed",
            engine="plan"))
    report.extend(bypass_findings(allowlist_path=allowlist_path))
    report.count_into_registry()
    return report
