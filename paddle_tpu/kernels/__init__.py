"""paddle_tpu.kernels — the shared Pallas kernel layer.

One registry, one autotuner, one interpret/fallback harness for every
Pallas kernel in the framework (flash attention, ring attention, ragged
paged decode, ragged paged prefill — and every variant ROADMAP items 1
and 3 add on top). See the submodule docstrings:

- :mod:`~paddle_tpu.kernels.registry` — kernel contracts + registration
- :mod:`~paddle_tpu.kernels.harness`  — ``dispatch()`` + parity battery
- :mod:`~paddle_tpu.kernels.autotune` — block-size tuner, persisted to
  the committed ``tools/kernel_tune.json``
- :mod:`~paddle_tpu.kernels.lint`     — contract-vs-HLO verification and
  the pallas_call bypass scan (``tools/graph_lint.py`` preset surface)

Kernels register from their home modules at import time;
:func:`load_all` imports them all so tools/tests can iterate the
registry.
"""

from paddle_tpu.kernels.autotune import (DEFAULT_CACHE_PATH, KernelTuner,
                                         default_tuner, seed_entry,
                                         set_default_tuner, static_prior,
                                         tune_key)
from paddle_tpu.kernels.harness import (IMPLS, dispatch, on_tpu,
                                        parity_check, resolve_impl)
from paddle_tpu.kernels.lint import bypass_findings, lint_registry
from paddle_tpu.kernels.registry import (KernelContract, KernelSpec,
                                         all_pallas_sites, get, load_all,
                                         names, register)

__all__ = [
    "DEFAULT_CACHE_PATH", "IMPLS", "KernelContract", "KernelSpec",
    "KernelTuner", "all_pallas_sites", "bypass_findings",
    "default_tuner", "dispatch", "get", "lint_registry", "load_all",
    "names", "on_tpu", "parity_check", "register", "resolve_impl",
    "seed_entry", "set_default_tuner", "static_prior", "tune_key",
]
