"""Shared block-size autotuner: measure-and-cache per (kernel, bucket).

Every registered kernel's tunable block sizes resolve through one
:class:`KernelTuner`:

- **Key** — ``kernel|v<contract-version>|<shape bucket>|<dtype>|<device
  kind>``. Shape dims are bucketed to the next power of two, so one
  cache entry covers a whole serving bucket family and the key is a
  deterministic function of the *abstract* call signature (tracers
  only contribute shape/dtype — resolution happens at trace time and
  can never retrace a steady-state step).
- **Prior** — on a cache miss the tuner does NOT guess blindly: a
  static prior picks the largest candidate block config whose VMEM
  working set (``spec.vmem_estimate``) fits the per-core budget; the
  offline ``--seed`` CLI additionally lowers the kernel's lax fallback
  through the PR 7 static cost model (:func:`analysis.estimate_cost`)
  and stamps the entry with the measured flops / traffic bytes /
  arithmetic intensity, so the committed cache starts near-optimal and
  CI never tunes from scratch.
- **Measurement** — :meth:`KernelTuner.measure` times each candidate on
  the live backend (``bench.py --model kernels``) and caches the best.
- **Persistence** — ``tools/kernel_tune.json`` is committed the way
  ``api_spec.txt`` is: regenerate with
  ``python -m paddle_tpu.kernels.autotune --seed`` and commit alongside
  any PR that changes a kernel's contract version or candidate set.
  Entries whose ``contract_version`` no longer matches the registered
  kernel are *stale*: detected, counted, and ignored (a cold cache is
  correct, just slower to warm).
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import jax

from paddle_tpu.kernels import registry as _registry

#: committed cache (kept beside api_spec/cost_budgets — tools/ is the
#: home of every frozen-artifact manifest)
DEFAULT_CACHE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tools", "kernel_tune.json")

#: per-core VMEM budget the static prior fits blocks into; TPU cores
#: have ~16 MiB — leave headroom for double buffering
VMEM_BUDGET_BYTES = 12 << 20

_SCHEMA_VERSION = 1


def next_pow2(n: int) -> int:
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


def device_kind() -> str:
    try:
        return jax.devices()[0].device_kind.replace(" ", "_")
    except RuntimeError:  # pragma: no cover - no backend at all
        return "unknown"


def tune_key(spec, args, kwargs) -> str:
    """Deterministic cache key for one abstract call signature."""
    if spec.tune_signature is not None:
        dims = spec.tune_signature(args, kwargs)
    else:
        dims = tuple(
            (f"a{i}d{j}", d)
            for i, a in enumerate(args) if hasattr(a, "shape")
            for j, d in enumerate(a.shape))
    bucket = "x".join(f"{label}{next_pow2(d)}" for label, d in dims)
    dtypes = "-".join(sorted({str(a.dtype) for a in args
                              if hasattr(a, "dtype")}))
    return (f"{spec.name}|v{spec.contract.version}|{bucket}|{dtypes}|"
            f"{device_kind()}")


def candidate_grid(contract) -> Tuple[Dict[str, int], ...]:
    """Every block config in the contract's candidate cartesian."""
    names = sorted(contract.block_candidates)
    if not names:
        return ({},)
    return tuple(dict(zip(names, vals)) for vals in itertools.product(
        *(contract.block_candidates[n] for n in names)))


def static_prior(spec, args, kwargs,
                 budget_bytes: int = VMEM_BUDGET_BYTES) -> Dict[str, int]:
    """Largest candidate block config whose VMEM working set fits the
    budget — the 'start near-optimal' seed for the measured search.
    Host-side and abstract-shape-only, so it is safe at trace time."""
    if not spec.contract.block_candidates:
        return {}

    def score(cand):
        s = 1
        for v in cand.values():
            s *= int(v)
        return s

    grid = candidate_grid(spec.contract)
    fits = []
    for cand in grid:
        if spec.vmem_estimate is not None:
            try:
                vmem = int(spec.vmem_estimate(args, kwargs, cand))
            except Exception:
                continue  # broken estimator reads as does-NOT-fit: an
                # error must never promote the largest working set
            if vmem > budget_bytes:
                continue
        fits.append(cand)
    if fits:
        return dict(max(fits, key=score))
    # nothing fits the budget: take the SMALLEST working set, not the
    # default (which the kernels order largest-first) — when VMEM is the
    # problem, the biggest blocks are the worst possible guess
    return dict(min(grid, key=score))


class KernelTuner:
    """Measure-and-cache block sizes, persisted like api_spec.txt.

    ``path=None`` is a pure in-memory tuner (tests, bench measuring);
    :func:`default_tuner` wires the committed ``tools/kernel_tune.json``.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.entries: Dict[str, dict] = {}
        self.hits = 0
        self.misses = 0
        self.stale = 0
        if path and os.path.exists(path):
            self.load(path)

    # -- persistence --------------------------------------------------------
    def load(self, path: str):
        with open(path) as f:
            data = json.load(f)
        if int(data.get("schema_version", 0)) != _SCHEMA_VERSION:
            return  # incompatible manifest: treat as cold cache
        self.entries.update(data.get("entries", {}))

    def save(self, path: Optional[str] = None):
        path = path or self.path
        manifest = {
            "_comment": [
                "Committed block-size cache for the shared kernel "
                "autotuner (paddle_tpu/kernels/autotune.py).",
                "Regenerate: python -m paddle_tpu.kernels.autotune "
                "--seed   (static-cost priors, no hardware)",
                "or refresh measured entries via bench.py --model "
                "kernels on the target device.",
                "Keys are kernel|v<contract>|<pow2 bucket>|<dtype>|"
                "<device kind>; entries with a stale contract_version "
                "are ignored at load and should be deleted.",
            ],
            "schema_version": _SCHEMA_VERSION,
            "entries": {k: self.entries[k] for k in sorted(self.entries)},
        }
        with open(path, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=False)
            f.write("\n")

    # -- resolution (trace-time safe) --------------------------------------
    def get(self, spec, args=(), kwargs=None) -> Dict[str, int]:
        """Resolve block sizes for one call signature. Pure host code on
        abstract shapes: called during tracing, never from compiled
        code, so tuning can never cause a steady-state recompile."""
        kwargs = kwargs or {}
        if not spec.contract.block_candidates:
            return {}
        key = tune_key(spec, args, kwargs)
        ent = self.entries.get(key)
        if ent is not None:
            blocks = ent.get("blocks", {})
            valid = (
                int(ent.get("contract_version", -1)) ==
                spec.contract.version
                and all(blocks.get(b) in c for b, c in
                        spec.contract.block_candidates.items()))
            if valid:
                self.hits += 1
                return dict(blocks)
            # version bump OR out-of-candidate blocks (hand-edited /
            # corrupt manifest): the entry is dead — re-derive, never
            # run an out-of-contract block config
            self.stale += 1
        self.misses += 1
        blocks = static_prior(spec, args, kwargs)
        self.entries[key] = {
            "blocks": blocks,
            "source": "prior",
            "contract_version": spec.contract.version,
        }
        return dict(blocks)

    # -- measurement (bench-time only) --------------------------------------
    def measure(self, spec, args, kwargs=None, *, impl: str = "pallas",
                reps: int = 3, candidates=None) -> dict:
        """Time every candidate block config and cache the winner.
        Returns ``{"blocks", "timings_s", "default_blocks",
        "default_s", "best_s"}``. Never called from traced code."""
        from paddle_tpu.kernels import harness
        kwargs = dict(kwargs or {})
        key = tune_key(spec, args, kwargs)
        default = static_prior(spec, args, kwargs)
        timings: Dict[str, float] = {}
        best_blocks, best_t = default, float("inf")
        for cand in (candidates or candidate_grid(spec.contract)):
            t = _time_call(
                lambda: harness.dispatch(spec.name, *args, impl=impl,
                                         block_sizes=cand, **kwargs),
                reps=reps)
            timings[json.dumps(cand, sort_keys=True)] = t
            if t < best_t:
                best_blocks, best_t = dict(cand), t
        self.entries[key] = {
            "blocks": best_blocks,
            "source": "measured",
            "contract_version": spec.contract.version,
            "timings_s": {k: round(v, 6) for k, v in timings.items()},
        }
        return {"blocks": best_blocks, "timings_s": timings,
                "default_blocks": default,
                "default_s": timings.get(
                    json.dumps(default, sort_keys=True), best_t),
                "best_s": best_t}

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "stale": self.stale, "entries": len(self.entries)}

    def stale_entries(self) -> list:
        """Keys that are dead: kernel unknown to the registry, recorded
        contract_version behind the registered contract, or blocks
        outside the contract's candidate set (hand-edited / corrupt
        manifest). THE validity rule — ``get()``, ``purge_stale``, the
        bench gate, and the registry lint all read it; don't re-derive
        it elsewhere."""
        _registry.load_all()
        dead = []
        for key, ent in self.entries.items():
            name = key.split("|", 1)[0]
            try:
                spec = _registry.get(name)
            except KeyError:
                dead.append(key)
                continue
            blocks = ent.get("blocks", {})
            if int(ent.get("contract_version", -1)) != \
                    spec.contract.version or \
                    not all(blocks.get(b) in c for b, c in
                            spec.contract.block_candidates.items()):
                dead.append(key)
        return dead

    def purge_stale(self) -> int:
        """Drop every stale entry (see :meth:`stale_entries`); returns
        how many were dropped. ``--seed`` calls this so a contract-
        version bump + reseed really clears the stale-entry CI gate
        (old-version keys would otherwise persist forever)."""
        dead = self.stale_entries()
        for key in dead:
            del self.entries[key]
        return len(dead)


def _time_call(fn, reps: int) -> float:
    out = fn()
    jax.block_until_ready(out)        # warmup compile excluded
    t0 = time.perf_counter()
    for _ in range(max(reps, 1)):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / max(reps, 1)


_DEFAULT: Optional[KernelTuner] = None


def default_tuner() -> KernelTuner:
    """Process-wide tuner over the committed cache."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = KernelTuner(DEFAULT_CACHE_PATH)
    return _DEFAULT


def set_default_tuner(tuner: Optional[KernelTuner]) -> Optional[KernelTuner]:
    """Swap the process-wide tuner (tests); returns the previous one."""
    global _DEFAULT
    prev, _DEFAULT = _DEFAULT, tuner
    return prev


# ---------------------------------------------------------------------------
# offline seeding: static-cost priors from the PR 7 cost model
# ---------------------------------------------------------------------------

def seed_entry(tuner: KernelTuner, spec, args, kwargs=None) -> str:
    """Seed one bucket's entry with the VMEM-fit prior, stamped with the
    lax fallback's static CostReport (flops / traffic bytes /
    arithmetic intensity) so the committed cache records WHY the prior
    was chosen. Lowering only — nothing executes."""
    kwargs = dict(kwargs or {})
    key = tune_key(spec, args, kwargs)
    existing = tuner.entries.get(key)
    if existing is not None and existing.get("source") == "measured" \
            and int(existing.get("contract_version", -1)) == \
            spec.contract.version:
        return key    # a current measured entry beats a re-derived prior
    blocks = static_prior(spec, args, kwargs)
    entry: Dict[str, Any] = {
        "blocks": blocks,
        "source": "prior",
        "contract_version": spec.contract.version,
    }
    try:
        from paddle_tpu import analysis
        abstract = tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype)
            if hasattr(a, "shape") else a for a in args)
        cost = analysis.estimate_cost(
            lambda *a: spec.lax_fn(*a, **kwargs), *abstract,
            name=spec.name)
        entry["cost_prior"] = {
            "flops": int(cost.total_flops),
            "traffic_bytes": int(cost.traffic_bytes),
            "arithmetic_intensity": round(
                cost.total_flops / max(cost.traffic_bytes, 1), 3),
        }
    except Exception as e:  # mesh kernels etc.: prior stands without cost
        entry["cost_prior"] = {"error": f"{type(e).__name__}: {e}"}
    tuner.entries[key] = entry
    return key


def seed_default_buckets(tuner: KernelTuner) -> Dict[str, str]:
    """Seed the canonical serving/training buckets for every registered
    kernel (the shapes the bench and the serving engine actually hit)."""
    _registry.load_all()
    seeded = {}
    for name in _registry.names():
        spec = _registry.get(name)
        if not spec.contract.block_candidates or spec.requires_mesh:
            continue               # mesh kernels inherit the inner kernel
        for seed in (0, 1, 2):     # 3 shape buckets per kernel
            args, kwargs = spec.sample_inputs(seed)
            seeded[seed_entry(tuner, spec, args, kwargs)] = name
        # tp-local twins: the tp-sharded wrappers dispatch THIS kernel
        # per shard at H/tp head counts — those buckets must resolve
        # from the committed manifest too, or every tp mesh starts on
        # an unseeded prior
        for variant in spec.tune_sample_variants:
            for seed in (0, 1, 2):
                sample = variant(seed)
                if sample is None:
                    continue       # head count not divisible by this tp
                v_args, v_kwargs = sample
                seeded[seed_entry(tuner, spec, v_args, v_kwargs)] = name
    return seeded


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Seed/refresh the committed kernel-tune cache")
    ap.add_argument("--seed", action="store_true",
                    help="seed canonical buckets with static-cost priors")
    ap.add_argument("--out", default=DEFAULT_CACHE_PATH)
    args = ap.parse_args(argv)
    if not args.seed:
        ap.error("nothing to do (pass --seed)")
    jax.config.update("jax_platforms", "cpu")  # pure lowering, no TPU
    tuner = KernelTuner(args.out if os.path.exists(args.out) else None)
    tuner.path = args.out
    purged = tuner.purge_stale()
    seeded = seed_default_buckets(tuner)
    tuner.save(args.out)
    print(f"seeded {len(seeded)} bucket(s), purged {purged} stale "
          f"-> {args.out}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys
    sys.exit(main())
