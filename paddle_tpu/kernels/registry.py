"""Kernel registry: one declared contract per Pallas kernel.

Before this layer existed, four hand-tuned Pallas kernels (flash
attention, ring attention, ragged paged decode, ragged paged prefill)
each carried a private block-size heuristic, a private interpret-mode
shim, and a private lax fallback — every new kernel variant (tensor-
parallel sharding, dequant-attend, speculative verify) would have become
a fifth bespoke module. Tensor Processing Primitives (PAPERS.md) argues
for exactly one microkernel-abstraction layer; TPU-MLIR's lowering
discipline motivates checking kernel contracts statically instead of by
convention. This module is that layer's spine:

- :class:`KernelContract` — the *declared* contract: layouts, donation-
  safety, grid/block constraints, tunable block parameters with their
  candidate sets, parity tolerances, and a version (bumped on any
  numerics or layout change — the autotuner rejects stale cache entries
  by it).
- :class:`KernelSpec` — one registered kernel: its Pallas body, its lax
  fallback (identical numerics, runs anywhere), a dense reference for
  the parity battery, a sample-input factory, and the source sites that
  are allowed to contain ``pallas_call`` (``tools/graph_lint.py``'s
  kernel-registry rule fails any Pallas call in ``ops/``, ``parallel/``
  or ``serving/`` outside these sites).
- :func:`register` / :func:`get` / :func:`names` / :func:`load_all` —
  the registry itself. Kernels register from their home modules at
  import time; :func:`load_all` imports every home module so tools and
  tests can iterate the full registry.

Dispatch lives in :mod:`~paddle_tpu.kernels.harness`; block-size
resolution in :mod:`~paddle_tpu.kernels.autotune`.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Callable, Dict, Mapping, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class KernelContract:
    """The declared (statically checkable) contract of one kernel.

    ``version`` participates in every autotuner cache key: bump it when
    the kernel's numerics, layouts, or block semantics change and every
    persisted tuning entry for the old kernel becomes stale (detected,
    reported, and re-derived — never silently reused).
    """

    version: int
    #: arg name -> layout string, e.g. ``"(B,H,S,D)"`` / ``"(S,mp) i32"``
    arg_layouts: Mapping[str, str]
    out_layout: str
    #: args that must stay donation-safe through an update-then-attend
    #: step (the serving engine donates its KV pages INTO the jitted
    #: step that calls this kernel) — verified against the lowered HLO's
    #: ``tf.aliasing_output`` by the kernel-registry lint rule
    donatable: Tuple[str, ...] = ()
    grid: str = ""
    #: tunable block parameter -> candidate values. The static prior
    #: resolves the LARGEST-product candidate that fits the VMEM budget
    #: (smallest when nothing fits) — ordering within the tuple carries
    #: no default semantics; `default_blocks()` (first entry) exists for
    #: display/reference only.
    block_candidates: Mapping[str, Tuple[int, ...]] = \
        dataclasses.field(default_factory=dict)
    #: collective kinds the kernel's lowering may emit (mesh kernels —
    #: e.g. the tp-sharded serving wrappers declare ("all_reduce",),
    #: the one attention-output collective). The kernel-contract lint
    #: lowers the donation probe and asserts EXACTLY these kinds
    #: appear; () keeps the single-device zero-collective contract.
    collectives: Tuple[str, ...] = ()
    #: parity-battery tolerances (pallas-interpret vs lax vs reference)
    atol: float = 1e-5
    rtol: float = 1e-5

    def default_blocks(self) -> Dict[str, int]:
        return {k: v[0] for k, v in self.block_candidates.items()}


@dataclasses.dataclass
class KernelSpec:
    """One registered kernel behind the shared dispatch/fallback layer.

    ``pallas_fn(*args, block_sizes=..., interpret=..., **kw)`` runs the
    Pallas body (interpret mode reuses the SAME body on CPU);
    ``lax_fn(*args, **kw)`` is the XLA-composed fallback with identical
    numerics; ``reference_fn`` is the dense reference the parity battery
    compares both against. ``sample_inputs(seed)`` returns
    ``(args, kwargs)`` small enough for CPU CI.
    """

    name: str
    contract: KernelContract
    pallas_fn: Callable[..., Any]
    lax_fn: Callable[..., Any]
    reference_fn: Callable[..., Any]
    sample_inputs: Callable[[int], Tuple[tuple, dict]]
    #: ``"module:function"`` sites allowed to contain ``pallas_call``
    pallas_sites: Tuple[str, ...] = ()
    #: needs a device mesh (parity/lint run it under one; the bench may
    #: skip it on single-device boxes)
    requires_mesh: bool = False
    #: dims of the tuning key, derived from the call args:
    #: ``tune_signature(args, kwargs) -> ((label, int_dim), ...)``
    tune_signature: Optional[Callable[..., Tuple[Tuple[str, int], ...]]] = \
        None
    #: VMEM working-set estimate (bytes) for a candidate block config —
    #: the static prior picks the largest candidate that fits budget
    vmem_estimate: Optional[Callable[..., int]] = None
    #: optional ``() -> (fn, args, donate_argnums)`` probe lowered by the
    #: lint rule to verify the donation contract in real HLO (and, for
    #: mesh kernels, that exactly the contract's declared ``collectives``
    #: lower). A mesh kernel's probe may return None when the box cannot
    #: host the mesh (single-device CI) — the check is skipped, not failed
    donation_probe: Optional[Callable[[], Optional[Tuple[
        Callable, tuple, Tuple[int, ...]]]]] = None
    #: extra ``seed -> (args, kwargs) | None`` sample factories the
    #: offline ``--seed`` CLI tunes IN ADDITION to ``sample_inputs`` —
    #: the tp-sharded wrappers dispatch this kernel per shard at H/tp
    #: head counts, and these keep the committed manifest covering
    #: those buckets (None = the variant does not apply to that seed)
    tune_sample_variants: Tuple[Callable[[int], Optional[Tuple[
        tuple, dict]]], ...] = ()
    #: optional custom parity check ``(seed) -> {impl: max_abs_err}``
    #: (mesh kernels need their own orchestration)
    parity_fn: Optional[Callable[[int], Dict[str, float]]] = None


_REGISTRY: Dict[str, KernelSpec] = {}

#: home modules that register kernels at import time
_HOME_MODULES = (
    "paddle_tpu.ops.attention",
    "paddle_tpu.serving.decode_attention",
    "paddle_tpu.parallel.ring_attention",
)


def register(spec: KernelSpec) -> KernelSpec:
    """Idempotent (module reloads re-register the same spec)."""
    _REGISTRY[spec.name] = spec
    return spec


def get(name: str) -> KernelSpec:
    if name not in _REGISTRY:
        load_all()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"no kernel {name!r} registered "
                       f"(have: {', '.join(sorted(_REGISTRY)) or 'none'})")


def names() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def load_all() -> Tuple[str, ...]:
    """Import every kernel home module (registration is an import-time
    side effect there) and return the registered names."""
    for mod in _HOME_MODULES:
        importlib.import_module(mod)
    return names()


def all_pallas_sites() -> Dict[str, str]:
    """``"module:function" -> kernel name`` over the whole registry —
    the allow-set the kernel-registry lint rule checks Pallas call
    sites against."""
    sites: Dict[str, str] = {}
    for spec in _REGISTRY.values():
        for site in spec.pallas_sites:
            sites[site] = spec.name
    return sites
