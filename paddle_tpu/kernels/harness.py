"""Shared dispatch / interpret / fallback harness for registered kernels.

Replaces the four private ``_on_tpu()`` + impl-string shims the flash,
ring, decode, and prefill kernels each carried. One entry point:

    out = dispatch("flash_attention", q, k, v, bias,
                   impl="auto", causal=True)

``impl`` is canonical across every kernel:

- ``"auto"``    — Pallas on TPU, lax fallback elsewhere;
- ``"pallas"``  — the compiled Pallas body (TPU);
- ``"pallas_interpret"`` — the SAME Pallas body run by the interpreter
  (CPU tier-1 tests exercise the real kernel logic);
- ``"lax"``     — the XLA-composed fallback (identical numerics).

For Pallas impls the tunable block sizes resolve through the shared
autotuner (:func:`~paddle_tpu.kernels.autotune.default_tuner`) at trace
time — pure host code over abstract shapes, so an autotuner cache update
can never retrace a compiled steady-state step.

The parity battery (:func:`parity_check`) is the one harness every
registered kernel must pass: pallas-interpret vs lax fallback vs dense
reference on the kernel's own sample inputs, at the contract's declared
tolerances.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np

from paddle_tpu.kernels import autotune as _autotune
from paddle_tpu.kernels import registry as _registry

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

IMPLS = ("auto", "pallas", "pallas_interpret", "lax")


def on_tpu() -> bool:
    """THE TPU probe (was private in four modules)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:  # pragma: no cover
        return False


def resolve_impl(impl: str) -> str:
    """Canonical impl name -> concrete backend for this process."""
    if impl not in IMPLS:
        raise ValueError(f"unknown impl {impl!r} (expected "
                         f"{'|'.join(IMPLS)})")
    if impl == "auto":
        return "pallas" if (pltpu is not None and on_tpu()) else "lax"
    if impl in ("pallas", "pallas_interpret") and pltpu is None:
        raise RuntimeError("Pallas TPU backend unavailable in this jax "
                           "install; use impl='lax'")
    return impl


def dispatch(name: str, *args, impl: str = "auto",
             block_sizes: Optional[Dict[str, int]] = None,
             tuner: Optional["_autotune.KernelTuner"] = None, **kwargs):
    """Run kernel ``name`` through its registered contract.

    ``block_sizes`` overrides the autotuner (bench sweeps); ``tuner``
    overrides the process-wide cache (tests)."""
    spec = _registry.get(name)
    concrete = resolve_impl(impl)
    if concrete == "lax":
        return spec.lax_fn(*args, **kwargs)
    if block_sizes is None:
        block_sizes = (tuner or _autotune.default_tuner()).get(
            spec, args, kwargs)
    return spec.pallas_fn(*args, block_sizes=dict(block_sizes),
                          interpret=concrete == "pallas_interpret",
                          **kwargs)


def parity_check(name: str, seed: int = 0) -> Dict[str, float]:
    """Run one kernel's parity battery: pallas-interpret and the lax
    fallback against the dense reference on the kernel's sample inputs.
    Returns ``{impl: max_abs_err}``; raises AssertionError outside the
    contract's tolerances."""
    spec = _registry.get(name)
    if spec.parity_fn is not None:     # mesh kernels orchestrate themselves
        return spec.parity_fn(seed)
    args, kwargs = spec.sample_inputs(seed)
    ref = np.asarray(spec.reference_fn(*args, **kwargs), np.float32)
    errs: Dict[str, float] = {}
    for impl in ("lax", "pallas_interpret"):
        out = np.asarray(dispatch(name, *args, impl=impl, **kwargs),
                         np.float32)
        np.testing.assert_allclose(
            out, ref, atol=spec.contract.atol, rtol=spec.contract.rtol,
            err_msg=f"{name}[{impl}] diverged from the dense reference")
        errs[impl] = float(np.max(np.abs(out - ref))) if ref.size else 0.0
    return errs
