"""Version-compat shims over moving JAX APIs.

The repo targets the newest public spellings; older jaxlibs (like the
pinned 0.4.x here) keep working through these fallbacks so the same code
runs on both sides of a JAX upgrade.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` (new) / ``jax.experimental.shard_map`` (old).

    ``check_vma=False`` (new name) == ``check_rep=False`` (old name):
    these wrappers take logically-replicated inputs whose axis-invariance
    the varying-axes checker cannot prove.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def axis_size(axis) -> int:
    """``jax.lax.axis_size`` (new) / ``jax.core.axis_frame`` (old): the
    STATIC size of a mapped mesh axis from inside shard_map/pmap —
    callers use it in Python control flow (``range(n)``), so it must be
    a concrete int, not a traced psum."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    frame = jax.core.axis_frame(axis)
    return frame if isinstance(frame, int) else frame.size
