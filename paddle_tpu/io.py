"""Save/load: parameters, training state, inference artifacts.

Reference mapping (SURVEY.md §5.4):
- ``save_op.cc``/``load_op.cc`` + ``io.py save_persistables:496`` →
  :func:`save_params` / :func:`load_params` (whole param pytree, one file,
  like save_combine_op).
- ``save_inference_model:974`` (prunes program to feed/fetch, serializes
  ProgramDesc) → :func:`save_inference_model` (serializes StableHLO of the
  jitted forward + params) in paddle_tpu.inference.
- Async sharded checkpointing for the distributed/large case
  (≙ checkpoint_notify + pserver shard snapshots): :class:`CheckpointManager`,
  a thin compatibility facade over
  :class:`paddle_tpu.resilience.snapshot.SnapshotEngine` — per-host shard
  files, background writes, hash-verified atomic manifests.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import numpy as np


# marker KEY for empty dict nodes: without it, a state containing an
# empty container (e.g. SGD's opt slots {}) silently CHANGES pytree
# structure across save/load — which then breaks jit caches / pjit
# sharding prefixes on resume. The marker lives in the KEY namespace
# (\x00 cannot appear in a normal field name), so no leaf VALUE can
# collide with it.
_EMPTY_KEY = "\x00empty"


def _flatten(tree, prefix=()):
    if isinstance(tree, dict):
        if not tree:
            return {"/".join(prefix + (_EMPTY_KEY,)): np.int8(0)}
        out = {}
        for k in sorted(tree):
            out.update(_flatten(tree[k], prefix + (str(k),)))
        return out
    return {"/".join(prefix): tree}


def _unflatten(flat):
    tree: dict = {}
    for key, val in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        if parts[-1] == _EMPTY_KEY:
            continue  # the walk above materialized the empty dict
        node[parts[-1]] = val
    return tree


def save_params(params: Any, path: str):
    """Persist a param/state pytree (save_persistables parity). Arrays are
    pulled to host; bf16 preserved via ml_dtypes numpy arrays."""
    flat = _flatten(jax.device_get(params))
    flat = {k: np.asarray(v) for k, v in flat.items()}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(flat, f, protocol=pickle.HIGHEST_PROTOCOL)


def load_params(path: str, target: Optional[Any] = None) -> Any:
    """Load a pytree saved by save_params. With ``target``, validates that
    shapes/keys match and preserves the target's structure ordering."""
    with open(path, "rb") as f:
        flat = pickle.load(f)
    tree = _unflatten(flat)
    if target is not None:
        tflat = _flatten(target)
        missing = set(tflat) - set(flat)
        extra = set(flat) - set(tflat)
        if missing or extra:
            raise ValueError(
                f"checkpoint mismatch: missing={sorted(missing)[:5]} "
                f"extra={sorted(extra)[:5]}")
        for k, v in tflat.items():
            if hasattr(v, "shape") and tuple(np.shape(flat[k])) != tuple(v.shape):
                raise ValueError(f"shape mismatch for {k}: "
                                 f"{np.shape(flat[k])} vs {v.shape}")
    return tree


save_persistables = save_params
load_persistables = load_params


class CheckpointManager:
    """Async, versioned, multi-host-safe checkpointing (≙ the reference's
    checkpoint_notify + FleetWrapper::SaveModel world).

    Compatibility facade: the engine underneath is
    :class:`paddle_tpu.resilience.snapshot.SnapshotEngine` — per-host
    sharded writes on a background thread, two-phase atomic manifest
    commit, hash-verified restore that skips torn/corrupt saves. This
    class only adds the historical ``save_interval_steps`` gating and the
    orbax-era method names (``save/restore/latest_step/wait/close``,
    ``.manager`` exposing ``all_steps()``)."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        from paddle_tpu.resilience.snapshot import SnapshotEngine

        self.directory = (directory if "://" in directory
                          else os.path.abspath(directory))
        self.manager = SnapshotEngine(self.directory,
                                      max_to_keep=max_to_keep)
        self._interval = max(1, int(save_interval_steps))
        # interval gating uses a cached high-water mark: latest_step()
        # hash-verifies every kept snapshot, far too heavy per-step
        self._last_saved: Optional[int] = None

    def save(self, step: int, state: Any, wait: bool = False,
             force: bool = False) -> bool:
        """``force=True`` bypasses save_interval_steps gating — required for
        the final end-of-fit save, which the interval gate otherwise drops
        when the last step is not on an interval boundary. Returns whether
        a save was actually started."""
        last = self._last_saved
        if last is None:
            # gating only needs the step NUMBER — skip hash verification
            # (a full read of every kept snapshot) on the training thread
            last = self._last_saved = self.manager.latest_step(verify=False)
        if not force and self._interval > 1 and last is not None \
                and step - last < self._interval:
            return False
        self.manager.save(step, state, wait=wait)
        self._last_saved = step
        return True

    def restore(self, step: Optional[int] = None, target: Optional[Any] = None,
                shardings: Optional[Any] = None):
        """Load the newest VALID snapshot (or ``step``), as host numpy
        trees; integrity is verified before any bytes are trusted.
        ``shardings`` (pytree of ``jax.sharding.Sharding``) switches to
        the sharded read path: only this host's addressable shard slices
        are materialized, directly onto device placements."""
        return self.manager.restore(step, target=target,
                                    shardings=shardings)

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    @property
    def last_saved_step(self) -> Optional[int]:
        """High-water mark of saves issued THROUGH this manager (cheap
        committed-manifest scan on first use; no hash pass). The
        end-of-fit duplicate-save guard reads this instead of re-
        verifying every kept snapshot."""
        if self._last_saved is None:
            self._last_saved = self.manager.latest_step(verify=False)
        return self._last_saved

    def latest_valid_manifest(self) -> Optional[dict]:
        return self.manager.latest_valid_manifest()

    def wait(self):
        self.manager.wait_until_finished()

    def close(self):
        self.manager.close()
