"""Finding records, reports, and suppressions — the lint reporting spine.

Every analysis engine (the jaxpr analyzer, the AST linter) emits
:class:`Finding` records into one :class:`Report`; the report renders as
text or JSON, counts findings into the observability registry
(``analysis_findings_total{rule,severity}``), and applies a committed
:class:`Suppressions` file so known-accepted warnings don't fail CI.

Reference mapping: the reference framework's correctness tooling is all
*runtime* (``FLAGS_check_nan_inf`` re-validates every op output as it
executes, operator.cc:35); this is the static half — hazards visible in
the traced program are reported before a step runs, with the same
"rule id + location + hint" shape as compiler diagnostics.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, List, Optional, Sequence, Tuple

SEVERITIES = ("info", "warning", "error")

#: rule id -> (severity, one-line description) — the registry of every
#: rule either engine can emit; docs and the CLI ``--list-rules`` read it.
RULES = {
    "host-callback": (
        "error",
        "pure_callback/io_callback in the traced step: every call is a "
        "device->host->device round trip on the hot path"),
    "debug-callback": (
        "warning",
        "debug_callback (jax.debug.print/callback) in the traced step: "
        "fine for debugging, a host sync in production"),
    "f64-promotion": (
        "warning",
        "float64/complex128 values in the traced step: TPUs emulate f64 "
        "(~10x slow); usually an accidental weak-type promotion"),
    "undonated-buffer": (
        "warning",
        "large input buffers with same-shape outputs are not donated: "
        "peak HBM holds old+new copies of the state"),
    "prng-key-reuse": (
        "error",
        "one PRNG key feeds >=2 random draws with no split/fold_in "
        "between: the draws are correlated (identical streams)"),
    "replicated-large": (
        "warning",
        "large array replicated on every device under the given sharding "
        "plan: HBM cost is multiplied by the mesh size"),
    "ast-host-sync": (
        "warning",
        "host-sync Python call (.item()/float()/np.asarray/time.time()/"
        "stdlib random) inside jit-reachable code"),
    "ast-tracer-branch": (
        "error",
        "Python if/while on a tracer value inside jit-reachable code: "
        "trace-time crash (ConcretizationTypeError) or silent retrace"),
    "unexpected-collective": (
        "error",
        "a collective op (all-reduce/all-gather/...) in the lowered HLO "
        "outside the declared allowlist: an implicit cross-device sync "
        "on every step (single-device serving steps must have zero)"),
    "resharding-churn": (
        "warning",
        "adjacent sharding annotations disagree on a large value's "
        "layout: the compiler inserts an implicit transpose/all-to-all "
        "between them on every step"),
    "peak-hbm-budget": (
        "error",
        "the lowered program's static peak-HBM estimate exceeds the "
        "preset's declared budget: the step may OOM (or silently evict) "
        "on hardware the budget was sized for"),
    "bucket-coverage": (
        "error",
        "a statically reachable pow2 bucket signature is missing from "
        "warmup's precompile plan: the first request hitting it "
        "recompiles mid-serving (breaks the zero-recompile invariant)"),
    "cost-regression": (
        "error",
        "a static cost metric (flops / peak-HBM / collective bytes) "
        "regressed beyond tolerance vs the committed baseline "
        "(tools/cost_budgets.json)"),
    "kernel-contract": (
        "error",
        "a registered kernel's declared contract (layouts, donation-"
        "safety, block candidates, zero-collective lowering) disagrees "
        "with what the lowered HLO actually does "
        "(paddle_tpu/kernels/lint.py)"),
    "kernel-registry-bypass": (
        "error",
        "a pallas_call in ops/, parallel/ or serving/ belongs to no "
        "registered kernel (and is not allowlisted in tools/"
        "kernel_registry_allowlist.txt): bespoke kernels bypass the "
        "shared autotuner, fallback harness, and parity battery"),
    "unguarded-access": (
        "error",
        "a @guarded_by field is read or written outside a `with "
        "self.<lock>:` scope (one level of intra-class call "
        "propagation): a data race once a second thread exists"),
    "lock-order-cycle": (
        "error",
        "the static lock-acquisition graph has a cycle: two threads "
        "taking the locks in opposite order deadlock"),
    "double-acquire": (
        "error",
        "a non-reentrant threading.Lock is acquired on a path that "
        "already holds it: guaranteed same-thread deadlock"),
    "lock-order-drift": (
        "error",
        "the extracted lock universe / acquisition edges differ from "
        "the committed tools/lock_order.json (missing, orphaned, or "
        "stale entries): regenerate with --update-lock-order and "
        "review the order"),
    "sanitizer-violation": (
        "error",
        "the runtime lock sanitizer observed an acquisition order "
        "between statically-ordered locks that the committed graph "
        "does not bless: an inversion or a statically invisible path"),
    "interface-drift": (
        "error",
        "a ReplicaHandle implementation or the wire dispatch table "
        "drifted from the handle protocol (missing method, signature "
        "mismatch, or unmapped wire op): a new handle method missing "
        "from the dispatch is a CI failure, not a runtime RemoteError"),
    "reject-vocab-drift": (
        "error",
        "a Reject(...) construction uses a reason literal outside "
        "scheduler.REJECT_REASONS, or a registry entry is constructed "
        "nowhere: the vocabulary has a single source of truth"),
}


@dataclasses.dataclass
class Finding:
    """One diagnostic: what rule fired, where, and how to fix it."""

    rule: str                 # key into RULES
    severity: str             # info|warning|error
    message: str              # specific to this site
    location: str = ""        # "eqn[3/0] pure_callback" or "file.py:42"
    fix: str = ""             # actionable hint
    engine: str = "jaxpr"     # jaxpr | ast | plan | concurrency

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity {self.severity!r} not in "
                             f"{SEVERITIES}")

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        loc = f" @ {self.location}" if self.location else ""
        hint = f"\n      fix: {self.fix}" if self.fix else ""
        return (f"  [{self.severity.upper():7s}] {self.rule}{loc}\n"
                f"      {self.message}{hint}")


class Suppressions:
    """Committed allow-list of known-accepted findings.

    File format, one entry per line::

        # comment
        <rule-id>  <substring matched against "name location message">

    A ``*`` substring (or none) suppresses every site of the rule.
    """

    def __init__(self, entries: Sequence[Tuple[str, str]] = ()):
        self.entries = list(entries)
        self.used: set = set()      # entry indices that matched a finding

    @classmethod
    def load(cls, path: str) -> "Suppressions":
        entries = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(None, 1)
                rule = parts[0]
                pat = parts[1].strip() if len(parts) > 1 else "*"
                entries.append((rule, pat))
        return cls(entries)

    def matches(self, context: str, finding: Finding) -> bool:
        hay = f"{context} {finding.location} {finding.message}"
        for i, (rule, pat) in enumerate(self.entries):
            if rule == finding.rule and (pat == "*" or pat in hay):
                self.used.add(i)
                return True
        return False

    def stale(self) -> List[Tuple[str, str]]:
        """Entries that matched nothing since construction. Run the full
        lint surface first (the CLI checks this only after the complete
        framework preset): a suppression that no longer fires is dead
        weight that would silently re-accept a future regression."""
        return [e for i, e in enumerate(self.entries) if i not in self.used]


class Report:
    """Findings for one linted function, with rendering + registry hooks."""

    def __init__(self, name: str = "fn",
                 findings: Iterable[Finding] = (),
                 suppressions: Optional[Suppressions] = None):
        self.name = name
        self.findings: List[Finding] = []
        self.suppressed: List[Finding] = []
        self._suppressions = suppressions
        #: attached by ``lint_fn(cost=True)``: the static
        #: :class:`~paddle_tpu.analysis.cost_model.CostReport`
        self.cost = None
        for f in findings:
            self.add(f)

    def add(self, finding: Finding):
        if self._suppressions is not None and \
                self._suppressions.matches(self.name, finding):
            self.suppressed.append(finding)
        else:
            self.findings.append(finding)

    def extend(self, findings: Iterable[Finding]):
        for f in findings:
            self.add(f)

    # -- queries ------------------------------------------------------------
    def by_severity(self, severity: str) -> List[Finding]:
        return [f for f in self.findings if f.severity == severity]

    @property
    def errors(self) -> List[Finding]:
        return self.by_severity("error")

    @property
    def warnings(self) -> List[Finding]:
        return self.by_severity("warning")

    def ok(self, fail_on: str = "error") -> bool:
        """True when no finding is at/above ``fail_on`` severity."""
        bad = SEVERITIES[SEVERITIES.index(fail_on):]
        return not any(f.severity in bad for f in self.findings)

    def __len__(self):
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)

    # -- rendering ----------------------------------------------------------
    def render_text(self) -> str:
        lines = [f"graph lint: {self.name} — {len(self.findings)} finding"
                 f"{'s' if len(self.findings) != 1 else ''}"
                 + (f" ({len(self.suppressed)} suppressed)"
                    if self.suppressed else "")]
        order = {s: i for i, s in enumerate(reversed(SEVERITIES))}
        for f in sorted(self.findings, key=lambda f: order[f.severity]):
            lines.append(f.render())
        if self.cost is not None:
            lines.append("  " + self.cost.render_text().splitlines()[0])
        return "\n".join(lines)

    def render_json(self) -> str:
        return json.dumps({
            "name": self.name,
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [f.as_dict() for f in self.suppressed],
            **({"cost": self.cost.as_dict()}
               if self.cost is not None else {}),
        }, indent=1)

    # -- observability ------------------------------------------------------
    def count_into_registry(self, reg=None):
        """One ``analysis_findings_total{rule,severity}`` bump per finding
        (+ an ``analysis_lint_runs_total`` bump per report)."""
        from paddle_tpu import observability
        reg = reg or observability.default()
        reg.counter("analysis_lint_runs_total",
                    "static-analysis reports produced").inc()
        for f in self.findings:
            reg.counter("analysis_findings_total",
                        "static-analysis findings by rule/severity").inc(
                            rule=f.rule, severity=f.severity)
        return self
