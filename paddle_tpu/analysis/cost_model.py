"""Static HLO cost model: walk a lowered StableHLO module into a CostReport.

The jaxpr linter sees the *traced program*; this module sees what the
compiler was actually handed. ``estimate_cost``/``estimate_lowered``
lower a (jitted) function — reusing the same ``Lowered.args_info``
donation plumbing as :mod:`~paddle_tpu.analysis.api` — and walk the
StableHLO module's operations to produce a :class:`CostReport`:

- **per-op flops and bytes** — ``dot_general``/``convolution`` get real
  contraction math (2·B·M·N·K, 2·out·k_spatial·c_in), reductions count
  their input elements, elementwise ops their results, and pure data
  movement (reshape/transpose/slice/gather/...) counts bytes only;
- **peak-HBM estimate** — a liveness scan over each function body:
  every SSA value is live from its defining op to its last use,
  non-donated entry arguments live for the whole call (the caller still
  holds them), donated arguments die at their last use (XLA may alias
  them into outputs), and region-carrying ops (while/case/reduce) add
  their bodies' internal peak at the op's program point;
- **per-collective accounting** — every ``all_reduce`` / ``all_gather``
  / ``reduce_scatter`` / ``all_to_all`` / ``collective_permute`` /
  ``collective_broadcast`` op is recorded with its payload bytes and
  replica-group shape, attributed to a mesh axis when ``mesh_axes``
  (``{axis_name: size}``) disambiguates the group size;
- **resharding chains** — ``custom_call @Sharding`` sites whose result
  flows (through elementwise ops) into another ``@Sharding`` site with
  a *different* sharding: the implicit transpose/all-to-all churn the
  ``resharding-churn`` lint rule reports.

Numbers are *static*: loop bodies and called functions count once per
call site (a lower bound — trip counts are runtime values), and the
peak-HBM scan models the unfused lowering, so it upper-bounds what XLA's
fusion achieves. That is exactly what a budget gate wants: the numbers
are deterministic functions of the lowered module, so a committed
baseline (``tools/cost_budgets.json``) catches *regressions* in the
lowered program without any hardware in the loop.

Pure lowering — nothing here compiles or executes device code.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

# element-type token -> bits (MLIR spellings)
_ETYPE_BITS = {
    "f64": 64, "f32": 32, "f16": 16, "bf16": 16,
    "f8E4M3FN": 8, "f8E5M2": 8, "f8E4M3FNUZ": 8, "f8E5M2FNUZ": 8,
    "f8E4M3B11FNUZ": 8,
    "i64": 64, "ui64": 64, "i32": 32, "ui32": 32,
    "i16": 16, "ui16": 16, "i8": 8, "ui8": 8, "i4": 4, "ui4": 4,
    "i1": 8,        # XLA stores predicates one per byte
    "c64": 64, "c128": 128, "index": 64,
}

_TENSOR_RE = re.compile(r"tensor<([^<>]*?)>")

#: ops that move/alias data but do no arithmetic
_DATA_MOVEMENT = {
    "reshape", "transpose", "broadcast_in_dim", "broadcast", "slice",
    "concatenate", "constant", "iota", "pad", "reverse", "copy",
    "bitcast_convert", "tuple", "get_tuple_element",
    "optimization_barrier", "dynamic_slice", "dynamic_update_slice",
    "gather", "scatter", "after_all", "create_token", "return", "call",
    "while", "case", "if", "custom_call", "convert", "composite",
    "partition_id", "replica_id",
}

#: stablehlo collective op name (sans dialect prefix) -> canonical kind
COLLECTIVE_OPS = {
    "all_reduce": "all_reduce",
    "all_gather": "all_gather",
    "reduce_scatter": "reduce_scatter",
    "all_to_all": "all_to_all",
    "collective_permute": "collective_permute",
    "collective_broadcast": "collective_broadcast",
}

#: ops a sharding annotation flows through unchanged (for churn chains)
_RESHARD_PASSTHROUGH = {
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "negate", "abs", "convert", "select", "tanh", "exponential", "log",
    "logistic", "sqrt", "rsqrt", "power", "optimization_barrier",
}

_TRANSCENDENTALS = {
    "exponential", "exponential_minus_one", "log", "log_plus_one",
    "logistic", "tanh", "sine", "cosine", "tan", "atan2", "power",
    "sqrt", "rsqrt", "cbrt", "erf", "erf_inv",
}


@functools.lru_cache(maxsize=4096)
def _type_counts(type_str: str) -> Tuple[int, int]:
    """(elements, bytes) summed over every ``tensor<...>`` in an MLIR
    type string (handles tuples/variadic renderings); unknown element
    types count zero. Cached — the walker parses each value's type for
    cost, flops, and liveness separately, and a module's type strings
    repeat massively."""
    elems = nbytes = 0
    for body in _TENSOR_RE.findall(str(type_str)):
        parts = body.split("x")
        etype = parts[-1].strip()
        bits = _ETYPE_BITS.get(etype)
        if bits is None:
            continue
        n = 1
        ok = True
        for d in parts[:-1]:
            d = d.strip()
            if not d.isdigit():     # dynamic dim / layout token
                ok = False
                break
            n *= int(d)
        if not ok:
            continue
        elems += n
        nbytes += n * ((bits + 7) // 8)
    return elems, nbytes


def _value_bytes(v) -> int:
    return _type_counts(str(v.type))[1]


def _value_elems(v) -> int:
    return _type_counts(str(v.type))[0]


def _short_loc(op) -> str:
    loc = str(getattr(op, "location", "")).strip()
    if loc.startswith("loc("):
        loc = loc[4:-1]
    loc = loc.strip('"')
    loc = loc.split('"(', 1)[0]     # drop the nested callsite chain
    return loc[:80] if loc and loc != "unknown" else ""


@dataclasses.dataclass
class OpCost:
    """Aggregate cost of every instance of one op kind."""
    count: int = 0
    flops: int = 0
    bytes: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Collective:
    """One collective op instance: payload + replica-group shape."""
    kind: str                 # all_reduce | all_gather | ...
    bytes: int
    groups: int = 1           # number of replica groups
    group_size: int = 1       # devices per group
    axis: str = ""            # mesh axis attribution (best effort)
    location: str = ""

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ReshardSite:
    """A value resharded between two explicit sharding annotations."""
    bytes: int
    src: str                  # mhlo.sharding of the producer
    dst: str                  # mhlo.sharding of the consumer
    location: str = ""

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class CostReport:
    """Static cost of one lowered function (see module docstring)."""

    def __init__(self, name: str = "fn"):
        self.name = name
        self.per_op: Dict[str, OpCost] = {}
        self.collectives: List[Collective] = []
        self.resharding: List[ReshardSite] = []
        self.peak_hbm_bytes: int = 0
        self.arg_bytes: int = 0
        self.out_bytes: int = 0
        self.donated_bytes: int = 0

    # -- aggregates ---------------------------------------------------------
    @property
    def total_flops(self) -> int:
        return sum(c.flops for c in self.per_op.values())

    @property
    def traffic_bytes(self) -> int:
        """Sum of operand+result bytes over every op: the memory-traffic
        face of the cost (upper bound — fusion elides most of it)."""
        return sum(c.bytes for c in self.per_op.values())

    @property
    def collective_bytes(self) -> int:
        return sum(c.bytes for c in self.collectives)

    @property
    def n_ops(self) -> int:
        return sum(c.count for c in self.per_op.values())

    def collective_kinds(self) -> Dict[str, int]:
        """kind -> total bytes, for allowlist checks."""
        out: Dict[str, int] = {}
        for c in self.collectives:
            out[c.kind] = out.get(c.kind, 0) + c.bytes
        return out

    def summary(self) -> Dict[str, int]:
        """The budget-gate metrics (what ``tools/cost_budgets.json``
        commits and ``--cost-diff`` compares)."""
        return {
            "flops": int(self.total_flops),
            "peak_hbm_bytes": int(self.peak_hbm_bytes),
            "traffic_bytes": int(self.traffic_bytes),
            "collective_bytes": int(self.collective_bytes),
        }

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            **self.summary(),
            "arg_bytes": self.arg_bytes,
            "out_bytes": self.out_bytes,
            "donated_bytes": self.donated_bytes,
            "n_ops": self.n_ops,
            "per_op": {k: v.as_dict()
                       for k, v in sorted(self.per_op.items())},
            "collectives": [c.as_dict() for c in self.collectives],
            "resharding": [r.as_dict() for r in self.resharding],
        }

    def render_text(self) -> str:
        def mb(n):
            return f"{n / (1 << 20):.2f}MiB"
        lines = [f"cost: {self.name} — {self.total_flops:,} flops, "
                 f"traffic {mb(self.traffic_bytes)}, peak HBM "
                 f"{mb(self.peak_hbm_bytes)} (args {mb(self.arg_bytes)}, "
                 f"out {mb(self.out_bytes)}, donated "
                 f"{mb(self.donated_bytes)}), "
                 f"{len(self.collectives)} collective(s)"]
        top = sorted(self.per_op.items(), key=lambda kv: -kv[1].flops)[:6]
        for op, c in top:
            if c.flops:
                lines.append(f"  {op:24s} x{c.count:<4d} "
                             f"{c.flops:,} flops  {mb(c.bytes)}")
        for c in self.collectives:
            ax = f" axis={c.axis}" if c.axis else ""
            lines.append(f"  collective {c.kind} {mb(c.bytes)} "
                         f"({c.groups}x{c.group_size}{ax})")
        for r in self.resharding:
            lines.append(f"  reshard {mb(r.bytes)} {r.src} -> {r.dst}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# flops models for the structured ops
# ---------------------------------------------------------------------------

def _tensor_dims(v) -> List[int]:
    body = _TENSOR_RE.findall(str(v.type))
    if not body:
        return []
    parts = body[0].split("x")[:-1]
    return [int(p) for p in parts if p.strip().isdigit()]


def _dot_flops(op) -> int:
    attr = str(op.attributes["dot_dimension_numbers"]) \
        if "dot_dimension_numbers" in op.attributes else ""
    # the batching lists may be absent from the attr text entirely, so
    # each dimension list is pulled by its own name
    named = {}
    for key in ("lhs_batching_dimensions", "rhs_batching_dimensions",
                "lhs_contracting_dimensions",
                "rhs_contracting_dimensions"):
        m = re.search(key + r"\s*=\s*\[([\d,\s]*)\]", attr)
        named[key] = [int(x) for x in m.group(1).split(",") if x.strip()] \
            if m else []
    lhs = _tensor_dims(op.operands[0])
    rhs = _tensor_dims(op.operands[1])
    lb = named["lhs_batching_dimensions"]
    lc = named["lhs_contracting_dimensions"]
    rb = named["rhs_batching_dimensions"]
    rc = named["rhs_contracting_dimensions"]
    try:
        b = math.prod(lhs[i] for i in lb) if lb else 1
        k = math.prod(lhs[i] for i in lc) if lc else 1
        m_ = math.prod(d for i, d in enumerate(lhs) if i not in lb + lc)
        n_ = math.prod(d for i, d in enumerate(rhs) if i not in rb + rc)
    except IndexError:
        return 2 * _value_elems(op.results[0])
    return 2 * b * m_ * n_ * k


def _conv_flops(op) -> int:
    out = _value_elems(op.results[0])
    kernel = _tensor_dims(op.operands[1])
    attr = str(op.attributes["dimension_numbers"]) \
        if "dimension_numbers" in op.attributes else ""
    # "#stablehlo.conv<[b, f, 0, 1]x[o, i, 0, 1]->[b, f, 0, 1]>"
    m = re.search(r"x\[([^\]]*)\]", attr)
    if not m or not kernel:
        return 2 * out
    spec = [t.strip() for t in m.group(1).split(",")]
    try:
        i_pos = spec.index("i")
        spatial = [kernel[j] for j, t in enumerate(spec)
                   if t not in ("i", "o")]
        return 2 * out * kernel[i_pos] * math.prod(spatial or [1])
    except (ValueError, IndexError):
        return 2 * out


def _op_flops(op, kind: str) -> int:
    if kind == "dot_general":
        return _dot_flops(op)
    if kind == "convolution":
        return _conv_flops(op)
    if kind in ("reduce", "reduce_window", "sort", "select_and_scatter"):
        return sum(_value_elems(v) for v in op.operands)
    if kind in _DATA_MOVEMENT:
        return 0
    # elementwise / transcendental / compare / everything else: one op
    # per result element (transcendentals are several, but a stable 1x
    # convention keeps the budget numbers comparable across PRs)
    return sum(_value_elems(r) for r in op.results)


def _replica_groups(op) -> Tuple[int, int]:
    """(groups, group_size) from a collective's replica_groups attr."""
    if "replica_groups" not in op.attributes:
        return 1, 1
    attr = str(op.attributes["replica_groups"])
    m = re.search(r"tensor<(\d+)x(\d+)xi64>", attr)
    if m:
        return int(m.group(1)), int(m.group(2))
    return 1, 1


def _axis_for(group_size: int,
              mesh_axes: Optional[Dict[str, int]]) -> str:
    if not mesh_axes or group_size <= 1:
        return ""
    hits = [a for a, s in mesh_axes.items() if int(s) == group_size]
    return "|".join(hits)


# ---------------------------------------------------------------------------
# the walker
# ---------------------------------------------------------------------------

class _Walker:
    def __init__(self, module, *, mesh_axes=None,
                 resharding_min_bytes: int = 1 << 16):
        self.funcs: Dict[str, Any] = {}
        self.mesh_axes = mesh_axes
        self.resharding_min_bytes = resharding_min_bytes
        self._stack: set = set()
        self._users: Dict[Any, List[Any]] = {}
        self._shard_ops: List[Any] = []
        for op in module.body.operations:
            if "sym_name" in op.attributes:
                self.funcs[str(op.attributes["sym_name"]).strip('"')] = op

    # -- entry --------------------------------------------------------------
    def run(self, report: CostReport,
            donated: Optional[Sequence[bool]]) -> None:
        main = self.funcs.get("main")
        if main is None:                       # defensive: empty module
            return
        blk = main.regions[0].blocks[0]
        args = list(blk.arguments)
        flags = list(donated or [])
        flags += [False] * (len(args) - len(flags))
        report.arg_bytes = sum(_value_bytes(a) for a in args)
        report.donated_bytes = sum(
            _value_bytes(a) for a, d in zip(args, flags) if d)
        report.peak_hbm_bytes = self._walk_block(
            blk, report, donated_args=flags[:len(args)])
        # main's outputs: the func.return operand bytes
        for o in blk.operations:
            if o.name == "func.return":
                report.out_bytes = sum(_value_bytes(v) for v in o.operands)
        self._resharding_chains(report)

    # -- per-block liveness + cost ------------------------------------------
    def _walk_block(self, blk, report: CostReport, *,
                    donated_args: Optional[Sequence[bool]] = None,
                    count_args: bool = True) -> int:
        """Accumulate op costs for ``blk`` (recursing into regions and
        called functions) and return the block's liveness peak in bytes.

        ``count_args``: region blocks pass False — their block args are
        the enclosing op's operands, already live at the outer level."""
        ops = list(blk.operations)
        deaths: Dict[Any, int] = {}
        extra = [0] * len(ops)

        for idx, o in enumerate(ops):
            for v in o.operands:
                deaths[v] = idx

        live_delta = [0] * (len(ops) + 1)

        args = list(blk.arguments)
        dflags = list(donated_args or []) + [False] * len(args)
        for a, d in zip(args, dflags):
            if not count_args:
                continue
            nb = _value_bytes(a)
            live_delta[0] += nb
            if d:
                # donated: XLA may alias it into the consuming op's
                # output, so the old copy is gone AT its last use (the
                # in-place update the donation lint rule wants);
                # non-donated args get no decrement at all — the caller
                # still holds them, so they stay live to the end
                live_delta[max(deaths.get(a, 0), 0)] -= nb

        for idx, o in enumerate(ops):
            kind = o.name.split(".", 1)[-1]
            dialect = o.name.split(".", 1)[0]

            # ---- cost accounting ----
            if o.name not in ("func.return", "stablehlo.return"):
                oc = report.per_op.setdefault(kind, OpCost())
                oc.count += 1
                oc.flops += _op_flops(o, kind)
                oc.bytes += sum(_value_bytes(v) for v in o.operands) \
                    + sum(_value_bytes(r) for r in o.results)

            # ---- collectives ----
            if kind in COLLECTIVE_OPS:
                nb = max(sum(_value_bytes(v) for v in o.operands),
                         sum(_value_bytes(r) for r in o.results))
                groups, gsize = _replica_groups(o)
                report.collectives.append(Collective(
                    COLLECTIVE_OPS[kind], nb, groups, gsize,
                    _axis_for(gsize, self.mesh_axes), _short_loc(o)))

            # ---- sharding annotations (for churn chains) ----
            if kind == "custom_call" and "call_target_name" in o.attributes \
                    and str(o.attributes["call_target_name"]).strip('"') \
                    == "Sharding" and "mhlo.sharding" in o.attributes:
                self._shard_ops.append(o)
            for v in o.operands:
                self._users.setdefault(v, []).append(o)

            # ---- recursion: called functions + regions ----
            if dialect == "func" and kind == "call" \
                    and "callee" in o.attributes:
                callee = str(o.attributes["callee"]).strip('"').lstrip("@")
                extra[idx] = max(extra[idx], self._walk_func(
                    callee, report))
            inner = 0
            for r in o.regions:
                for b in r.blocks:
                    inner = max(inner, self._walk_block(
                        b, report, count_args=False))
            extra[idx] = max(extra[idx], inner)

            # ---- liveness births ----
            for res in o.results:
                nb = _value_bytes(res)
                live_delta[idx] += nb
                end = deaths.get(res, idx)
                if end + 1 <= len(ops) - 1:
                    live_delta[end + 1] -= nb

        peak = running = 0
        for idx in range(len(ops)):
            running += live_delta[idx]
            peak = max(peak, running + extra[idx])
        return peak

    def _walk_func(self, name: str, report: CostReport) -> int:
        fn = self.funcs.get(name)
        if fn is None or name in self._stack:
            return 0
        self._stack.add(name)
        try:
            # callee peak: its args are the call's operands, live at the
            # caller already, so count only the body's intermediates
            return self._walk_block(fn.regions[0].blocks[0], report,
                                    count_args=False)
        finally:
            self._stack.discard(name)

    # -- resharding chains --------------------------------------------------
    def _resharding_chains(self, report: CostReport) -> None:
        """For every @Sharding site, follow its result forward through
        elementwise ops; a different @Sharding downstream on a large
        tensor is a resharding-churn site."""
        def sharding_of(o) -> str:
            return str(o.attributes["mhlo.sharding"]).strip('"')

        for src_op in self._shard_ops:
            src = sharding_of(src_op)
            if src in ("{manual}", "{replicated}"):
                continue
            nb = _value_bytes(src_op.results[0])
            if nb < self.resharding_min_bytes:
                continue
            seen: set = set()
            frontier = list(src_op.results)
            depth = 0
            while frontier and depth < 16:
                nxt = []
                for v in frontier:
                    for user in self._users.get(v, ()):
                        kind = user.name.split(".", 1)[-1]
                        if user in seen:
                            continue
                        seen.add(user)
                        if kind == "custom_call" and \
                                "mhlo.sharding" in user.attributes and \
                                "call_target_name" in user.attributes and \
                                str(user.attributes["call_target_name"]
                                    ).strip('"') == "Sharding":
                            dst = sharding_of(user)
                            if dst not in (src, "{manual}"):
                                report.resharding.append(ReshardSite(
                                    nb, src, dst, _short_loc(user)))
                            continue            # chain ends at a reshard
                        if kind in _RESHARD_PASSTHROUGH:
                            nxt.extend(user.results)
                frontier = nxt
                depth += 1


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def analyze_module(module, *, name: str = "fn",
                   donated: Optional[Sequence[bool]] = None,
                   mesh_axes: Optional[Dict[str, int]] = None,
                   resharding_min_bytes: int = 1 << 16) -> CostReport:
    """Walk an MLIR/StableHLO module into a :class:`CostReport`."""
    report = CostReport(name)
    _Walker(module, mesh_axes=mesh_axes,
            resharding_min_bytes=resharding_min_bytes).run(report, donated)
    return report


def estimate_lowered(lowered, *, name: str = "fn",
                     donated: Optional[Sequence[bool]] = None,
                     mesh_axes: Optional[Dict[str, int]] = None,
                     resharding_min_bytes: int = 1 << 16) -> CostReport:
    """Cost-analyze a ``jax.stages.Lowered``. Donation flags default to
    the lowering's own ``args_info`` (the same plumbing the donation
    lint rule reads)."""
    if donated is None:
        try:
            donated = [a.donated
                       for a in jax.tree_util.tree_leaves(lowered.args_info)]
        except Exception:
            donated = None
    module = lowered.compiler_ir(dialect="stablehlo")
    return analyze_module(module, name=name, donated=donated,
                          mesh_axes=mesh_axes,
                          resharding_min_bytes=resharding_min_bytes)


def estimate_cost(fn, *args, name: Optional[str] = None,
                  donate_argnums=None,
                  mesh_axes: Optional[Dict[str, int]] = None,
                  resharding_min_bytes: int = 1 << 16,
                  **kwargs) -> CostReport:
    """Lower ``fn(*args, **kwargs)`` (jitting if it is not already a
    jit wrapper) and cost-analyze the StableHLO. Args may be concrete
    arrays or ``jax.ShapeDtypeStruct`` — nothing executes."""
    name = name or getattr(fn, "__name__", None) or type(fn).__name__
    if hasattr(fn, "lower"):
        lowered = fn.lower(*args, **kwargs)
    else:
        if donate_argnums is None:
            lowered = jax.jit(fn).lower(*args, **kwargs)
        else:
            lowered = jax.jit(fn, donate_argnums=donate_argnums).lower(
                *args, **kwargs)
    return estimate_lowered(lowered, name=name, mesh_axes=mesh_axes,
                            resharding_min_bytes=resharding_min_bytes)
