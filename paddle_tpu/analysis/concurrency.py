"""Concurrency analysis tier: lock-discipline lint, lock-order graph,
and a test-time lock sanitizer for the threaded serving plane.

The first three analysis tiers (AST / jaxpr / HLO) prove properties of
the *traced program*; this tier proves properties of the *host threads
around it* — the engine step loops, router pumps, snapshot writer,
streaming applier, and socket selector loops that grew around the jitted
steps. Three instruments, one reporting spine:

- **Lock-discipline lint** (:func:`lint_locks`): classes declare which
  lock guards which fields via the :func:`guarded_by` decorator; an AST
  dataflow pass flags any read/write of a guarded attribute outside a
  ``with self._lock:`` scope, with one level of intra-class call
  propagation (a private helper's unguarded access is accepted only
  when every intra-class call site holds the right lock).
- **Lock-order graph** (:func:`extract_lock_graph`): every
  ``threading.Lock/RLock/Condition`` attribute in the package plus the
  nested ``with``-acquisition edges between them, including one level
  of call propagation (intra-class, and cross-class through attributes
  whose type is statically resolvable). Cycles are potential deadlocks;
  double-acquire of a non-reentrant lock is a guaranteed one. The
  blessed acyclic order is committed as ``tools/lock_order.json`` and
  drift-gated like ``tools/cost_budgets.json``.
- **Runtime lock sanitizer** (:func:`sanitize`): a context manager that
  instruments locks *created inside it*, records actual acquisition
  orders and hold-while-blocking events during threaded tests, refuses
  (raises) instead of deadlocking on a same-thread double-acquire, and
  cross-checks ``observed ⊆ committed graph`` so the static model is
  proven against real executions. Counts surface as ``concurrency_*``
  metrics in the observability registry.

Reference mapping: the reference framework's distributed runtime makes
cross-thread correctness a first-class system concern (the TensorFlow
runtime paper's rendezvous/executor protocols); this is the static +
dynamic half of that discipline for the Python serving plane, in the
same "rule id + location + hint" shape as the other lint tiers.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import sys
import threading
from typing import (Any, Callable, Dict, Iterable, List, Mapping, Optional,
                    Sequence, Set, Tuple)

from paddle_tpu.analysis.findings import Finding, Report, Suppressions

__all__ = [
    "DoubleAcquireError", "LockGraph", "LockMonitor", "extract_lock_graph",
    "guarded_by", "lint_concurrency", "lint_locks", "load_lock_order",
    "lock_order_diff", "lock_order_manifest", "package_sources", "sanitize",
]

# real constructors, captured before any sanitize() patching
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

#: lock-like threading constructors -> graph kind (reentrancy class)
_LOCK_KINDS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

#: methods a lint pass never flags: no other thread can observe the
#: object while its constructor/finalizer runs
_EXEMPT_METHODS = ("__init__", "__post_init__", "__del__")


# ---------------------------------------------------------------------------
# the annotation convention


def guarded_by(lock: str, *fields: str) -> Callable[[type], type]:
    """Class decorator declaring that ``lock`` (an attribute name, e.g.
    ``"_lock"``) guards ``fields`` (attribute names). Stackable for
    classes with more than one lock::

        @guarded_by("_cv", "_pending", "_error")
        @guarded_by("_vlock", "_versions", "_dirty")
        class StreamingUpdateChannel: ...

    At runtime this only records ``cls.__guarded_by__`` (a merged
    ``{field: lock}`` dict, inherited copies included) — the contract is
    enforced statically by :func:`lint_locks` and dynamically (order
    only) by :func:`sanitize`.
    """
    def deco(cls: type) -> type:
        merged = dict(getattr(cls, "__guarded_by__", {}))
        for f in fields:
            merged[f] = lock
        cls.__guarded_by__ = merged
        return cls
    return deco


# ---------------------------------------------------------------------------
# shared AST helpers


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _threading_ctor(node: ast.AST) -> Optional[str]:
    """``threading.Lock()`` / ``Lock()`` -> graph kind, else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "threading":
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    return _LOCK_KINDS.get(name or "")


def _decorator_guards(cls: ast.ClassDef) -> Dict[str, str]:
    """Merged ``{field: lock}`` from stacked ``@guarded_by`` decorators
    (literal string arguments only — anything computed is ignored, the
    same way the runtime decorator would be unanalyzable)."""
    guards: Dict[str, str] = {}
    for dec in cls.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        fn = dec.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        if name != "guarded_by" or not dec.args:
            continue
        vals = [a.value for a in dec.args
                if isinstance(a, ast.Constant) and isinstance(a.value, str)]
        if len(vals) == len(dec.args) and len(vals) >= 2:
            for field in vals[1:]:
                guards[field] = vals[0]
    return guards


def _with_locks(node: ast.With, own_locks: Set[str],
                module_locks: Set[str]) -> List[str]:
    """Lock names acquired by one ``with`` statement: ``with
    self._lock:`` (own attribute) or ``with _LOCK:`` (module-level)."""
    out = []
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr is not None and attr in own_locks:
            out.append(attr)
        elif (isinstance(item.context_expr, ast.Name)
                and item.context_expr.id in module_locks):
            out.append(item.context_expr.id)
    return out


# ---------------------------------------------------------------------------
# (a) lock-discipline lint


@dataclasses.dataclass
class _Access:
    field: str
    lock: str
    lineno: int
    write: bool


@dataclasses.dataclass
class _CallSite:
    caller: str
    held: frozenset
    lineno: int


class _MethodScan:
    """One method's unguarded accesses + intra-class call sites, from a
    single held-lock-aware walk."""

    def __init__(self, guards: Dict[str, str], own_locks: Set[str]):
        self.guards = guards
        self.own_locks = own_locks
        self.accesses: List[_Access] = []
        self.calls: List[Tuple[str, frozenset, int]] = []  # (name, held, ln)

    def walk(self, body: Sequence[ast.stmt], held: frozenset):
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, node: ast.AST, held: frozenset):
        if isinstance(node, ast.With):
            for item in node.items:
                self._expr(item.context_expr, held)
            acquired = _with_locks(node, self.own_locks, set())
            self.walk(node.body, held | frozenset(acquired))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, possibly from another thread with
            # no lock held — walk it with an empty held set
            self.walk(node.body, frozenset())
            return
        # ExceptHandler / match_case are AST nodes but not ast.stmt;
        # their bodies hold statements and must stay on the held-aware
        # path (an _expr blind walk would drop `with` scopes)
        stmt_like = (ast.stmt, ast.ExceptHandler, ast.match_case)
        for field, expr in ast.iter_fields(node):
            if isinstance(expr, ast.AST):
                (self._stmt if isinstance(expr, stmt_like)
                 else self._expr)(expr, held)
            elif isinstance(expr, list):
                for item in expr:
                    if isinstance(item, stmt_like):
                        self._stmt(item, held)
                    elif isinstance(item, ast.AST):
                        self._expr(item, held)

    def _expr(self, node: ast.AST, held: frozenset):
        for sub in ast.walk(node):
            attr = _self_attr(sub)
            if attr is not None and attr in self.guards:
                lock = self.guards[attr]
                if lock not in held:
                    self.accesses.append(_Access(
                        attr, lock, sub.lineno,
                        isinstance(sub.ctx, (ast.Store, ast.Del))))
            if isinstance(sub, ast.Call):
                callee = _self_attr(sub.func)
                if callee is not None:
                    self.calls.append((callee, held, sub.lineno))


def lint_locks(source: str, *, filename: str = "<string>"
               ) -> List[Finding]:
    """The lock-discipline pass over one module's source: flag every
    read/write of a ``@guarded_by`` field outside a ``with self.<lock>:``
    scope. One level of intra-class call propagation: a private helper's
    unguarded access is accepted iff the helper has at least one
    intra-class call site and *every* such call site holds the required
    lock (public methods are always flagged — external callers cannot be
    assumed to hold an internal lock)."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [Finding("unguarded-access", "error",
                        f"could not parse {filename}: {e}",
                        location=filename, engine="concurrency")]
    findings: List[Finding] = []
    base = os.path.basename(filename)
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        guards = _decorator_guards(cls)
        if not guards:
            continue
        own_locks = set(guards.values())
        scans: Dict[str, _MethodScan] = {}
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name in _EXEMPT_METHODS:
                continue
            scan = _MethodScan(guards, own_locks)
            scan.walk(meth.body, frozenset())
            scans[meth.name] = scan
        # call-site index: helper name -> held sets at intra-class sites
        sites: Dict[str, List[_CallSite]] = {}
        for caller, scan in scans.items():
            for callee, held, ln in scan.calls:
                sites.setdefault(callee, []).append(
                    _CallSite(caller, held, ln))
        for name, scan in scans.items():
            private = name.startswith("_")
            for acc in scan.accesses:
                callers = sites.get(name, [])
                if private and callers and all(
                        acc.lock in s.held for s in callers):
                    continue        # every caller holds the lock
                verb = "writes" if acc.write else "reads"
                via = ""
                if private and callers:
                    bad = [s for s in callers if acc.lock not in s.held]
                    via = (f" (reached from unlocked call site "
                           f"{cls.name}.{bad[0].caller})" if bad else "")
                findings.append(Finding(
                    "unguarded-access", "error",
                    f"{cls.name}.{name} {verb} self.{acc.field} without "
                    f"holding self.{acc.lock}{via}",
                    location=f"{base}:{acc.lineno}",
                    fix=f"wrap the access in `with self.{acc.lock}:` or "
                        f"move it under an already-locked caller",
                    engine="concurrency"))
    return findings


# ---------------------------------------------------------------------------
# (b) lock-order graph


@dataclasses.dataclass
class LockGraph:
    """The package's static lock universe and acquisition-order edges.

    ``locks`` maps a qualified lock id (``"LocalReplica._lock"`` or
    ``"native._LOCK"`` for module-level) to its reentrancy kind;
    ``edges`` maps ``(held, acquired)`` pairs to one representative
    source location; ``double_acquires`` lists non-reentrant locks
    re-acquired while already held on the same path.
    """

    locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    edges: Dict[Tuple[str, str], str] = dataclasses.field(
        default_factory=dict)
    double_acquires: List[Tuple[str, str]] = dataclasses.field(
        default_factory=list)

    def add_edge(self, src: str, dst: str, location: str):
        if src != dst:
            self.edges.setdefault((src, dst), location)

    def cycles(self) -> List[List[str]]:
        """Simple cycles in the edge digraph (DFS, deduplicated by the
        cycle's node set — enough to name each deadlock once)."""
        adj: Dict[str, List[str]] = {}
        for (src, dst) in self.edges:
            adj.setdefault(src, []).append(dst)
        seen_sets: List[frozenset] = []
        cycles: List[List[str]] = []

        def dfs(node: str, path: List[str], on_path: Set[str]):
            for nxt in adj.get(node, ()):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in seen_sets:
                        seen_sets.append(key)
                        cycles.append(cyc)
                    continue
                dfs(nxt, path + [nxt], on_path | {nxt})

        for start in sorted(adj):
            dfs(start, [start], {start})
        return cycles

    def acyclic(self) -> bool:
        return not self.cycles()

    def findings(self) -> List[Finding]:
        """Cycle + double-acquire findings over the extracted graph."""
        out = []
        for cyc in self.cycles():
            loc = self.edges.get((cyc[0], cyc[1]), "")
            out.append(Finding(
                "lock-order-cycle", "error",
                "potential deadlock: lock acquisition cycle "
                + " -> ".join(cyc),
                location=loc,
                fix="pick one global order for these locks and release "
                    "before acquiring against it",
                engine="concurrency"))
        for lock, loc in self.double_acquires:
            out.append(Finding(
                "double-acquire", "error",
                f"non-reentrant {lock} acquired while already held on "
                "the same path: guaranteed self-deadlock",
                location=loc,
                fix=f"make {lock} an RLock only if re-entry is by "
                    "design; otherwise split the inner acquisition out",
                engine="concurrency"))
        return out


class _ClassInfo:
    def __init__(self, name: str, filename: str):
        self.name = name
        self.filename = filename
        self.locks: Dict[str, str] = {}           # attr -> kind
        self.attr_types: Dict[str, str] = {}      # attr -> class name
        self.acquires: Dict[str, Set[str]] = {}   # method -> own lock attrs
        self.self_calls: Dict[str, Set[str]] = {}  # method -> callee names
        #: (method, held lock-ids, target attr|"self", callee, lineno)
        self.locked_calls: List[Tuple[str, frozenset, str, str, int]] = []

    def qual(self, attr: str) -> str:
        return f"{self.name}.{attr}"


def _scan_class(cls: ast.ClassDef, filename: str,
                module_locks: Dict[str, str],
                graph: LockGraph) -> _ClassInfo:
    info = _ClassInfo(cls.name, filename)
    base = os.path.basename(filename)
    # pass 1: lock attributes + attribute types (from direct
    # constructions and from annotated __init__ params)
    param_types: Dict[str, str] = {}
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if meth.name == "__init__":
            for arg in meth.args.args + meth.args.kwonlyargs:
                ann = arg.annotation
                if isinstance(ann, ast.Name):
                    param_types[arg.arg] = ann.id
                elif isinstance(ann, ast.Constant) and \
                        isinstance(ann.value, str):
                    param_types[arg.arg] = ann.value
        for node in ast.walk(meth):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            attr = _self_attr(node.targets[0])
            if attr is None:
                continue
            kind = _threading_ctor(node.value)
            if kind is not None:
                info.locks[attr] = kind
                continue
            if isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Name):
                info.attr_types.setdefault(attr, node.value.func.id)
            elif isinstance(node.value, ast.Name) and \
                    node.value.id in param_types:
                info.attr_types.setdefault(
                    attr, param_types[node.value.id])
    # pass 2: per-method held-stack walk for direct edges + call sites
    own = set(info.locks)
    mod = set(module_locks)

    def walk(method: str, body: Sequence[ast.AST], held: Tuple[str, ...]):
        for node in body:
            if isinstance(node, ast.With):
                acquired = []
                for lock in _with_locks(node, own, mod):
                    lid = (info.qual(lock) if lock in own
                           else f"{_modbase(filename)}.{lock}")
                    kind = info.locks.get(lock, module_locks.get(lock))
                    if lid in held and kind == "lock":
                        graph.double_acquires.append(
                            (lid, f"{base}:{node.lineno}"))
                    for h in held:
                        graph.add_edge(h, lid, f"{base}:{node.lineno}")
                    info.acquires.setdefault(method, set()).update(
                        {lock} if lock in own else set())
                    acquired.append(lid)
                walk(method, node.body, held + tuple(acquired))
                continue
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee is not None:
                    info.self_calls.setdefault(method, set()).add(callee)
                    if held:
                        info.locked_calls.append(
                            (method, frozenset(held), "self", callee,
                             node.lineno))
                elif (isinstance(node.func, ast.Attribute)
                        and held
                        and _self_attr(node.func.value) is not None):
                    info.locked_calls.append(
                        (method, frozenset(held),
                         _self_attr(node.func.value), node.func.attr,
                         node.lineno))
            for field, expr in ast.iter_fields(node):
                if isinstance(expr, ast.AST):
                    walk(method, [expr], held)
                elif isinstance(expr, list):
                    walk(method, [e for e in expr
                                  if isinstance(e, ast.AST)], held)

    for meth in cls.body:
        if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.acquires.setdefault(meth.name, set())
            info.self_calls.setdefault(meth.name, set())
            walk(meth.name, meth.body, ())
    return info


def _modbase(filename: str) -> str:
    name = os.path.basename(filename)
    if name == "__init__.py":
        name = os.path.basename(os.path.dirname(filename)) or name
    return name[:-3] if name.endswith(".py") else name


def _scan_module_functions(tree: ast.Module, filename: str,
                           module_locks: Dict[str, str],
                           graph: LockGraph):
    """Edges from module-level functions' nested ``with`` acquisitions
    of module-level locks."""
    base = os.path.basename(filename)
    mod = set(module_locks)

    def walk(body, held):
        for node in body:
            if isinstance(node, ast.With):
                acquired = []
                for lock in _with_locks(node, set(), mod):
                    lid = f"{_modbase(filename)}.{lock}"
                    if lid in held and module_locks[lock] == "lock":
                        graph.double_acquires.append(
                            (lid, f"{base}:{node.lineno}"))
                    for h in held:
                        graph.add_edge(h, lid, f"{base}:{node.lineno}")
                    acquired.append(lid)
                walk(node.body, held + tuple(acquired))
                continue
            for field, expr in ast.iter_fields(node):
                if isinstance(expr, ast.AST):
                    walk([expr], held)
                elif isinstance(expr, list):
                    walk([e for e in expr if isinstance(e, ast.AST)],
                         held)

    for fn in tree.body:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk(fn.body, ())


def _transitive_acquires(info: _ClassInfo) -> Dict[str, Set[str]]:
    """Per-method fixpoint of own-lock acquisitions through intra-class
    calls (``step -> _refresh_health -> _health_lock``)."""
    closure = {m: set(s) for m, s in info.acquires.items()}
    changed = True
    while changed:
        changed = False
        for m, callees in info.self_calls.items():
            for c in callees:
                extra = closure.get(c, set()) - closure.setdefault(m, set())
                if extra:
                    closure[m] |= extra
                    changed = True
    return closure


def extract_lock_graph(sources: Mapping[str, str]) -> LockGraph:
    """Extract the package-wide :class:`LockGraph` from ``{filename:
    source}``. Direct nested ``with`` edges, plus one level of call
    propagation: inside a locked region, a call to ``self.m()`` adds
    edges to every lock ``m`` (transitively, intra-class) acquires, and
    a call to ``self.attr.m()`` does the same when ``attr``'s class is
    statically resolvable (a direct construction in ``__init__`` or an
    annotated constructor parameter)."""
    graph = LockGraph()
    classes: Dict[str, _ClassInfo] = {}
    trees: Dict[str, ast.Module] = {}
    for filename, source in sources.items():
        try:
            trees[filename] = ast.parse(source, filename=filename)
        except SyntaxError:
            continue
    for filename, tree in trees.items():
        module_locks: Dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = _threading_ctor(node.value)
                if kind is not None:
                    module_locks[node.targets[0].id] = kind
        for name, kind in module_locks.items():
            graph.locks[f"{_modbase(filename)}.{name}"] = kind
        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            info = _scan_class(cls, filename, module_locks, graph)
            if info.locks or info.locked_calls:
                classes.setdefault(info.name, info)
            for attr, kind in info.locks.items():
                graph.locks[info.qual(attr)] = kind
        _scan_module_functions(tree, filename, module_locks, graph)
    closures = {name: _transitive_acquires(info)
                for name, info in classes.items()}
    for info in classes.values():
        base = os.path.basename(info.filename)
        for method, held, target, callee, lineno in info.locked_calls:
            if target == "self":
                tgt = info
            else:
                tname = info.attr_types.get(target)
                tgt = classes.get(tname) if tname else None
            if tgt is None:
                continue
            for lock in closures[tgt.name].get(callee, ()):
                lid = tgt.qual(lock)
                loc = f"{base}:{lineno}"
                if lid in held and tgt.locks.get(lock) == "lock":
                    graph.double_acquires.append((lid, loc))
                for h in held:
                    graph.add_edge(h, lid, loc)
    return graph


def package_sources(root: Optional[str] = None) -> Dict[str, str]:
    """``{filename: source}`` for every ``.py`` under the package root
    (defaults to the installed ``paddle_tpu`` package directory)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out: Dict[str, str] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                with open(path) as f:
                    out[path] = f.read()
    return out


# ---------------------------------------------------------------------------
# lock_order.json: the committed blessed order + drift gating


def lock_order_manifest(graph: LockGraph) -> dict:
    """The committed-manifest shape for ``tools/lock_order.json``."""
    return {
        "_comment": [
            "Blessed static lock-acquisition order for "
            "tools/graph_lint.py --concurrency.",
            "Regenerate with: python tools/graph_lint.py --concurrency "
            "--update-lock-order",
            "and commit alongside any PR that legitimately adds or "
            "removes a lock or a nested acquisition.",
            "'edges' are [held, acquired, location] triples; the graph "
            "must stay acyclic.",
        ],
        "locks": dict(sorted(graph.locks.items())),
        "edges": [[src, dst, loc] for (src, dst), loc
                  in sorted(graph.edges.items())],
    }


def load_lock_order(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def lock_order_diff(graph: LockGraph, manifest: Optional[dict],
                    *, path: str = "tools/lock_order.json"
                    ) -> List[Finding]:
    """Drift gate mirroring ``--cost-diff``: the extracted lock universe
    and edge set must exactly match the committed manifest — a new lock
    or edge missing from it fails (review the order, then regenerate),
    and an orphaned/stale entry fails (dead entries would silently
    re-bless a future regression)."""
    fix = (f"run `python tools/graph_lint.py --concurrency "
           f"--update-lock-order`, review the order, and commit {path}")
    if manifest is None:
        return [Finding("lock-order-drift", "error",
                        f"no committed lock-order manifest at {path}",
                        fix=fix, engine="concurrency")]
    committed_locks = dict(manifest.get("locks", {}))
    committed_edges = {(e[0], e[1]): (e[2] if len(e) > 2 else "")
                       for e in manifest.get("edges", [])}
    out: List[Finding] = []
    for lid, kind in sorted(graph.locks.items()):
        if lid not in committed_locks:
            out.append(Finding(
                "lock-order-drift", "error",
                f"lock {lid} ({kind}) is not in the committed manifest",
                location=path, fix=fix, engine="concurrency"))
        elif committed_locks[lid] != kind:
            out.append(Finding(
                "lock-order-drift", "error",
                f"lock {lid} changed kind: committed "
                f"{committed_locks[lid]}, extracted {kind}",
                location=path, fix=fix, engine="concurrency"))
    for lid in sorted(set(committed_locks) - set(graph.locks)):
        out.append(Finding(
            "lock-order-drift", "error",
            f"stale manifest lock {lid}: no such lock is extracted "
            "from the package anymore",
            location=path, fix=fix, engine="concurrency"))
    for (src, dst), loc in sorted(graph.edges.items()):
        if (src, dst) not in committed_edges:
            out.append(Finding(
                "lock-order-drift", "error",
                f"new acquisition edge {src} -> {dst} is not in the "
                "committed manifest",
                location=loc, fix=fix, engine="concurrency"))
    for (src, dst) in sorted(set(committed_edges) - set(graph.edges)):
        out.append(Finding(
            "lock-order-drift", "error",
            f"orphaned manifest edge {src} -> {dst}: not extracted "
            "from the package anymore",
            location=path, fix=fix, engine="concurrency"))
    return out


def lint_concurrency(root: Optional[str] = None, *,
                     lock_order: Optional[str] = None,
                     suppressions: Optional[Suppressions] = None,
                     registry: bool = True) -> Report:
    """The full static concurrency tier over the package: the
    lock-discipline pass on every module, cycle/double-acquire findings
    on the extracted lock-order graph, and (when ``lock_order`` names a
    manifest path) the drift gate against ``tools/lock_order.json``."""
    sources = package_sources(root)
    report = Report("concurrency", suppressions=suppressions)
    for filename in sorted(sources):
        report.extend(lint_locks(sources[filename], filename=filename))
    graph = extract_lock_graph(sources)
    report.extend(graph.findings())
    if lock_order is not None:
        report.extend(lock_order_diff(graph, load_lock_order(lock_order),
                                      path=lock_order))
    report.graph = graph
    if registry:
        report.count_into_registry()
    return report


# ---------------------------------------------------------------------------
# (c) runtime lock sanitizer


class DoubleAcquireError(RuntimeError):
    """A thread re-acquired a non-reentrant lock it already holds. The
    sanitizer raises (with the lock's name) instead of letting the test
    deadlock silently."""


class _SanitizedLock:
    """Instrumented stand-in for ``threading.Lock``/``RLock``. Delegates
    to a real lock; records acquisition-order edges, hold-while-blocking
    events, and same-thread double-acquires with the monitor."""

    def __init__(self, monitor: "LockMonitor", kind: str):
        self._inner = (_REAL_RLOCK if kind == "rlock" else _REAL_LOCK)()
        self._monitor = monitor
        self._kind = kind
        self.name: Optional[str] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        mon = self._monitor
        mon._resolve_name(self)
        stack = mon._stack()
        if any(lk is self for lk in stack):
            if self._kind == "lock":
                mon._record_double(self)
                raise DoubleAcquireError(
                    f"double-acquire of non-reentrant lock "
                    f"{self.name or '<anonymous>'} on thread "
                    f"{threading.current_thread().name}")
            got = self._inner.acquire(blocking, timeout)
            if got:
                stack.append(self)      # reentrant: no new edge
            return got
        got = self._inner.acquire(False)
        if not got:
            if stack:
                mon._record_blocked(stack[-1], self)
            if not blocking:
                return False
            got = (self._inner.acquire(True) if timeout < 0
                   else self._inner.acquire(True, timeout))
            if not got:
                return False
        mon._record_acquire(stack, self)
        stack.append(self)
        return True

    def release(self):
        stack = self._monitor._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _SanitizedCondition:
    """Instrumented ``threading.Condition``: the enter/exit (acquire/
    release) side is recorded like a lock; ``wait*``/``notify*``
    delegate to a real condition (which manages its own lock state —
    the brief release inside ``wait`` is invisible to the monitor, a
    documented approximation: a blocked waiter acquires nothing)."""

    def __init__(self, monitor: "LockMonitor",
                 lock: Optional[object] = None):
        inner = lock._inner if isinstance(lock, _SanitizedLock) else lock
        self._cond = _REAL_CONDITION(inner)
        self._monitor = monitor
        self._kind = "condition"
        self.name: Optional[str] = None

    def acquire(self, *a, **kw) -> bool:
        mon = self._monitor
        mon._resolve_name(self)
        stack = mon._stack()
        reentry = any(lk is self for lk in stack)
        got = self._cond.acquire(*a, **kw)
        if got:
            if not reentry:
                mon._record_acquire(stack, self)
            stack.append(self)
        return got

    def release(self):
        stack = self._monitor._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._cond.release()

    def wait(self, timeout: Optional[float] = None):
        return self._cond.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        return self._cond.wait_for(predicate, timeout)

    def notify(self, n: int = 1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class LockMonitor:
    """What :func:`sanitize` observed: acquisition-order edges between
    named paddle_tpu locks, hold-while-blocking events, double-acquire
    attempts, and raw acquisition counts."""

    def __init__(self):
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        self.edges: Dict[Tuple[str, str], int] = {}
        self.blocked: List[Tuple[str, str]] = []   # (held, wanted)
        self.double_acquires: List[str] = []
        self.acquisitions = 0
        self.locks_created = 0

    # -- bookkeeping (called from instrumented locks) ----------------------
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _record_acquire(self, stack: list, lock):
        with self._mu:
            self.acquisitions += 1
            if lock.name is None:
                return
            for held in stack:
                if held.name is not None and held.name != lock.name:
                    key = (held.name, lock.name)
                    self.edges[key] = self.edges.get(key, 0) + 1

    def _record_blocked(self, held, lock):
        self._resolve_name(lock)
        with self._mu:
            if held.name is not None and lock.name is not None:
                self.blocked.append((held.name, lock.name))

    def _record_double(self, lock):
        with self._mu:
            self.double_acquires.append(lock.name or "<anonymous>")

    def _resolve_name(self, lock):
        """Lazily name a lock at acquisition time by finding the
        paddle_tpu object (or module) that holds it as an attribute —
        the acquiring frame's ``self`` almost always does."""
        if lock.name is not None:
            return
        f = sys._getframe(2)
        depth = 0
        while f is not None and depth < 10:
            if f.f_globals.get("__name__") != __name__:
                slf = f.f_locals.get("self")
                if slf is not None and getattr(
                        type(slf), "__module__", "").startswith(
                        "paddle_tpu"):
                    try:
                        attrs = vars(slf).items()
                    except TypeError:
                        attrs = ()
                    for k, v in attrs:
                        if v is lock:
                            lock.name = f"{type(slf).__qualname__}.{k}"
                            return
                g = f.f_globals
                if g.get("__name__", "").startswith("paddle_tpu"):
                    for k, v in g.items():
                        if v is lock:
                            mod = g["__name__"].rsplit(".", 1)[-1]
                            lock.name = f"{mod}.{k}"
                            return
            f = f.f_back
            depth += 1

    # -- results -----------------------------------------------------------
    def observed_edges(self) -> Set[Tuple[str, str]]:
        with self._mu:
            return set(self.edges)

    def check(self, manifest) -> List[Finding]:
        """``observed ⊆ committed``, scoped to the locks the committed
        graph actually orders (the nodes of its edge set): an observed
        edge between two ordered locks that the static graph does not
        bless is a sanitizer violation — either a real inversion or a
        path the extractor cannot see, and both must be triaged into
        ``tools/lock_order.json``. Leaf locks (never held across other
        acquisitions in the committed model) are out of scope. Accepts
        a loaded manifest dict or a :class:`LockGraph`."""
        if isinstance(manifest, LockGraph):
            committed = set(manifest.edges)
        else:
            committed = {(e[0], e[1])
                         for e in (manifest or {}).get("edges", [])}
        modeled = {n for e in committed for n in e}
        out = []
        for (src, dst) in sorted(self.observed_edges()):
            if src in modeled and dst in modeled \
                    and (src, dst) not in committed:
                out.append(Finding(
                    "sanitizer-violation", "error",
                    f"observed runtime acquisition {src} -> {dst} is "
                    "not in the committed static lock-order graph",
                    fix="triage: a genuine order inversion must be "
                        "fixed; a statically invisible path must be "
                        "added to tools/lock_order.json",
                    engine="concurrency"))
        for name in self.double_acquires:
            out.append(Finding(
                "double-acquire", "error",
                f"runtime double-acquire of non-reentrant {name}",
                engine="concurrency"))
        return out

    def export_metrics(self, reg=None):
        """``concurrency_*`` counters into the observability registry."""
        from paddle_tpu import observability
        reg = reg or observability.default()
        # snapshot under _mu, write counters OUTSIDE it: a registry
        # built inside the sanitize() context guards itself with a
        # _SanitizedLock whose acquire calls back into _record_acquire,
        # and _mu is not reentrant — holding it across reg.counter()
        # self-deadlocks the exporting thread
        with self._mu:
            acquisitions = self.acquisitions
            n_blocked = len(self.blocked)
            n_double = len(self.double_acquires)
            edges = sorted(self.edges.items())
        reg.counter(
            "concurrency_lock_acquisitions_total",
            "lock acquisitions recorded by the sanitizer").inc(
                acquisitions)
        reg.counter(
            "concurrency_hold_while_blocking_total",
            "blocking lock waits entered while holding another "
            "lock").inc(n_blocked)
        reg.counter(
            "concurrency_double_acquire_total",
            "same-thread double-acquires of non-reentrant locks "
            "refused by the sanitizer").inc(n_double)
        for (src, dst), n in edges:
            reg.counter(
                "concurrency_lock_order_edges_total",
                "observed lock acquisition-order edges").inc(
                    n, src=src, dst=dst)
        return self


class _Sanitize:
    """Context manager patching ``threading.Lock/RLock/Condition`` so
    locks *created inside the context* are instrumented. Locks created
    before entry keep their real classes (documented limitation: build
    the threaded system inside the context, as the threaded tests do)."""

    def __init__(self, register_metrics: bool = True):
        self.monitor = LockMonitor()
        self._register_metrics = register_metrics
        self._saved: Dict[str, Any] = {}

    def __enter__(self) -> LockMonitor:
        mon = self.monitor

        def make_lock():
            mon.locks_created += 1
            return _SanitizedLock(mon, "lock")

        def make_rlock():
            mon.locks_created += 1
            return _SanitizedLock(mon, "rlock")

        def make_condition(lock=None):
            mon.locks_created += 1
            return _SanitizedCondition(mon, lock)

        self._saved = {"Lock": threading.Lock, "RLock": threading.RLock,
                       "Condition": threading.Condition}
        threading.Lock = make_lock
        threading.RLock = make_rlock
        threading.Condition = make_condition
        return mon

    def __exit__(self, *exc):
        threading.Lock = self._saved["Lock"]
        threading.RLock = self._saved["RLock"]
        threading.Condition = self._saved["Condition"]
        if self._register_metrics:
            try:
                self.monitor.export_metrics()
            except Exception:
                pass
        return False


def sanitize(register_metrics: bool = True) -> _Sanitize:
    """Run threaded code under the lock sanitizer::

        with sanitize() as mon:
            fleet = build_fleet(...)        # locks created in here
            run_threaded_traffic(fleet)
        assert not mon.check(load_lock_order("tools/lock_order.json"))

    Records actual acquisition orders and hold-while-blocking events,
    raises :class:`DoubleAcquireError` instead of deadlocking on a
    same-thread re-acquire of a non-reentrant lock, and exports
    ``concurrency_*`` metrics on exit."""
    return _Sanitize(register_metrics)
