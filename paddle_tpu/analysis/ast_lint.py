"""AST linter for jit-reachable step functions.

The jaxpr analyzer sees what *traced*; this pass reads the Python
*source* of a step function and flags host-sync idioms that either crash
at trace time or silently sync the device every step:

- ``.item()`` / ``.tolist()`` / ``float()/int()/bool()`` on tracer values
  (device→host transfer per call);
- ``np.asarray`` / ``np.array`` / ``numpy.*`` materialization;
- ``time.time()`` / ``time.perf_counter()`` (trace-time constant — the
  compiled step bakes in ONE timestamp forever);
- bare stdlib ``random.*`` (same: one trace-time draw replayed forever);
- Python ``if``/``while`` on tracer-valued names (trace-time
  ``ConcretizationTypeError``, or a retrace per distinct value when the
  name is a weakly-typed scalar).

Tracer inference is a deliberate, shallow heuristic: the function's
parameters seed the tracer set (minus parameters whose defaults are
plain Python flags — ``training=False``, ``mode="train"``, ``key=None``
— which are static config by convention), and assignments propagate.
``x is None``-style comparisons are static and never flagged. The lint
is per-function — callees are not followed; run it on the function you
``jit``.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List, Optional, Set

from paddle_tpu.analysis.findings import Finding, RULES

_NUMPY_MODULES = {"np", "numpy"}
_TIME_CALLS = {"time", "perf_counter", "monotonic", "process_time"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_CAST_BUILTINS = {"float", "int", "bool", "complex"}


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_none_compare(test: ast.AST) -> bool:
    """`x is None` / `x is not None` / `x == None` — static, never a sync."""
    if not isinstance(test, ast.Compare):
        return False
    return any(isinstance(c, ast.Constant) and c.value is None
               for c in test.comparators)


def _static_default(default: ast.AST) -> bool:
    """Defaults that mark a parameter as static config, not a tracer."""
    return isinstance(default, ast.Constant) and isinstance(
        default.value, (bool, str, int, float, type(None)))


class _FnLinter(ast.NodeVisitor):
    def __init__(self, fn_node: ast.FunctionDef, filename: str,
                 line_offset: int):
        self.filename = filename
        self.off = line_offset
        self.findings: List[Finding] = []
        self.tracers: Set[str] = set()
        args = fn_node.args
        pos = list(args.posonlyargs) + list(args.args)
        n_def = len(args.defaults)
        defaults = [None] * (len(pos) - n_def) + list(args.defaults)
        for a, d in zip(pos, defaults):
            if a.arg != "self" and (d is None or not _static_default(d)):
                self.tracers.add(a.arg)
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is None or not _static_default(d):
                self.tracers.add(a.arg)
        if args.vararg:
            self.tracers.add(args.vararg.arg)
        if args.kwarg:
            self.tracers.add(args.kwarg.arg)

    # -- helpers ------------------------------------------------------------
    def _loc(self, node) -> str:
        return f"{self.filename}:{node.lineno + self.off}"

    def _tracer_expr(self, node: ast.AST) -> bool:
        return bool(_names_in(node) & self.tracers)

    def _add(self, rule: str, node: ast.AST, message: str, fix: str):
        self.findings.append(Finding(
            rule, RULES[rule][0], message, location=self._loc(node),
            fix=fix, engine="ast"))

    # -- dataflow -----------------------------------------------------------
    def visit_Assign(self, node: ast.Assign):
        if self._tracer_expr(node.value):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        self.tracers.add(n.id)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        if self._tracer_expr(node.value) and isinstance(node.target,
                                                        ast.Name):
            self.tracers.add(node.target.id)
        self.generic_visit(node)

    def visit_For(self, node: ast.For):
        if self._tracer_expr(node.iter):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    self.tracers.add(n.id)
        self.generic_visit(node)

    # -- rules --------------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        fn = node.func
        # x.item() / x.tolist() / x.block_until_ready()
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_METHODS \
                and self._tracer_expr(fn.value):
            self._add("ast-host-sync", node,
                      f"`.{fn.attr}()` on a tracer value: device->host "
                      "sync inside the step",
                      "return the array in the metrics dict and convert "
                      "on the host after dispatch")
        # np.asarray / np.array / numpy.*
        elif isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in _NUMPY_MODULES and \
                fn.attr in ("asarray", "array", "copy"):
            self._add("ast-host-sync", node,
                      f"`{fn.value.id}.{fn.attr}(...)` materializes a "
                      "host numpy array inside jit-reachable code",
                      "use jnp.asarray (stays on device) or hoist the "
                      "conversion out of the step")
        # time.time() family
        elif isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id == "time" and fn.attr in _TIME_CALLS:
            self._add("ast-host-sync", node,
                      f"`time.{fn.attr}()` in jit-reachable code is a "
                      "trace-time constant: the compiled step replays ONE "
                      "timestamp forever",
                      "time on the host around the step call "
                      "(Trainer/StepTelemetry already does)")
        # bare stdlib random.*
        elif isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "random":
            self._add("ast-host-sync", node,
                      f"stdlib `random.{fn.attr}(...)` in jit-reachable "
                      "code: one trace-time draw, baked into the "
                      "compiled step",
                      "use jax.random with an explicit key")
        # float(x) / int(x) / bool(x) on a tracer
        elif isinstance(fn, ast.Name) and fn.id in _CAST_BUILTINS and \
                node.args and self._tracer_expr(node.args[0]):
            self._add("ast-host-sync", node,
                      f"`{fn.id}(...)` on a tracer value forces a "
                      "device->host sync (or a trace-time crash)",
                      "keep it as a jnp scalar; convert after the step "
                      "returns")
        self.generic_visit(node)

    def _check_branch(self, node, kind: str):
        if _is_none_compare(node.test):
            return
        if self._tracer_expr(node.test):
            names = sorted(_names_in(node.test) & self.tracers)
            self._add("ast-tracer-branch", node,
                      f"Python `{kind}` on tracer value(s) "
                      f"{', '.join(names)}: crashes at trace time under "
                      "jit (ConcretizationTypeError) or forces a retrace "
                      "per value",
                      "use jnp.where / lax.cond / lax.while_loop, or "
                      "hoist the decision out of the jitted function")

    def visit_If(self, node: ast.If):
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        self._check_branch(node, "while")
        self.generic_visit(node)


def lint_source(src: str, *, filename: str = "<src>",
                line_offset: int = 0) -> List[Finding]:
    """Lint already-extracted function source (first def found)."""
    tree = ast.parse(textwrap.dedent(src))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            linter = _FnLinter(node, filename, line_offset)
            linter.visit(node)
            return linter.findings
    return []


def lint_callable(fn) -> List[Finding]:
    """Lint a function's source; silently returns [] when source is
    unavailable (builtins, jitted wrappers, REPL lambdas)."""
    inner = inspect.unwrap(getattr(fn, "__wrapped__", fn))
    try:
        src = inspect.getsource(inner)
        filename = inspect.getsourcefile(inner) or "<src>"
        _, first_line = inspect.getsourcelines(inner)
    except (OSError, TypeError):
        return []
    try:
        return lint_source(src, filename=filename,
                           line_offset=max(0, first_line - 1))
    except SyntaxError:
        return []
