"""Framework self-conformance lints: interface drift + reject vocabulary.

Two small rules that turn recurring review findings into CI gates,
reported on the same findings spine as the other tiers:

- **interface-drift**: every :class:`ReplicaHandle` implementation
  (``LocalReplica``, ``NetReplica``, the duck-typed ``ChaosReplica``)
  must carry every handle method with a matching signature, and the
  wire protocol's server-side dispatch table (``replica_server.py
  _dispatch``) must name an op for every handle method — a new method
  added to the handle but missing from the dispatch would otherwise
  surface as a runtime ``RemoteError`` on the first fleet that crosses
  a socket.
- **reject-vocab-drift**: the ``Reject.reason`` vocabulary has one
  source of truth (``scheduler.REJECT_REASONS``); every literal reason
  constructed anywhere in the serving plane must be registered, and
  every registered reason must be constructed somewhere (dead vocab is
  drift in the other direction).
"""

from __future__ import annotations

import ast
import inspect
import os
from typing import Dict, List, Optional, Set, Tuple

from paddle_tpu.analysis.findings import Finding

__all__ = ["lint_interfaces", "lint_reject_vocab"]

#: server-only wire ops with no ReplicaHandle counterpart (session
#: setup, drain control, process teardown)
_SERVER_ONLY_OPS = frozenset({"hello", "set_draining", "shutdown"})

#: handle methods that deliberately have no wire op: ``close()`` is the
#: client-side transport teardown (the server side is the ``shutdown``
#: op), and ``start``/``stop``/``running`` are LocalReplica's thread
#: controls, not part of the protocol
_NO_WIRE_OP = frozenset({"close"})


def _handle_methods(base: type) -> Dict[str, inspect.Signature]:
    out = {}
    for name, fn in vars(base).items():
        if name.startswith("_") or not callable(fn):
            continue
        out[name] = inspect.signature(fn)
    return out


def _sig_shape(sig: inspect.Signature) -> List[Tuple[str, str, bool]]:
    """Comparable shape: (name, kind, has_default) per parameter —
    annotations and default *values* may legitimately differ between
    the protocol and a transport."""
    return [(p.name, p.kind.name, p.default is not inspect.Parameter.empty)
            for p in sig.parameters.values()]


def _dispatch_ops(server_source: str, filename: str
                  ) -> Tuple[Set[str], Set[str]]:
    """``(ops, hello_keys)``: the op strings ``ReplicaServer._dispatch``
    compares against (``if op == "submit": ...``) plus the literal keys
    of the hello-handshake reply dict (immutable per-replica config like
    ``page_size`` rides the handshake instead of its own op). Read
    statically so the lint needs no socket, no engine, and no spawned
    process."""
    ops: Set[str] = set()
    hello_keys: Set[str] = set()
    tree = ast.parse(server_source, filename=filename)
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        for meth in cls.body:
            if not isinstance(meth, ast.FunctionDef) \
                    or meth.name != "_dispatch":
                continue
            for node in ast.walk(meth):
                if not isinstance(node, ast.If) \
                        or not isinstance(node.test, ast.Compare) \
                        or len(node.test.comparators) != 1:
                    continue
                left = node.test.left
                right = node.test.comparators[0]
                op = None
                for a, b in ((left, right), (right, left)):
                    if (isinstance(a, ast.Name) and a.id == "op"
                            and isinstance(b, ast.Constant)
                            and isinstance(b.value, str)):
                        op = b.value
                if op is None:
                    continue
                ops.add(op)
                if op == "hello":
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Dict):
                            hello_keys.update(
                                k.value for k in sub.keys
                                if isinstance(k, ast.Constant)
                                and isinstance(k.value, str))
    return ops, hello_keys


def lint_interfaces() -> List[Finding]:
    """ReplicaHandle conformance: implementations + wire dispatch."""
    from paddle_tpu.serving.fleet import faults, replica
    from paddle_tpu.serving.fleet.net import replica_server
    from paddle_tpu.serving.fleet.net import replica as net_replica

    base = replica.ReplicaHandle
    impls = (replica.LocalReplica, net_replica.NetReplica,
             faults.ChaosReplica)
    methods = _handle_methods(base)
    out: List[Finding] = []
    for impl in impls:
        for name, base_sig in sorted(methods.items()):
            fn = getattr(impl, name, None)
            if fn is None:
                out.append(Finding(
                    "interface-drift", "error",
                    f"{impl.__name__} is missing ReplicaHandle method "
                    f"{name}()",
                    location=inspect.getsourcefile(impl) or "",
                    fix=f"implement {name}{base_sig} (or inherit it)",
                    engine="concurrency"))
                continue
            # inherited-from-base default implementations conform by
            # construction; only compare overrides
            if getattr(impl, name) is getattr(base, name, None):
                continue
            impl_sig = inspect.signature(fn)
            if _sig_shape(impl_sig) != _sig_shape(base_sig):
                out.append(Finding(
                    "interface-drift", "error",
                    f"{impl.__name__}.{name}{impl_sig} drifted from "
                    f"ReplicaHandle.{name}{base_sig}",
                    location=inspect.getsourcefile(impl) or "",
                    fix="match the protocol's parameter names/kinds "
                        "(annotations and default values are free)",
                    engine="concurrency"))
    server_file = inspect.getsourcefile(replica_server)
    with open(server_file) as f:
        ops, hello_keys = _dispatch_ops(f.read(), server_file)
    base_name = os.path.basename(server_file)
    for name in sorted(set(methods) - _NO_WIRE_OP):
        if name not in ops and name not in hello_keys:
            out.append(Finding(
                "interface-drift", "error",
                f"ReplicaHandle.{name}() has no op in the wire "
                f"dispatch table ({base_name} _dispatch): a NetReplica "
                "call would die as a runtime RemoteError",
                location=base_name,
                fix=f'add `if op == "{name}":` to '
                    f"ReplicaServer._dispatch (and NetReplica)",
                engine="concurrency"))
    for op in sorted(ops - set(methods) - _SERVER_ONLY_OPS):
        out.append(Finding(
            "interface-drift", "error",
            f"wire dispatch op {op!r} maps to no ReplicaHandle method "
            "and is not a declared server-only op",
            location=base_name,
            fix="remove the dead op or add the handle method",
            engine="concurrency"))
    return out


#: call shapes whose literal reason argument feeds a Reject: the
#: constructor itself (positional 0 / reason=), and the router's
#: `_shed_redrive(frid, rec, reason, src)` funnel
_REASON_ARG = {"Reject": 0, "_shed_redrive": 2}


def _literal_reasons(source: str) -> List[Tuple[str, int]]:
    """(reason, lineno) for every literal reason fed to a Reject
    construction (directly or via the router's shed funnel)."""
    out: List[Tuple[str, int]] = []
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        if name not in _REASON_ARG:
            continue
        pos = _REASON_ARG[name]
        arg: Optional[ast.expr] = None
        if len(node.args) > pos:
            arg = node.args[pos]
        for kw in node.keywords:
            if kw.arg == "reason":
                arg = kw.value
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, node.lineno))
    return out


def lint_reject_vocab(root: Optional[str] = None) -> List[Finding]:
    """Every literal ``Reject`` reason in the serving plane must be in
    ``scheduler.REJECT_REASONS``, and every registered reason must be
    constructed somewhere (no dead vocabulary)."""
    from paddle_tpu.serving.scheduler import REJECT_REASONS

    if root is None:
        root = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "serving")
    out: List[Finding] = []
    seen: Dict[str, str] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                source = f.read()
            for reason, lineno in _literal_reasons(source):
                seen.setdefault(reason, f"{fn}:{lineno}")
                if reason not in REJECT_REASONS:
                    out.append(Finding(
                        "reject-vocab-drift", "error",
                        f"Reject reason {reason!r} is not registered "
                        "in scheduler.REJECT_REASONS",
                        location=f"{fn}:{lineno}",
                        fix="add it to REJECT_REASONS (one source of "
                            "truth: wire round-trip validation and the "
                            "parametrized wire tests read it)",
                        engine="concurrency"))
    for reason in sorted(set(REJECT_REASONS) - set(seen)):
        out.append(Finding(
            "reject-vocab-drift", "error",
            f"registered Reject reason {reason!r} is constructed "
            "nowhere in the serving plane (dead vocabulary)",
            location="scheduler.py",
            fix="remove it from REJECT_REASONS or wire up the "
                "construction site",
            engine="concurrency"))
    return out
