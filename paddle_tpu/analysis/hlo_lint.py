"""HLO-level lint rules over a :class:`~paddle_tpu.analysis.cost_model.
CostReport`, plus the bucket-coverage proof for the serving engines.

The third tier of the static-analysis stack (AST → jaxpr → HLO): these
rules fire on hazards only visible in the *lowered* program —

- **unexpected-collective** — collectives outside a declared allowlist.
  A single-device serving decode/prefill step must contain zero; on a
  tensor-parallel mesh only the planned kinds (e.g. the tp all-reduce
  after sharded attention) are acceptable, and anything else is an
  implicit cross-device sync the sharding specs accidentally created.
- **resharding-churn** — adjacent sharding annotations that disagree on
  a large value's layout, forcing an implicit transpose/all-to-all
  between them (detected as ``@Sharding``→``@Sharding`` chains by the
  cost walker).
- **peak-hbm-budget** — the liveness-based peak-HBM estimate exceeds
  the preset's declared budget.
- **flops budget** (reported as ``cost-regression``) — static flops
  exceed the declared budget.
- **bucket-coverage** — the ahead-of-time half of the zero-recompile
  invariant: statically enumerate every pow2 bucket signature the
  engine's steady-state loop can request and prove ``warmup()``'s
  precompile plan covers it. The reachable set is derived from the
  *step-side* bucketing functions and the warmed set from the
  *warmup-side* plan — two independent derivations, so a drift in
  either fires the rule before the first mid-serving recompile.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.findings import Finding, RULES


def _mb(n: int) -> str:
    return f"{n / (1 << 20):.2f}MiB"


def lint_cost_report(cost, *,
                     collective_allowlist: Optional[Sequence[str]] = None,
                     hbm_budget_bytes: Optional[int] = None,
                     flops_budget: Optional[int] = None) -> List[Finding]:
    """Findings for one :class:`CostReport`.

    ``collective_allowlist``: ``None`` skips the collective check
    entirely; a sequence (possibly empty — the single-device serving
    contract) permits exactly those kinds. ``hbm_budget_bytes`` /
    ``flops_budget``: ``None`` skips that budget."""
    findings: List[Finding] = []
    if collective_allowlist is not None:
        allowed = set(collective_allowlist)
        for kind, nbytes in sorted(cost.collective_kinds().items()):
            if kind in allowed:
                continue
            sites = [c for c in cost.collectives if c.kind == kind]
            ax = sorted({c.axis for c in sites if c.axis})
            findings.append(Finding(
                "unexpected-collective", RULES["unexpected-collective"][0],
                f"{len(sites)} `{kind}` op(s) moving {_mb(nbytes)} "
                f"{'over axis ' + '/'.join(ax) + ' ' if ax else ''}"
                f"in the lowered program, outside the allowlist "
                f"{sorted(allowed) or '(none)'}",
                location=sites[0].location,
                fix="fix the sharding specs that force the implicit "
                    "collective, or declare it in the surface's "
                    "allowlist if the comm is intended",
                engine="hlo"))
    for site in cost.resharding:
        findings.append(Finding(
            "resharding-churn", RULES["resharding-churn"][0],
            f"a {_mb(site.bytes)} value is resharded "
            f"{site.src} -> {site.dst} between adjacent sharding "
            "annotations: the compiler inserts an implicit "
            "transpose/all-to-all here every step",
            location=site.location,
            fix="make the adjacent with_sharding_constraint specs "
                "agree, or reorder the computation so the layout "
                "changes once",
            engine="hlo"))
    if hbm_budget_bytes is not None and \
            cost.peak_hbm_bytes > hbm_budget_bytes:
        findings.append(Finding(
            "peak-hbm-budget", RULES["peak-hbm-budget"][0],
            f"static peak-HBM estimate {_mb(cost.peak_hbm_bytes)} "
            f"exceeds the declared budget {_mb(hbm_budget_bytes)}",
            location=cost.name,
            fix="donate the large buffers (cuts old+new copies), shrink "
                "the surface, or raise the committed budget with a "
                "rationale",
            engine="hlo"))
    if flops_budget is not None and cost.total_flops > flops_budget:
        findings.append(Finding(
            "cost-regression", RULES["cost-regression"][0],
            f"static flops {cost.total_flops:,} exceed the declared "
            f"budget {flops_budget:,}",
            location=cost.name,
            fix="profile what grew (CostReport.per_op names the op), or "
                "raise the committed budget with a rationale",
            engine="hlo"))
    return findings


# ---------------------------------------------------------------------------
# bucket coverage: reachable signatures vs the warmup plan
# ---------------------------------------------------------------------------

def _coverage_findings(reachable: Set[Tuple], warmed: Set[Tuple],
                       name: str, engine_kind: str) -> List[Finding]:
    findings = []
    for sig in sorted(reachable - warmed, key=str):
        findings.append(Finding(
            "bucket-coverage", RULES["bucket-coverage"][0],
            f"{engine_kind} bucket signature {sig} is statically "
            "reachable by the steady-state loop but missing from "
            "warmup's precompile plan: the first request hitting it "
            "recompiles mid-serving",
            location=f"{name}:{sig}",
            fix="align warmup()'s bucket enumeration with the step-side "
                "bucketing (warmup_plan() must cover every reachable "
                "signature)",
            engine="hlo"))
    return findings


def serving_bucket_coverage(engine, warmed: Optional[Set[Tuple]] = None,
                            name: str = "serving") -> List[Finding]:
    """Prove ``ServingEngine.warmup()`` precompiles every decode/prefill
    signature ``step()`` can request.

    Reachable signatures are enumerated from the *step-side* bucketing
    (``_pow2_width`` over every live page count, ``_pow2_count`` over
    every in-prefill slot count); the warmed set defaults to the
    *warmup-side* :meth:`ServingEngine.warmup_plan`. Pass ``warmed``
    explicitly to audit a doctored or partial warmup (the tests do)."""
    if warmed is None:
        warmed = set(engine.warmup_plan())
    return _coverage_findings(set(engine.reachable_signatures()),
                              set(warmed), name, "serving")


def embedding_bucket_coverage(cache, max_uniq: int,
                              warmed: Optional[Set[Tuple]] = None,
                              name: str = "embedding"
                              ) -> List[Finding]:
    """Prove ``DeviceEmbeddingCache.warmup(max_uniq)`` precompiles every
    gather/install width a batch with up to ``max_uniq`` unique ids can
    request (same two-sided derivation as the serving variant)."""
    if warmed is None:
        warmed = set(cache.warmup_plan(max_uniq))
    return _coverage_findings(set(cache.reachable_buckets(max_uniq)),
                              set(warmed), name, "embedding")


def check_bucket_coverage(engine, *, max_uniq: Optional[int] = None,
                          warmed: Optional[Set[Tuple]] = None,
                          name: Optional[str] = None) -> List[Finding]:
    """Dispatch on engine type: a token-serving engine (has
    ``reachable_signatures``) or an embedding cache/engine (needs
    ``max_uniq``)."""
    if hasattr(engine, "reachable_signatures"):
        return serving_bucket_coverage(engine, warmed,
                                       name or "serving")
    cache = getattr(engine, "cache", engine)
    if max_uniq is None:
        raise ValueError("embedding coverage needs max_uniq (the "
                         "warmup's per-batch unique-id bound)")
    return embedding_bucket_coverage(cache, max_uniq, warmed,
                                     name or "embedding")
