"""Entry points: lint a step function ahead of time.

``lint_fn(fn, *abstract_args, **abstract_kwargs)`` traces ``fn`` with
abstract values (no compile, no execute) and runs the jaxpr analyzer +
AST linter; ``lint_train_step`` wraps the framework's ``step(state,
**batch)`` convention (used by ``Trainer.fit(lint=...)`` and the
Executor's compile-time hook); ``enforce`` turns a report into warnings
or a :class:`LintError` per the requested mode.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from paddle_tpu.analysis import ast_lint, jaxpr_lint
from paddle_tpu.analysis.findings import Finding, Report, Suppressions

LINT_MODES = ("off", "warn", "error")


class LintError(RuntimeError):
    """Raised by ``enforce`` when a lint report fails in 'error' mode."""

    def __init__(self, report: Report):
        self.report = report
        super().__init__("static analysis failed:\n" + report.render_text())


def abstractify(tree: Any) -> Any:
    """Concrete array pytree -> ShapeDtypeStruct pytree (pass-through for
    leaves that are already abstract)."""
    def one(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return x
    return jax.tree_util.tree_map(one, tree)


def _donation_flags(fn, args, kwargs, donate_argnums):
    """Per-flat-input donation flags, or None when undeterminable.

    Explicit ``donate_argnums`` wins; otherwise a jit-wrapped ``fn``
    reports its own flags through ``Lowered.args_info``."""
    if donate_argnums is not None:
        if isinstance(donate_argnums, int):
            donate_argnums = (donate_argnums,)
        flags = []
        for i, a in enumerate(args):
            n = len(jax.tree_util.tree_leaves(a))
            flags.extend([i in donate_argnums] * n)
        for _, v in sorted(kwargs.items()):
            flags.extend([False] * len(jax.tree_util.tree_leaves(v)))
        return flags
    if hasattr(fn, "lower"):
        try:
            info = fn.lower(*args, **kwargs).args_info
            return [a.donated for a in jax.tree_util.tree_leaves(info)]
        except Exception:
            return None
    return None


def lint_fn(fn, *args,
            donate_argnums=None,
            donated=None,
            plan=None,
            state_argnum: Optional[int] = 0,
            name: Optional[str] = None,
            ast: bool = True,
            ast_fn=None,
            suppressions: Optional[Suppressions] = None,
            donation_min_bytes: int = 1 << 16,
            replicated_min_bytes: int = 1 << 20,
            registry: bool = True,
            cost: bool = False,
            hbm_budget_bytes: Optional[int] = None,
            flops_budget: Optional[int] = None,
            collective_allowlist=None,
            mesh_axes=None,
            **kwargs) -> Report:
    """Statically lint ``fn(*args, **kwargs)``; returns a :class:`Report`.

    ``args``/``kwargs`` are example or abstract inputs (arrays and
    ``jax.ShapeDtypeStruct`` both work — everything is abstracted before
    tracing, so nothing executes). ``donate_argnums`` feeds the donation
    rule (a jit-wrapped ``fn`` reports its own donation flags, so it is
    usually unnecessary). ``plan`` (a ``parallel.plan.ShardingPlan``)
    enables the replicated-large check against the argument at
    ``state_argnum``. ``ast=False`` skips the source linter; ``ast_fn``
    lints a different function's source than the traced one (used when
    ``fn`` is an adapter closure around the real user step). Findings
    are counted into the observability registry unless
    ``registry=False``.

    ``cost=True`` (implied by any of the cost options) additionally
    lowers the function to StableHLO, attaches the static
    :class:`~paddle_tpu.analysis.cost_model.CostReport` as
    ``report.cost``, and runs the HLO-tier rules:
    ``collective_allowlist`` (a sequence, possibly empty) gates
    ``unexpected-collective``, ``hbm_budget_bytes``/``flops_budget``
    gate the budget rules, resharding chains always report, and
    ``mesh_axes`` (``{axis: size}``) attributes collective bytes to
    mesh axes. Lowering only — still nothing compiles or executes.
    """
    if hbm_budget_bytes is not None or flops_budget is not None \
            or collective_allowlist is not None or mesh_axes is not None:
        cost = True
    args = tuple(abstractify(a) for a in args)
    kwargs = {k: abstractify(v) for k, v in kwargs.items()}
    name = name or getattr(fn, "__name__", None) or type(fn).__name__

    closed = jax.make_jaxpr(fn)(*args, **kwargs)

    # invar -> human label ("args[0]['params']['w']")
    flat, _ = jax.tree_util.tree_flatten_with_path((args, kwargs))
    labels = [jax.tree_util.keystr(p) for p, _ in flat]
    arg_labels = list(zip(closed.jaxpr.invars, labels))

    if donated is None:
        donated = _donation_flags(fn, args, kwargs, donate_argnums)
    state_tree = None
    if plan is not None and state_argnum is not None \
            and state_argnum < len(args):
        state_tree = args[state_argnum]

    report = Report(name, suppressions=suppressions)
    report.extend(jaxpr_lint.analyze_jaxpr(
        closed, name=name, arg_labels=arg_labels, donated=donated,
        donation_min_bytes=donation_min_bytes, plan=plan,
        state_tree=state_tree, replicated_min_bytes=replicated_min_bytes))
    if ast:
        report.extend(ast_lint.lint_callable(ast_fn or fn))
    if cost:
        from paddle_tpu.analysis import cost_model, hlo_lint
        if hasattr(fn, "lower"):
            lowered = fn.lower(*args, **kwargs)
        elif donate_argnums is not None:
            lowered = jax.jit(fn, donate_argnums=donate_argnums).lower(
                *args, **kwargs)
        else:
            lowered = jax.jit(fn).lower(*args, **kwargs)
        report.cost = cost_model.estimate_lowered(
            lowered, name=name, donated=donated, mesh_axes=mesh_axes)
        report.extend(hlo_lint.lint_cost_report(
            report.cost, collective_allowlist=collective_allowlist,
            hbm_budget_bytes=hbm_budget_bytes, flops_budget=flops_budget))
    if registry:
        report.count_into_registry()
    return report


def lint_train_step(step, state, batch, *, plan=None, **kw) -> Report:
    """Lint a ``step(state, **batch) -> (state, metrics)`` function with
    this framework's train-step calling convention (arg 0 is the donated
    state; the batch feeds as keyword arrays). Batch keys are passed
    through an adapter closure, so they can never collide with lint
    options; the AST pass still reads the real step's source, and a
    jit-wrapped step still reports its own donation flags."""
    state = abstractify(state)
    batch = {k: abstractify(v) for k, v in batch.items()}

    def _kw_step(state, batch):
        return step(state, **batch)

    donated = kw.pop("donated", None)
    if donated is None:
        # flag extraction runs against the REAL step (the adapter has no
        # .lower); jit flattens ((state,), batch-kwargs) to the same leaf
        # order as our positional (state, batch)
        donated = _donation_flags(step, (state,), batch, None)
    return lint_fn(_kw_step, state, batch, plan=plan, donated=donated,
                   ast_fn=step,
                   name=kw.pop("name", None) or getattr(
                       step, "__name__", "train_step"), **kw)


def enforce(report: Report, mode: str, *, log_fn=None):
    """Apply a lint mode: 'off' ignores, 'warn' logs every finding,
    'error' additionally raises :class:`LintError` when any
    error-severity finding survives suppression. Returns the report."""
    if mode not in LINT_MODES:
        raise ValueError(f"lint mode must be one of {LINT_MODES}, "
                         f"got {mode!r}")
    if mode == "off" or not len(report):
        return report
    text = report.render_text()
    if log_fn is not None:
        log_fn(text)
    else:
        import warnings
        warnings.warn(text, stacklevel=3)
    if mode == "error" and not report.ok("error"):
        raise LintError(report)
    return report
