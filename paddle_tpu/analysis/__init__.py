"""Static analysis subsystem: graph lint for TPU-native step functions.

The reference framework finds every hazard at *runtime* (per-op
``FLAGS_check_nan_inf`` guards, operator.cc:35); on TPU the expensive
failure modes — host syncs in the step loop, accidental f64, undonated
buffers doubling peak HBM, reused PRNG keys, replicated multi-GB params
— are all statically visible in the traced jaxpr before a single step
runs. This package is the ahead-of-time complement to the observability
subsystem's runtime ``RecompileDetector``:

The analysis runs in four tiers, one per program representation:

- :mod:`~paddle_tpu.analysis.ast_lint` — reads step-function *source*
  for host-sync idioms (``.item()``, ``np.asarray``, ``time.time()``,
  stdlib ``random``) and Python branches on tracer values.
- :mod:`~paddle_tpu.analysis.jaxpr_lint` — walks the closed *jaxpr*
  (through pjit/scan/while/cond/remat) for host callbacks, f64
  promotions, missed donation, PRNG key reuse, and plan-degenerate
  replication.
- :mod:`~paddle_tpu.analysis.cost_model` +
  :mod:`~paddle_tpu.analysis.hlo_lint` — lower to *StableHLO* and walk
  the module: per-op flops/bytes, a liveness-based peak-HBM estimate,
  per-collective accounting (:class:`CostReport`), and the HLO-tier
  rules — unexpected collectives, resharding churn, peak-HBM budgets,
  and the bucket-coverage proof that serving ``warmup()`` precompiles
  every reachable pow2 signature.
- :mod:`~paddle_tpu.analysis.concurrency` — the *host threads* around
  the jitted steps: the :func:`guarded_by` lock-discipline lint, the
  static lock-order graph committed as ``tools/lock_order.json``
  (cycles = potential deadlocks, drift-gated like cost budgets), and
  the :func:`sanitize` runtime lock sanitizer that proves
  ``observed ⊆ static`` during threaded tests. Its sibling
  :mod:`~paddle_tpu.analysis.conformance` gates ReplicaHandle /
  wire-dispatch interface drift and the single-source
  ``Reject.reason`` vocabulary.
- :mod:`~paddle_tpu.analysis.findings` — the reporting spine: structured
  :class:`Finding` records, text/JSON rendering, registry counting, and
  committed :class:`Suppressions` for CI (with stale-entry detection).

Entry points: :func:`lint_fn` / :func:`lint_train_step` here (pass
``cost=True`` or any budget option for the HLO tier),
``Trainer.fit(lint='warn'|'error'|'off', lint_cost=...)``,
``Executor(lint=..., lint_cost=...)``, and the ``tools/graph_lint.py``
CLI over the model zoo (``--cost`` / ``--cost-diff`` gate the committed
``tools/cost_budgets.json`` budgets in CI — a perf-regression gate that
needs no hardware).
"""

from paddle_tpu.analysis.api import (LINT_MODES, LintError, abstractify,
                                     enforce, lint_fn, lint_train_step)
from paddle_tpu.analysis.ast_lint import lint_callable, lint_source
from paddle_tpu.analysis.concurrency import (DoubleAcquireError, LockGraph,
                                             LockMonitor,
                                             extract_lock_graph,
                                             guarded_by, lint_concurrency,
                                             lint_locks, load_lock_order,
                                             lock_order_diff,
                                             lock_order_manifest, sanitize)
from paddle_tpu.analysis.conformance import (lint_interfaces,
                                             lint_reject_vocab)
from paddle_tpu.analysis.cost_model import (CostReport, analyze_module,
                                            estimate_cost,
                                            estimate_lowered)
from paddle_tpu.analysis.findings import (RULES, SEVERITIES, Finding,
                                          Report, Suppressions)
from paddle_tpu.analysis.hlo_lint import (check_bucket_coverage,
                                          embedding_bucket_coverage,
                                          lint_cost_report,
                                          serving_bucket_coverage)
from paddle_tpu.analysis.jaxpr_lint import analyze_jaxpr

__all__ = [
    "CostReport", "DoubleAcquireError", "LINT_MODES", "LintError",
    "LockGraph", "LockMonitor", "RULES", "SEVERITIES", "Finding",
    "Report", "Suppressions", "abstractify", "analyze_jaxpr",
    "analyze_module", "check_bucket_coverage", "embedding_bucket_coverage",
    "enforce", "estimate_cost", "estimate_lowered", "extract_lock_graph",
    "guarded_by", "lint_callable", "lint_concurrency", "lint_cost_report",
    "lint_fn", "lint_interfaces", "lint_locks", "lint_reject_vocab",
    "lint_source", "lint_train_step", "load_lock_order",
    "lock_order_diff", "lock_order_manifest", "sanitize",
    "serving_bucket_coverage",
]
