"""Static analysis subsystem: graph lint for TPU-native step functions.

The reference framework finds every hazard at *runtime* (per-op
``FLAGS_check_nan_inf`` guards, operator.cc:35); on TPU the expensive
failure modes — host syncs in the step loop, accidental f64, undonated
buffers doubling peak HBM, reused PRNG keys, replicated multi-GB params
— are all statically visible in the traced jaxpr before a single step
runs. This package is the ahead-of-time complement to the observability
subsystem's runtime ``RecompileDetector``:

- :mod:`~paddle_tpu.analysis.jaxpr_lint` — walks the closed jaxpr
  (through pjit/scan/while/cond) for host callbacks, f64 promotions,
  missed donation, PRNG key reuse, and plan-degenerate replication.
- :mod:`~paddle_tpu.analysis.ast_lint` — reads step-function source for
  host-sync idioms (``.item()``, ``np.asarray``, ``time.time()``, stdlib
  ``random``) and Python branches on tracer values.
- :mod:`~paddle_tpu.analysis.findings` — the reporting spine: structured
  :class:`Finding` records, text/JSON rendering, registry counting, and
  committed :class:`Suppressions` for CI.

Entry points: :func:`lint_fn` / :func:`lint_train_step` here,
``Trainer.fit(lint='warn'|'error'|'off')``, ``Executor(lint=...)``, and
the ``tools/graph_lint.py`` CLI over the model zoo.
"""

from paddle_tpu.analysis.api import (LINT_MODES, LintError, abstractify,
                                     enforce, lint_fn, lint_train_step)
from paddle_tpu.analysis.ast_lint import lint_callable, lint_source
from paddle_tpu.analysis.findings import (RULES, SEVERITIES, Finding,
                                          Report, Suppressions)
from paddle_tpu.analysis.jaxpr_lint import analyze_jaxpr

__all__ = [
    "LINT_MODES", "LintError", "RULES", "SEVERITIES", "Finding", "Report",
    "Suppressions", "abstractify", "analyze_jaxpr", "enforce",
    "lint_callable", "lint_fn", "lint_source", "lint_train_step",
]
