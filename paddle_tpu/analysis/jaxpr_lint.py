"""Jaxpr analyzer: static hazard detection over a traced step function.

Walks a closed jaxpr (recursing through pjit / scan / while / cond /
remat (``jax.checkpoint``) / custom-derivative sub-jaxprs — remat
bodies are stored as OPEN jaxprs and need their own unwrap) and emits
findings for the TPU failure
modes that are statically visible before a single step runs:

- **host-callback / debug-callback** — ``pure_callback`` / ``io_callback``
  / ``debug_callback`` equations: each is a device→host→device round trip
  in the compiled step (the reference's runtime ``PrintFetchVars`` world
  leaking into the hot path).
- **f64-promotion** — float64/complex128 avals anywhere in the program:
  TPUs emulate f64 in software, and the usual cause is an accidental
  weak-type promotion from a Python float / numpy scalar.
- **undonated-buffer** — large inputs with a same-shape/dtype output that
  are not donated: peak HBM holds both the old and new copy of every
  such buffer (the static face of ``donate_argnums``, parallel/api.py).
- **prng-key-reuse** — one key origin feeding >= 2 random draws with no
  ``split``/``fold_in`` in between (the static version of the
  ``distributions.sample()`` keyless-draw guard), including the
  loop-const variant: a key closed over by ``scan``/``while`` and drawn
  inside the body repeats the SAME stream every iteration.
- **replicated-large** — given a :class:`~paddle_tpu.parallel.plan.
  ShardingPlan`, large state leaves whose spec degenerates to fully
  replicated; plus in-graph ``sharding_constraint`` equations that pin a
  large intermediate to a fully-replicated sharding on a >1-device mesh.

Pure tracing — nothing here compiles or executes device code.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from paddle_tpu.analysis.findings import Finding, RULES

HOST_CALLBACK_PRIMS = {"pure_callback", "io_callback"}
DEBUG_CALLBACK_PRIMS = {"debug_callback"}
# primitives that DRAW from a key (consume its stream)
KEY_DRAW_PRIMS = {"random_bits", "threefry2x32"}
# primitives that DERIVE fresh independent keys (consuming is fine)
KEY_DERIVE_PRIMS = {"random_split", "random_fold_in", "random_seed",
                    "random_clone"}
# primitives whose output IS the same key as their input (aliasing)
KEY_ALIAS_PRIMS = {"random_wrap", "random_unwrap"}

_SLOW_DTYPES = ("float64", "complex128")


def _aval(v):
    return getattr(v, "aval", None)


def _nbytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(math.prod(shape)) * dtype.itemsize
    except (TypeError, AttributeError):
        return 0


def _is_key_like(aval) -> bool:
    """True for new-style key arrays AND raw uint32[..., 2] key buffers."""
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return False
    try:
        if jax.dtypes.issubdtype(dtype, jax.dtypes.prng_key):
            return True
    except (AttributeError, TypeError):
        pass
    shape = getattr(aval, "shape", ())
    return str(dtype) == "uint32" and tuple(shape)[-1:] == (2,)


def _src(eqn) -> str:
    """User-frame source location of an equation, best effort."""
    try:
        from jax._src import source_info_util
        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return ""


def _where(prefix: str, i: int, eqn) -> str:
    loc = f"{prefix}eqn[{i}] {eqn.primitive.name}"
    src = _src(eqn)
    return f"{loc} ({src})" if src else loc


def _sub_open(params: dict, *keys):
    """The inner (open) jaxpr under any of ``keys`` — accepts both
    ClosedJaxpr params (pjit's ``jaxpr``) and bare open Jaxprs
    (``remat2``/checkpoint store the body UNclosed, which the previous
    ClosedJaxpr-only probe silently skipped: every rule was blind
    inside ``jax.checkpoint`` scopes)."""
    for k in keys:
        v = params.get(k)
        if v is None:
            continue
        if hasattr(v, "jaxpr"):        # ClosedJaxpr
            return v.jaxpr
        if hasattr(v, "eqns"):         # open core.Jaxpr
            return v
    return None


class _KeyFlow:
    """Cross-scope PRNG dataflow state (origins are outer-most var ids)."""

    def __init__(self):
        self.counts: Dict[Any, int] = {}
        self.sites: Dict[Any, List[str]] = {}
        self.loop_reuse: List[Tuple[Any, str]] = []

    def draw(self, origin, where: str, in_loop_consts: bool):
        self.counts[origin] = self.counts.get(origin, 0) + 1
        self.sites.setdefault(origin, []).append(where)
        if in_loop_consts:
            self.loop_reuse.append((origin, where))


def analyze_jaxpr(
    closed_jaxpr,
    *,
    name: str = "fn",
    arg_labels: Optional[Sequence[Tuple[Any, str]]] = None,
    donated: Optional[Sequence[bool]] = None,
    donation_min_bytes: int = 1 << 16,
    plan=None,
    state_tree: Any = None,
    replicated_min_bytes: int = 1 << 20,
) -> List[Finding]:
    """Run every jaxpr rule over ``closed_jaxpr``; returns findings.

    ``arg_labels`` is ``[(invar, label), ...]`` for readable messages;
    ``donated`` is per-flat-input donation flags (None = unknown, skips
    the donation rule); ``plan``+``state_tree`` (abstract leaves) enable
    the replicated-large plan check.
    """
    findings: List[Finding] = []
    jaxpr = closed_jaxpr.jaxpr
    label_of = dict(arg_labels or ())
    flow = _KeyFlow()
    f64_sites: List[str] = []
    f64_seen = 0
    repl_sites: List[str] = []

    def walk(jx, env: Dict[Any, Any], prefix: str, loop_consts: set):
        nonlocal f64_seen

        def origin(v):
            if isinstance(v, jax.core.Literal) or not hasattr(v, "aval"):
                return None
            if v in env:
                return env[v]
            if not _is_key_like(v.aval):
                return None
            # fresh origin: scope-qualified so a sub-jaxpr shared by two
            # call sites (jax caches traced subfunctions) does not merge
            # its internal keys' draw counts across the calls
            return (prefix, v) if prefix else v

        for i, eqn in enumerate(jx.eqns):
            prim = eqn.primitive.name
            # ---- host syncs ----
            if prim in HOST_CALLBACK_PRIMS:
                cb = eqn.params.get("callback", "")
                findings.append(Finding(
                    "host-callback", RULES["host-callback"][0],
                    f"`{prim}` reachable from the hot path"
                    + (f" (callback={cb})" if cb else ""),
                    location=_where(prefix, i, eqn),
                    fix="move host work out of the step; if data must "
                        "leave the device, fetch it AFTER dispatch from "
                        "the returned metrics instead"))
            elif prim in DEBUG_CALLBACK_PRIMS:
                findings.append(Finding(
                    "debug-callback", RULES["debug-callback"][0],
                    "`debug_callback` (jax.debug.print/callback) in the "
                    "traced step",
                    location=_where(prefix, i, eqn),
                    fix="strip jax.debug.* calls from production steps or "
                        "gate them behind a flag"))
            # ---- f64 ----
            for v in tuple(eqn.outvars) + tuple(eqn.invars):
                av = _aval(v)
                if av is not None and str(getattr(av, "dtype", "")) \
                        in _SLOW_DTYPES:
                    f64_seen += 1
                    if len(f64_sites) < 3:
                        site = _where(prefix, i, eqn)
                        if site not in f64_sites:
                            f64_sites.append(site)
                    break
            # ---- replicated sharding_constraint ----
            if prim == "sharding_constraint":
                sh = eqn.params.get("sharding")
                try:
                    big = _nbytes(_aval(eqn.invars[0])) >= \
                        replicated_min_bytes
                    multi = len(getattr(sh, "device_set", ())) > 1
                    if sh is not None and big and multi \
                            and sh.is_fully_replicated:
                        repl_sites.append(_where(prefix, i, eqn))
                except Exception:
                    pass
            # ---- PRNG dataflow ----
            if prim in KEY_ALIAS_PRIMS:
                o = origin(eqn.invars[0])
                if o is not None:
                    for ov in eqn.outvars:
                        env[ov] = o
            elif prim in KEY_DERIVE_PRIMS:
                pass                      # outputs are fresh origins
            elif prim in KEY_DRAW_PRIMS:
                for v in eqn.invars:
                    o = origin(v)
                    if o is not None:
                        flow.draw(o, _where(prefix, i, eqn),
                                  o in loop_consts)
            # ---- recursion ----
            _recurse(eqn, env, origin, prefix, i, loop_consts, walk)

    def _recurse(eqn, env, origin, prefix, i, loop_consts, walk):
        prim = eqn.primitive.name
        params = eqn.params
        tag = f"{prefix}eqn[{i}]:{prim}/"
        if prim == "pjit" or prim in ("closed_call", "core_call", "call",
                                      "remat", "remat2", "checkpoint",
                                      "custom_jvp_call", "custom_vjp_call",
                                      "custom_vjp_call_jaxpr"):
            inner = _sub_open(params, "jaxpr", "call_jaxpr", "fun_jaxpr")
            if inner is None:
                return
            sub_env = dict(zip(inner.invars,
                               (origin(v) for v in eqn.invars)))
            sub_env = {k: v for k, v in sub_env.items() if v is not None}
            walk(inner, sub_env, tag, loop_consts)
        elif prim == "cond":
            branches = params.get("branches", ())
            # each branch sees the same outer keys; one branch executes,
            # so counts merge by MAX, not sum
            base = dict(flow.counts)
            merged = dict(base)
            for b, sub in enumerate(branches):
                inner = sub.jaxpr
                sub_env = dict(zip(inner.invars,
                                   (origin(v) for v in eqn.invars[1:])))
                sub_env = {k: v for k, v in sub_env.items()
                           if v is not None}
                flow.counts = dict(base)
                walk(inner, sub_env, f"{tag}branch{b}/", loop_consts)
                for k, v in flow.counts.items():
                    if v > merged.get(k, 0):
                        merged[k] = v
            flow.counts = merged
        elif prim == "scan":
            sub = params.get("jaxpr")
            if sub is None:
                return
            inner = sub.jaxpr
            n_const = int(params.get("num_consts", 0))
            sub_env = {}
            sub_consts = set(loop_consts)
            for bind, outer in zip(inner.invars[:n_const],
                                   eqn.invars[:n_const]):
                o = origin(outer)
                if o is not None:
                    sub_env[bind] = o
                    sub_consts.add(o)
            walk(inner, sub_env, tag, sub_consts)
        elif prim == "while":
            for which, n_key in (("cond_jaxpr", "cond_nconsts"),
                                 ("body_jaxpr", "body_nconsts")):
                sub = params.get(which)
                if sub is None:
                    continue
                inner = sub.jaxpr
                n_const = int(params.get(n_key, 0))
                # while invars: [cond_consts, body_consts, carry]
                off = 0 if which == "cond_jaxpr" else \
                    int(params.get("cond_nconsts", 0))
                sub_env = {}
                sub_consts = set(loop_consts)
                for bind, outer in zip(inner.invars[:n_const],
                                       eqn.invars[off:off + n_const]):
                    o = origin(outer)
                    if o is not None:
                        sub_env[bind] = o
                        sub_consts.add(o)
                walk(inner, sub_env, f"{tag}{which}/", sub_consts)
        else:
            # unknown higher-order primitive: still scan nested programs
            # (fresh origins) so callbacks/f64 inside are not missed
            for v in params.values():
                for sub in (v if isinstance(v, (tuple, list)) else (v,)):
                    if hasattr(sub, "jaxpr"):
                        walk(sub.jaxpr, {}, tag, set())

    walk(jaxpr, {}, "", set())

    # ---- key-reuse findings ----
    def _origin_label(o) -> str:
        if o in label_of:
            return f"key argument {label_of[o]}"
        return "an intermediate key"

    loop_reused = {o for o, _ in flow.loop_reuse}
    for o, where in flow.loop_reuse:
        findings.append(Finding(
            "prng-key-reuse", RULES["prng-key-reuse"][0],
            f"{_origin_label(o)} is closed over by a scan/while loop and "
            "drawn inside the body: every iteration replays the SAME "
            "random stream",
            location=where,
            fix="pass per-iteration keys through xs "
                "(jax.random.split(key, n)) or fold_in the loop index"))
    for o, n in flow.counts.items():
        if n >= 2 and o not in loop_reused:
            sites = "; ".join(flow.sites.get(o, [])[:4])
            findings.append(Finding(
                "prng-key-reuse", RULES["prng-key-reuse"][0],
                f"{_origin_label(o)} feeds {n} random draws with no "
                "split/fold_in between them — the draws are correlated "
                "(identical streams)",
                location=sites,
                fix="jax.random.split the key once per independent draw "
                    "(or fold_in a distinct integer per consumer)"))

    # ---- f64 finding ----
    if f64_seen:
        findings.append(Finding(
            "f64-promotion", RULES["f64-promotion"][0],
            f"{f64_seen} equation(s) carry float64/complex128 values "
            "(TPU executes f64 in software, ~10x slower)",
            location="; ".join(f64_sites),
            fix="drop jax_enable_x64 or cast explicitly to float32 / "
                "use weak-typed Python scalars"))

    # ---- donation finding ----
    if donated is not None:
        findings.extend(_donation_findings(
            jaxpr, donated, label_of, donation_min_bytes))

    # ---- replicated-large: plan check + constraint sites ----
    if plan is not None and state_tree is not None:
        findings.extend(_plan_findings(plan, state_tree,
                                       replicated_min_bytes))
    for site in repl_sites:
        findings.append(Finding(
            "replicated-large", RULES["replicated-large"][0],
            "a large intermediate is pinned to a fully-replicated "
            "sharding on a multi-device mesh",
            location=site,
            fix="give the with_sharding_constraint a partitioned spec "
                "(e.g. batch dim over ('dp','fsdp'))"))
    return findings


def _donation_findings(jaxpr, donated, label_of, min_bytes):
    """Inputs that COULD be donated (same shape+dtype as an output) but
    are not. Matching is a multiset walk: donated inputs consume their
    matching outputs first, so a partially-donated step only reports the
    leftovers."""
    out_pool: Dict[Tuple, int] = {}
    for ov in jaxpr.outvars:
        av = _aval(ov)
        if av is None:
            continue
        k = (tuple(getattr(av, "shape", ())), str(getattr(av, "dtype", "")))
        out_pool[k] = out_pool.get(k, 0) + 1

    def take(aval) -> bool:
        k = (tuple(getattr(aval, "shape", ())),
             str(getattr(aval, "dtype", "")))
        if out_pool.get(k, 0) > 0:
            out_pool[k] -= 1
            return True
        return False

    invars = jaxpr.invars
    flags = list(donated) + [False] * (len(invars) - len(donated))
    for v, d in zip(invars, flags):          # donated inputs consume first
        if d and v.aval is not None:
            take(v.aval)
    missed_bytes = 0
    examples = []
    for v, d in zip(invars, flags):
        av = _aval(v)
        if d or av is None or _nbytes(av) < min_bytes:
            continue
        if take(av):
            missed_bytes += _nbytes(av)
            if len(examples) < 3:
                examples.append(label_of.get(v, str(av)))
    if missed_bytes:
        return [Finding(
            "undonated-buffer", RULES["undonated-buffer"][0],
            f"{missed_bytes} bytes of inputs have same-shape outputs but "
            f"are not donated (e.g. {', '.join(examples)}): peak HBM "
            "holds the old AND new copy of each",
            fix="jit with donate_argnums covering the state argument "
                "(shard_train_step does this by default)")]
    return []


def _plan_findings(plan, state_tree, min_bytes):
    """Large state leaves whose plan spec degenerates to replicated."""
    try:
        specs = plan.state_specs(state_tree)
    except Exception:
        try:
            specs = plan.params_specs(state_tree)
        except Exception:
            return []
    from jax.sharding import PartitionSpec
    leaves_s = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, PartitionSpec))[0]
    leaves_v = dict(jax.tree_util.tree_flatten_with_path(state_tree)[0])
    findings = []
    for path, spec in leaves_s:
        val = leaves_v.get(path)
        if val is None or _nbytes(val) < min_bytes:
            continue
        entries = tuple(spec) if spec is not None else ()
        if all(e is None for e in entries):
            findings.append(Finding(
                "replicated-large", RULES["replicated-large"][0],
                f"state leaf {jax.tree_util.keystr(path)} "
                f"({_nbytes(val)} bytes) is fully replicated under the "
                "given sharding plan: HBM cost multiplies by mesh size",
                location=jax.tree_util.keystr(path),
                fix="add a plan rule or ParamSpec sharding hint for it "
                    "(or use fsdp_plan() to shard big params)"))
    return findings
