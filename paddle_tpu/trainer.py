"""High-level training driver: epochs, checkpointing, resume, logging.

Reference mapping: the Trainer/DeviceWorker runtime —
``Executor::RunFromDataset`` (executor.cc:168), ``MultiTrainer`` thread-per
-worker loops (multi_trainer.cc:69), ``PullDenseWorker``, fetch-var printing
(``device_worker.h`` PrintFetchVars) and the checkpoint conventions of
``io.py save_persistables``. TPU-native: ONE jitted step consumed in a host
loop; the worker threads collapse into the data loader's prefetch thread +
XLA's async dispatch. Failure recovery = auto-resume from the newest
checkpoint (SURVEY.md §5.3: the reference's story is also
restart-from-checkpoint; here it is built in).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, Optional

import jax

from paddle_tpu import io as io_lib
from paddle_tpu import observability


class Trainer:
    """Epoch/step driver over a jitted train step.

    train_step(state, **batch) -> (state, metrics) — built by
    paddle_tpu.train.build_train_step (or amp.scaled_train_step) and
    optionally sharded by parallel.api.shard_train_step.

    Telemetry (observability subsystem): every ``fit`` drives a
    :class:`~paddle_tpu.observability.StepTelemetry` — step wall time,
    examples/s (and tokens/s for token batches), data-wait vs compute
    split, a recompile detector over jax.monitoring, periodic device
    memory gauges, and (multi-process) a cross-host min/mean/max line.
    ``run_log=`` additionally writes one crash-safe JSONL record per
    step; ``telemetry=False`` turns the whole thing off.

    Resilience (resilience subsystem): checkpoints go through the sharded
    snapshot engine — ``restore()`` resumes from the newest *valid*
    manifest, silently falling back past torn/corrupt saves, and (multi-
    host) barriers so every host agrees on the resume step. Passing
    ``preemption_guard=`` makes ``fit`` drain the in-flight step when
    SIGTERM arrives, take a forced emergency snapshot, and exit with
    ``resilience.EXIT_PREEMPTED`` for the launcher.
    """

    def __init__(self, train_step: Callable, state: Any, *,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1000,
                 keep_checkpoints: int = 3,
                 log_every: int = 100,
                 log_fn: Callable[[str], None] = print,
                 hooks: Iterable[Callable] = (),
                 run_log: Optional[str] = None,
                 telemetry: bool = True,
                 tokens_per_example: Optional[int] = None,
                 preemption_guard=None):
        self.train_step = train_step
        self.state = state
        self.log_every = log_every
        self.log_fn = log_fn
        self.hooks = list(hooks)  # hook(trainer, step, metrics)
        self.checkpoint_every = checkpoint_every
        self.run_log = run_log
        self.telemetry = telemetry
        self.tokens_per_example = tokens_per_example
        self.preemption_guard = preemption_guard
        self.manager = None
        if checkpoint_dir is not None:
            self.manager = io_lib.CheckpointManager(
                checkpoint_dir, max_to_keep=keep_checkpoints,
                save_interval_steps=checkpoint_every)

    # -- resume ------------------------------------------------------------
    def restore(self) -> int:
        """Resume from the newest VALID checkpoint if one exists (torn or
        corrupt saves are skipped by the snapshot engine). Multi-host runs
        barrier so every host resumes at the SAME step — a host whose
        local view is ahead (e.g. it committed before the crash, others
        did not) drops back to the common step. Returns the restored step
        (0 if none)."""
        if self.manager is None:
            return 0
        step = self.manager.latest_step()
        agreed = _agree_on_resume_step(step)
        if agreed is None:
            return 0
        restored = self.manager.restore(
            agreed, target=jax.device_get(self.state))
        self.state = restored
        step = int(restored["step"])
        self.log_fn(f"[trainer] resumed from step {step}")
        return step

    @property
    def step_count(self) -> int:
        return int(self.state["step"])

    # -- loops -------------------------------------------------------------
    def fit(self, data_iter: Iterable[Dict[str, Any]], *,
            epochs: int = 1,
            steps_per_epoch: Optional[int] = None,
            make_iter: Optional[Callable] = None,
            lint: str = "off",
            lint_cost: Optional[Dict[str, Any]] = None
            ) -> Dict[str, float]:
        """Train over batches. ``data_iter`` is an iterable of feed dicts
        (re-created per epoch via ``make_iter`` when given — pass the
        dataset's ``.batches`` factory for multi-epoch runs).

        ``lint='warn'|'error'`` statically analyzes the train step against
        the first batch before any step runs (``paddle_tpu.analysis``:
        host syncs, f64 promotions, missed donation, PRNG key reuse,
        tracer branches); ``'warn'`` logs findings, ``'error'`` raises
        :class:`~paddle_tpu.analysis.LintError` on error-severity ones.

        ``lint_cost`` adds the HLO cost tier to the same gate: a dict of
        :func:`~paddle_tpu.analysis.lint_fn` cost options, e.g.
        ``{"hbm_budget_bytes": 2 << 30, "collective_allowlist":
        ["all_reduce"]}`` — the train step is then lowered to StableHLO
        and checked for unexpected collectives, resharding churn, and
        the peak-HBM/flops budgets (pass ``{}`` for the cost report
        alone)."""
        if epochs > 1 and make_iter is None and not hasattr(
                data_iter, "__len__"):
            raise ValueError(
                "epochs > 1 with a one-shot iterator: pass make_iter= so "
                "each epoch gets a fresh pass over the data")
        last_metrics: Dict[str, float] = {}
        tel = None
        if self.telemetry:
            tel = observability.StepTelemetry(
                "train", run_log=self.run_log,
                run_meta={"epochs": epochs},
                log_fn=self.log_fn,
                memory_every=self.log_every or 50,
                aggregate_every=self.log_every)
        # host-mirrored global step: one device sync here, none in the loop
        gstep = self.step_count
        try:
            # trace root for the run: per-step spans (recorded inside the
            # epoch loop when tracing is enabled) nest under it via the
            # thread-local span stack; a no-op when tracing is disabled
            with observability.tracing.default().span(
                    "trainer.fit", epochs=epochs, start_step=gstep):
                last_metrics = self._fit_epochs(
                    epochs, data_iter, make_iter, steps_per_epoch, tel,
                    gstep, lint=lint, lint_cost=lint_cost)
        finally:
            if tel is not None:
                tel.close(summary={"metrics": last_metrics})
        if self.manager is not None:
            last = self.step_count
            # cached high-water mark, not latest_step(): the latter hash-
            # verifies every kept snapshot, a full read per fit() end
            if self.manager.last_saved_step != last:
                self.manager.save(last, jax.device_get(self.state),
                                  wait=True, force=True)
            else:
                self.manager.wait()
        return last_metrics

    def _fit_epochs(self, epochs, data_iter, make_iter, steps_per_epoch,
                    tel, gstep, lint="off", lint_cost=None):
        last_metrics: Dict[str, float] = {}
        metrics: Dict[str, Any] = {}
        for epoch in range(epochs):
            it = iter(make_iter() if make_iter is not None else data_iter)
            t0 = time.perf_counter()
            n = 0
            while True:
                t_fetch = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                data_wait_s = time.perf_counter() - t_fetch
                if lint != "off" and epoch == 0 and n == 0:
                    # ahead-of-time gate: abstract tracing only (nothing
                    # compiles or executes), against the real first batch.
                    # data_wait was captured above so trace time is not
                    # booked as an input stall.
                    self._lint(batch, lint, lint_cost)
                if tel is not None:
                    tel.data_wait(data_wait_s)
                t_step = time.perf_counter()
                self.state, metrics = self.train_step(self.state, **batch)
                step_time_s = time.perf_counter() - t_step
                n += 1
                gstep += 1
                tracer = observability.tracing.default()
                if tracer.enabled:
                    tracer.record_span("trainer.step",
                                       duration_s=step_time_s,
                                       step=gstep, epoch=epoch,
                                       data_wait_s=round(data_wait_s, 6))
                if tel is not None:
                    ex, tok = _batch_counts(batch, self.tokens_per_example)
                    tel.step(gstep, feeds=batch,
                             step_time_s=step_time_s,
                             examples=ex, tokens=tok, epoch=epoch)
                if self.log_every and n % self.log_every == 0:
                    last_metrics = {k: float(v) for k, v in metrics.items()}
                    rate = n / (time.perf_counter() - t0)
                    self.log_fn(
                        f"[trainer] epoch {epoch} step {gstep} "
                        f"{_fmt(last_metrics)} ({rate:.1f} it/s)")
                # gate on the GLOBAL step so epochs shorter than
                # checkpoint_every still checkpoint across epochs
                if self.manager is not None \
                        and gstep % self.checkpoint_every == 0:
                    # label with the TRUE state step — gstep can drift ahead
                    # when a step declines to increment (AMP overflow skips);
                    # the sync is per-checkpoint, not per-step
                    host_state = jax.device_get(self.state)
                    gstep = int(host_state["step"])
                    self.manager.save(gstep, host_state)
                for hook in self.hooks:
                    hook(self, n, metrics)
                if self.preemption_guard is not None \
                        and self.preemption_guard.triggered:
                    # the in-flight step has drained (device_get below
                    # syncs XLA's async dispatch); snapshot and leave with
                    # the launcher-visible preemption code
                    self._emergency_snapshot()
                    self.preemption_guard.exit()
                if steps_per_epoch and n >= steps_per_epoch:
                    break
            if n == 0:
                raise ValueError(
                    f"epoch {epoch} yielded no batches (exhausted "
                    "iterator? pass make_iter= for multi-epoch runs)")
            last_metrics = {k: float(v) for k, v in metrics.items()}
            self.log_fn(f"[trainer] epoch {epoch} done: {_fmt(last_metrics)}")
        return last_metrics

    def evaluate(self, eval_step: Callable,
                 data_iter: Iterable[Dict[str, Any]],
                 metrics: Optional[Dict[str, Any]] = None):
        """Run eval_step(params, **batch) over batches; streams into
        paddle_tpu.metrics objects when given ({name: (metric, extractor)})."""
        outs = []
        reg = observability.default() if self.telemetry else None
        for batch in data_iter:
            t0 = time.perf_counter()
            out = eval_step(self.state["params"], **batch)
            if reg is not None:
                reg.histogram("eval_step_seconds",
                              "per-batch eval wall time").observe(
                                  time.perf_counter() - t0)
                reg.counter("eval_steps_total").inc()
                ex, _ = _batch_counts(batch, None)
                reg.counter("eval_examples_total").inc(ex)
            if metrics:
                for name, (metric, extract) in metrics.items():
                    metric.update(*extract(out, batch))
            else:
                outs.append(out)
        if metrics:
            return {name: m.eval() for name, (m, _) in metrics.items()}
        return outs

    def predict(self, predict_step: Callable,
                data_iter: Iterable[Dict[str, Any]]):
        """Forward-only pass collecting host numpy outputs per batch
        (hapi Model.predict / infer_from_dataset convenience)."""
        outs = []
        for batch in data_iter:
            out = predict_step(self.state["params"], **batch)
            outs.append(jax.device_get(out))   # pytree -> host numpy
        return outs


    def _lint(self, batch: Dict[str, Any], mode: str, lint_cost=None):
        """Static analysis of the train step against one batch's avals
        (``paddle_tpu.analysis``); 'warn' logs, 'error' raises.
        ``lint_cost`` (a dict of cost options) adds the HLO tier."""
        from paddle_tpu import analysis
        cost_kw = dict(lint_cost, cost=True) if lint_cost is not None \
            else {}
        report = analysis.lint_train_step(self.train_step, self.state,
                                          batch, **cost_kw)
        analysis.enforce(report, mode, log_fn=self.log_fn)

    def _emergency_snapshot(self):
        """Forced synchronous snapshot of the current state (preemption
        drain path); a no-op without a checkpoint manager."""
        if self.manager is None:
            return
        host_state = jax.device_get(self.state)
        step = int(host_state["step"])
        self.manager.save(step, host_state, wait=True, force=True)
        self.log_fn(f"[trainer] emergency snapshot at step {step}")


def _agree_on_resume_step(step):
    """Multi-host agreement on the resume step (None = no checkpoint)."""
    from paddle_tpu import fleet as fleet_lib
    return fleet_lib.agree_on_resume_step(step)


def _fmt(metrics: Dict[str, float]) -> str:
    return " ".join(f"{k}={v:.4f}" for k, v in sorted(metrics.items()))


def _batch_counts(batch: Dict[str, Any], tokens_per_example: Optional[int]):
    """(examples, tokens) for one feed dict. Examples = leading dim of
    the first array leaf. Tokens = examples * T for (B, T) integer leaves
    (token-id batches — BERT/GPT/Transformer feeds); None when the batch
    doesn't look tokenized and no explicit tokens_per_example is set."""
    leaves = [x for x in jax.tree_util.tree_leaves(batch)
              if hasattr(x, "shape") and getattr(x, "ndim", 0) >= 1]
    if not leaves:
        return 0, None
    examples = int(leaves[0].shape[0])
    if tokens_per_example is not None:
        return examples, examples * int(tokens_per_example)
    tokens = None
    for x in leaves:
        if x.ndim == 2 and jax.numpy.issubdtype(x.dtype, jax.numpy.integer):
            tokens = max(tokens or 0, int(x.shape[0]) * int(x.shape[1]))
    return examples, tokens
