"""Streaming metrics (fluid ``metrics.py`` parity: Accuracy, Auc,
Precision/Recall, ChunkEvaluator surface; plus ops/tensor.accuracy for the
in-graph op). Host-side accumulators over device-computed statistics — the
update computations are jax-traceable so they fuse into eval steps."""

from __future__ import annotations

import numpy as np


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(Metric):
    """Streaming top-1 accuracy (fluid metrics.Accuracy)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._correct = 0.0
        self._total = 0.0

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(preds.shape[0], -1)[:, 0]
        if preds.ndim > 1:
            preds = preds.argmax(-1)
        self._correct += float((preds == labels).sum())
        self._total += preds.shape[0]
        return self

    def eval(self) -> float:
        return self._correct / max(self._total, 1.0)


class Auc(Metric):
    """Streaming ROC-AUC via fixed binning (fluid metrics.Auc / the auc op:
    reference accumulates a 2 x bins histogram of predicted probabilities)."""

    def __init__(self, num_thresholds: int = 4095):
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1)
        self._neg = np.zeros(self.num_thresholds + 1)

    def update(self, probs, labels):
        probs = np.asarray(probs).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip((probs * self.num_thresholds).astype(np.int64),
                      0, self.num_thresholds)
        np.add.at(self._pos, idx[labels > 0.5], 1)
        np.add.at(self._neg, idx[labels <= 0.5], 1)
        return self

    def eval(self) -> float:
        # sweep thresholds high->low accumulating TP/FP (trapezoid rule)
        tp = np.cumsum(self._pos[::-1])
        fp = np.cumsum(self._neg[::-1])
        tot_p, tot_n = tp[-1], fp[-1]
        if tot_p == 0 or tot_n == 0:
            return 0.5
        tpr = tp / tot_p
        fpr = fp / tot_n
        return float(np.trapezoid(tpr, fpr))


class MeanMetric(Metric):
    """Running mean of a scalar stream (loss trackers, fleet_util means)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._sum = 0.0
        self._n = 0

    def update(self, value, weight: float = 1.0):
        self._sum += float(np.asarray(value)) * weight
        self._n += weight
        return self

    def eval(self) -> float:
        return self._sum / max(self._n, 1e-12)


class ChunkEvaluator(Metric):
    """Chunking F1 for sequence labeling (fluid metrics.ChunkEvaluator +
    ``chunk_eval`` op). Tags follow IOB: tag = chunk_type * 2 + {0:B, 1:I},
    with ``num_chunk_types * 2`` == outside tag ("O")."""

    def __init__(self, num_chunk_types: int):
        self.num_chunk_types = num_chunk_types
        self.reset()

    def reset(self):
        self.num_infer = 0.0
        self.num_label = 0.0
        self.num_correct = 0.0

    @staticmethod
    def extract_chunks(tags, num_chunk_types):
        """[(start, end, type), ...] from an IOB tag sequence."""
        chunks = []
        start = ctype = None
        tags = list(np.asarray(tags))
        for i, t in enumerate(tags + [num_chunk_types * 2]):
            is_begin = t < num_chunk_types * 2 and t % 2 == 0
            is_inside = t < num_chunk_types * 2 and t % 2 == 1
            cur_type = t // 2 if t < num_chunk_types * 2 else None
            if start is not None and (not is_inside or cur_type != ctype):
                chunks.append((start, i, ctype))
                start = ctype = None
            if is_begin:
                start, ctype = i, cur_type
        return chunks

    def update(self, infer_tags, label_tags, lengths=None):
        infer_tags = np.asarray(infer_tags)
        label_tags = np.asarray(label_tags)
        if infer_tags.ndim == 1:
            infer_tags = infer_tags[None]
            label_tags = label_tags[None]
        for i in range(infer_tags.shape[0]):
            n = int(lengths[i]) if lengths is not None \
                else infer_tags.shape[1]
            inf = set(self.extract_chunks(infer_tags[i, :n],
                                          self.num_chunk_types))
            lab = set(self.extract_chunks(label_tags[i, :n],
                                          self.num_chunk_types))
            self.num_infer += len(inf)
            self.num_label += len(lab)
            self.num_correct += len(inf & lab)
        return self

    def eval(self):
        p = self.num_correct / max(self.num_infer, 1e-12)
        r = self.num_correct / max(self.num_label, 1e-12)
        f1 = 2 * p * r / max(p + r, 1e-12)
        return {"precision": p, "recall": r, "f1": f1}


class PrecisionRecall(Metric):
    """Binary precision/recall/F1 at a threshold (metrics.Precision/Recall)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.reset()

    def reset(self):
        self.tp = self.fp = self.fn = 0.0

    def update(self, probs, labels):
        probs = np.asarray(probs).reshape(-1)
        labels = np.asarray(labels).reshape(-1) > 0.5
        pred = probs >= self.threshold
        self.tp += float((pred & labels).sum())
        self.fp += float((pred & ~labels).sum())
        self.fn += float((~pred & labels).sum())
        return self

    def eval(self):
        p = self.tp / max(self.tp + self.fp, 1e-12)
        r = self.tp / max(self.tp + self.fn, 1e-12)
        f1 = 2 * p * r / max(p + r, 1e-12)
        return {"precision": p, "recall": r, "f1": f1}
