"""Profiling/tracing: host+device timeline with the reference's contract.

Reference mapping (SURVEY.md §5.1): RAII ``RecordEvent`` wrapping every op
(operator.cc:180) + CUPTI ``DeviceTracer`` correlating device activity +
``tools/timeline.py`` Chrome-trace emission, driven by
``fluid.profiler.profiler`` context managers (python/paddle/fluid/
profiler.py). TPU-native: ``jax.profiler`` (XPlane → TensorBoard/Perfetto)
carries the device side; ``record_event``/named_scope annotate traced
regions so XLA ops correlate back to model code; a lightweight host-side
event table reproduces the sorted per-op summary report.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional

import jax


class _Events(threading.local):
    def __init__(self):
        self.active: Optional[List] = None


_EVENTS = _Events()


@contextlib.contextmanager
def record_event(name: str):
    """Annotate a region: shows up in device traces (named_scope → XLA op
    metadata) and, under :func:`profiler`, in the host event table."""
    t0 = time.perf_counter()
    with jax.named_scope(name):
        yield
    if _EVENTS.active is not None:
        _EVENTS.active.append((name, time.perf_counter() - t0))


@contextlib.contextmanager
def profiler(output_dir: Optional[str] = None, *, summary: bool = True):
    """Profile a region. With ``output_dir``, captures a jax.profiler trace
    viewable in TensorBoard/XProf (device timeline ≙ CUPTI tracer + Chrome
    trace). Always collects host record_event stats; prints the sorted
    summary table on exit (EnableProfiler/DisableProfiler parity)."""
    prev = _EVENTS.active
    _EVENTS.active = []
    if output_dir:
        jax.profiler.start_trace(output_dir)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        wall = time.perf_counter() - t0
        if output_dir:
            jax.profiler.stop_trace()
        events = _EVENTS.active
        _EVENTS.active = prev
        if summary and events:
            print(format_summary(events, wall))


def format_summary(events, wall: float) -> str:
    """Sorted per-event table (profiler.cc sorted summaries)."""
    agg: Dict[str, List[float]] = {}
    for name, dt in events:
        agg.setdefault(name, []).append(dt)
    rows = sorted(agg.items(), key=lambda kv: -sum(kv[1]))
    lines = [f"{'Event':<32}{'Calls':>8}{'Total(s)':>12}{'Avg(ms)':>12}"
             f"{'Ratio':>8}"]
    for name, ts in rows:
        tot = sum(ts)
        lines.append(f"{name:<32}{len(ts):>8}{tot:>12.4f}"
                     f"{1e3 * tot / len(ts):>12.3f}"
                     f"{tot / max(wall, 1e-9):>8.2%}")
    return "\n".join(lines)


def start_server(port: int = 9012):
    """Live profiling endpoint (jax.profiler server) for on-demand capture."""
    return jax.profiler.start_server(port)


@contextlib.contextmanager
def step_marker(step: int):
    """Mark a training step (XProf StepEvents)."""
    with jax.profiler.StepTraceAnnotation("train", step_num=step):
        yield
