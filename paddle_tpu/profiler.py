"""Profiling/tracing: host+device timeline with the reference's contract.

Reference mapping (SURVEY.md §5.1): RAII ``RecordEvent`` wrapping every op
(operator.cc:180) + CUPTI ``DeviceTracer`` correlating device activity +
``tools/timeline.py`` Chrome-trace emission, driven by
``fluid.profiler.profiler`` context managers (python/paddle/fluid/
profiler.py). TPU-native: ``jax.profiler`` (XPlane → TensorBoard/Perfetto)
carries the device side; ``record_event``/named_scope annotate traced
regions so XLA ops correlate back to model code; a lightweight host-side
event table reproduces the sorted per-op summary report.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional

import jax

from paddle_tpu import observability as _obs


class _Events(threading.local):
    def __init__(self):
        self.active: Optional[List] = None


_EVENTS = _Events()


@contextlib.contextmanager
def record_event(name: str):
    """Annotate a region: shows up in device traces (named_scope → XLA op
    metadata), in the host event table under :func:`profiler`, in the
    observability registry's span histogram (so ``observability.report()``
    covers record_event spans without a profiler context), and — when the
    default tracer is enabled — in the request-trace timeline, parented
    to the calling thread's current span. (Inside jit the span fires once
    per TRACE, not per execution — host spans measure host work.)"""
    t0 = time.perf_counter()
    with jax.named_scope(name):
        yield
    dt = time.perf_counter() - t0
    _obs.observe_span(name, dt)
    tr = _obs.tracing.default()
    if tr.enabled:
        # duration-only record: perf_counter and the tracer's monotonic
        # clock may differ in epoch, so let the tracer place the span at
        # its own "now" minus the measured duration
        tr.record_span(name, duration_s=dt, cat="record_event")
    if _EVENTS.active is not None:
        _EVENTS.active.append((name, dt, t0))


@contextlib.contextmanager
def _collect_events(out: list):
    """Install a fresh host-event buffer; restore the previous one and
    append (events, wall) to ``out`` on exit. Shared by every profiling
    context manager so the collection protocol lives in one place."""
    prev = _EVENTS.active
    _EVENTS.active = []
    t0 = time.perf_counter()
    try:
        yield
    finally:
        events = _EVENTS.active
        _EVENTS.active = prev
        out.append((events, time.perf_counter() - t0))


@contextlib.contextmanager
def profiler(output_dir: Optional[str] = None, *, summary: bool = True):
    """Profile a region. With ``output_dir``, captures a jax.profiler trace
    viewable in TensorBoard/XProf (device timeline ≙ CUPTI tracer + Chrome
    trace). Always collects host record_event stats; prints the sorted
    summary table on exit (EnableProfiler/DisableProfiler parity)."""
    if output_dir:
        jax.profiler.start_trace(output_dir)
    res = []
    try:
        with _collect_events(res):
            yield
    finally:
        if output_dir:
            jax.profiler.stop_trace()
        events, wall = res[0]
        if summary and events:
            print(format_summary(events, wall))


def format_summary(events, wall: float) -> str:
    """Sorted per-event table (profiler.cc sorted summaries)."""
    agg: Dict[str, List[float]] = {}
    for name, dt, *_ in events:
        agg.setdefault(name, []).append(dt)
    rows = sorted(agg.items(), key=lambda kv: -sum(kv[1]))
    lines = [f"{'Event':<32}{'Calls':>8}{'Total(s)':>12}{'Avg(ms)':>12}"
             f"{'Ratio':>8}"]
    for name, ts in rows:
        tot = sum(ts)
        lines.append(f"{name:<32}{len(ts):>8}{tot:>12.4f}"
                     f"{1e3 * tot / len(ts):>12.3f}"
                     f"{tot / max(wall, 1e-9):>8.2%}")
    return "\n".join(lines)


def chrome_trace(events, path: str, *, pid: int = 0):
    """Write host events as a Chrome trace (``chrome://tracing`` /
    Perfetto) — ``tools/timeline.py:131`` ``_ChromeTraceFormatter`` parity
    for the host-side table. Device-side timelines come from the
    jax.profiler capture (XPlane → Perfetto) which subsumes the CUPTI
    path; this covers the reference's host-annotation stream."""
    import json

    if not events:
        trace = {"traceEvents": []}
    else:
        base = min(t0 for _, _, t0 in events)
        trace = {"traceEvents": [
            {"name": name, "ph": "X", "pid": pid, "tid": 0,
             "ts": (t0 - base) * 1e6, "dur": dt * 1e6,
             "cat": "host"}
            for name, dt, t0 in events]}
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


@contextlib.contextmanager
def profile_to_chrome_trace(path: str, *, summary: bool = False):
    """Profile a region and dump the host event stream as a Chrome trace
    file (fluid.profiler.profiler(output='timeline') parity)."""
    res = []
    try:
        with _collect_events(res):
            yield
    finally:
        events, wall = res[0]
        chrome_trace(events, path)
        if summary and events:
            print(format_summary(events, wall))


def start_server(port: int = 9012):
    """Live profiling endpoint (jax.profiler server) for on-demand capture."""
    return jax.profiler.start_server(port)


@contextlib.contextmanager
def step_marker(step: int):
    """Mark a training step (XProf StepEvents)."""
    with jax.profiler.StepTraceAnnotation("train", step_num=step):
        yield
