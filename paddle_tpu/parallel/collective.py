"""Explicit collective ops over named mesh axes.

Parity surface: the reference's ``operators/collective/`` c_* ops
(``c_allreduce_{sum,max,min,prod}``, ``c_allgather``, ``c_reducescatter``,
``c_broadcast``, ``c_comm_init`` — kernel = direct ncclAllReduce at
``collective/c_allreduce_op.h:105``) and the legacy ``operators/nccl/`` ops.

TPU-native design: each collective is ``shard_map``-wrapped ``lax.p*`` over a
named mesh axis, so the communication rides ICI links chosen by XLA. There
is no comm-init/nccl-id bootstrap (``c_gen_nccl_id_op.cc``): the Mesh IS the
communicator. "ring id"/"nccl_comm_num" knobs have no analog — XLA owns
channel scheduling. Hierarchical allreduce (``details/nccl_op_handle.h:124``)
is expressed by passing a tuple of axes, e.g. ``axis=("dp", "dcn")``.

These are mostly for user-level algorithms (LocalSGD, custom PS-style
updates, tests); ordinary data parallelism never calls them — GSPMD inserts
collectives automatically (see parallel.api).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def shard_map(f, *, mesh, in_specs, out_specs):
    # check_vma=False: these wrappers take logically-replicated inputs whose
    # axis-invariance the varying-axes checker cannot prove.
    from paddle_tpu.core.compat import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs,
               out_specs=out_specs, check_vma=False)

from paddle_tpu.core import mesh as mesh_lib

AxisArg = Union[str, Sequence[str]]

_REDUCERS = {
    "sum": jax.lax.psum,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


def _axes(axis: AxisArg):
    return (axis,) if isinstance(axis, str) else tuple(axis)


def _mesh(mesh: Optional[Mesh]) -> Mesh:
    m = mesh or mesh_lib.current_mesh()
    if m is None:
        raise ValueError("no mesh: pass mesh= or enter mesh_context()")
    return m


def _other_axes_spec(mesh: Mesh, axis: AxisArg) -> P:
    """Inputs replicated over `axis`, outputs too; other axes untouched."""
    del mesh
    return P()


def all_reduce(x, axis: AxisArg = mesh_lib.DP, *, op: str = "sum",
               mesh: Optional[Mesh] = None):
    """c_allreduce_{sum,max,min,prod} parity (collective/c_allreduce_op.h).

    ``x`` is interpreted as each shard's local value (replicated layout over
    ``axis``); returns the reduction across the axis on every member.
    """
    m = _mesh(mesh)
    axes = _axes(axis)
    if op == "prod":
        def body(v):
            return jnp.exp(jax.lax.psum(jnp.log(v.astype(jnp.float32)),
                                        axes)).astype(v.dtype)
    else:
        red = _REDUCERS[op]

        def body(v):
            return red(v, axes)

    return shard_map(body, mesh=m, in_specs=P(*[None] * x.ndim),
                     out_specs=P(*[None] * x.ndim))(x)


def all_gather(x, axis: AxisArg = mesh_lib.DP, *, concat_axis: int = 0,
               tiled: bool = True, mesh: Optional[Mesh] = None):
    """c_allgather parity: concat per-member values along ``concat_axis``."""
    m = _mesh(mesh)
    axes = _axes(axis)

    def body(v):
        out = v
        for a in axes:
            out = jax.lax.all_gather(out, a, axis=concat_axis, tiled=True)
        return out

    return shard_map(body, mesh=m, in_specs=P(*[None] * x.ndim),
                     out_specs=P(*[None] * x.ndim))(x)


def reduce_scatter(x, axis: str = mesh_lib.DP, *, scatter_axis: int = 0,
                   mesh: Optional[Mesh] = None):
    """c_reducescatter parity: sum over axis, shard result along
    ``scatter_axis``. Input dim must divide by the axis size; the output
    keeps the scattered layout (spec names the axis)."""
    m = _mesh(mesh)

    def body(v):
        return jax.lax.psum_scatter(v, axis, scatter_dimension=scatter_axis,
                                    tiled=True)

    in_spec = P(*[None] * x.ndim)
    out_entries = [None] * x.ndim
    out_entries[scatter_axis] = axis
    return shard_map(body, mesh=m, in_specs=in_spec,
                     out_specs=P(*out_entries))(x)


def broadcast(x, axis: AxisArg = mesh_lib.DP, *, root: int = 0,
              mesh: Optional[Mesh] = None):
    """c_broadcast parity: every member gets the root member's value."""
    m = _mesh(mesh)
    axes = _axes(axis)

    def body(v):
        out = v
        for a in axes:
            idx = jax.lax.axis_index(a)
            src = jnp.where(idx == root, out, jnp.zeros_like(out))
            out = jax.lax.psum(src, a)
        return out

    return shard_map(body, mesh=m, in_specs=P(*[None] * x.ndim),
                     out_specs=P(*[None] * x.ndim))(x)


def all_to_all(x, axis: str = mesh_lib.EP, *, split_axis: int = 0,
               concat_axis: int = 0, mesh: Optional[Mesh] = None):
    """Dense all-to-all (the sharded-embedding / MoE shuffle primitive;
    no direct reference analog — its PS world moves rows by gRPC instead,
    ``parameter_send.cc``)."""
    m = _mesh(mesh)

    def body(v):
        return jax.lax.all_to_all(v, axis, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    return shard_map(body, mesh=m, in_specs=P(*[None] * x.ndim),
                     out_specs=P(*[None] * x.ndim))(x)


def ppermute(x, axis: str, perm, *, mesh: Optional[Mesh] = None):
    """Point-to-point ring shift (building block of ring attention /
    pipeline transfer; ≙ the reference's send_op/recv_op pairs but on ICI)."""
    m = _mesh(mesh)

    def body(v):
        return jax.lax.ppermute(v, axis, perm)

    return shard_map(body, mesh=m, in_specs=P(*[None] * x.ndim),
                     out_specs=P(*[None] * x.ndim))(x)


def hierarchical_all_reduce(x, *, ici_axis: str = mesh_lib.DP,
                            dcn_axis: str = "dcn", scatter_axis: int = 0,
                            mesh: Optional[Mesh] = None):
    """Two-level all-reduce (hierarchical allreduce parity,
    platform/nccl_helper.h + nccl_op_handle.h:124 — there: intra-node
    NCCL ring then inter-node ring over fewer, fatter links).

    TPU topology analog: ``ici_axis`` spans the fast in-slice links,
    ``dcn_axis`` the slower cross-slice network. Schedule:

        reduce_scatter over ICI  ->  all_reduce the 1/n shard over DCN
        ->  all_gather over ICI

    so the DCN leg moves 1/|ici| of the bytes — exactly the NCCL
    hierarchical trick. Numerically equal to one psum over both axes
    (asserted by tests); XLA may also derive this itself, the explicit
    form is for topologies/compilers where it does not.

    ``x``: per-member local value (replicated layout); dim
    ``scatter_axis`` must be divisible by the ICI axis size.
    """
    m = _mesh(mesh)

    def body(v):
        shard = jax.lax.psum_scatter(v, ici_axis,
                                     scatter_dimension=scatter_axis,
                                     tiled=True)
        shard = jax.lax.psum(shard, dcn_axis)
        return jax.lax.all_gather(shard, ici_axis, axis=scatter_axis,
                                  tiled=True)

    return shard_map(body, mesh=m, in_specs=P(*[None] * x.ndim),
                     out_specs=P(*[None] * x.ndim))(x)


def barrier(axis: AxisArg = mesh_lib.DP, *, mesh: Optional[Mesh] = None):
    """send_barrier/fetch_barrier parity: a no-op psum forcing rendezvous."""
    return all_reduce(jnp.zeros(()), axis, mesh=mesh)
