"""Pipeline parallelism: GPipe schedule over the "pp" mesh axis.

Reference mapping: fluid's pipeline is a runtime construct — the program is
cut into sections, each run by a ``SectionWorker`` thread with scope-queues
between stages (``PipelineOptimizer`` optimizer.py:2931, ``PipelineTrainer``
trainer.h:113, ``SectionWorker`` device_worker.h:267). TPU-native: the
schedule is *traced* — a fori_loop over M + n - 1 ticks inside a shard_map
over "pp"; activations hop stages via ``lax.ppermute`` (ICI neighbor
transfer), and autodiff through the loop yields the reverse pipeline, so
one jitted train step contains the whole fwd+bwd schedule.

Composition: the shard_map binds the FULL mesh, so the activation can stay
sharded over (dp, fsdp) batch axes and the "sp" sequence axis via
``x_spec`` while layers hop over "pp" (stage params are replicated over the
other axes; their backward psums the grad contributions automatically).
Per-microbatch side inputs (attention bias, the microbatch index for
dropout PRNG folding) ride the ring alongside the activation.

Constraint (same as scan-over-layers): pipelined blocks must be
structurally identical — true for transformer stacks. Embedding/head run
outside the pipelined middle.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core import mesh as mesh_lib


def stack_layer_params(params_list):
    """[{layer params}, ...] -> single pytree with stacked (L, ...) leaves
    (the pipeline's weight layout; ≙ section programs per device)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *params_list)


def _get_at(tree, path):
    for p in path:
        tree = tree[p]
    return tree


def _set_at(tree, path, value):
    if not path:
        return value
    return {**tree, path[0]: _set_at(tree[path[0]], path[1:], value)}


def stack_params_at(params, path, num_layers: int):
    """Convert the LayerList-layout subtree at ``path`` (per-layer dicts
    keyed "0".."L-1") into stacked (L, ...) leaves — checkpoint migration
    into the StackedLayers layout. E.g. BERT: path=("bert", "encoder");
    GPT: path=("blocks",)."""
    node = _get_at(params, path)
    stacked = stack_layer_params([node[str(i)] for i in range(num_layers)])
    return _set_at(params, tuple(path), stacked)


def unstack_params_at(params, path, num_layers: int):
    """Inverse of :func:`stack_params_at`."""
    node = _get_at(params, path)
    per = {str(i): jax.tree_util.tree_map(lambda x: x[i], node)
           for i in range(num_layers)}
    return _set_at(params, tuple(path), per)


def gpipe(
    block_fn: Callable,
    stacked_params: Any,
    x_microbatches,
    *,
    extras: Any = None,
    mesh: Optional[Mesh] = None,
    axis: str = mesh_lib.PP,
    remat: bool = True,
    x_spec: Optional[P] = None,
    extras_spec: Any = None,
):
    """Run microbatches through a pipelined stack of identical blocks.

    ``block_fn(layer_params, h, extra, mb_idx) -> h``; ``stacked_params``
    leaves are (L_total, ...) with L_total divisible by the "pp" axis size;
    ``x_microbatches``: (M, mb, ...) microbatched activations; ``extras``:
    optional pytree of (M, ...) per-microbatch side inputs that travel the
    ring with the activation (e.g. attention bias); ``mb_idx`` is the
    traced int32 microbatch index (for dropout key folding).

    ``x_spec``/``extras_spec``: PartitionSpecs for the (M, ...) arrays so
    batch/sequence sharding over the other mesh axes is preserved inside
    the pipeline (default: replicated). Returns (M, mb, ...) outputs
    (replicated over "pp", sharded per ``x_spec`` elsewhere).
    """
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None:
        raise ValueError("gpipe requires a mesh")
    n = mesh.shape[axis]
    M = x_microbatches.shape[0]
    if remat:
        block_fn = jax.checkpoint(block_fn)
    x_spec = x_spec if x_spec is not None else P()
    if extras_spec is None:
        extras_spec = jax.tree_util.tree_map(lambda _: P(), extras)

    def local_stage(local_params, h, extra, mb):
        # apply this stage's L_total/n layers (scan over stacked leaves)
        def body(h, layer_params):
            return block_fn(layer_params, h, extra, mb), None
        h, _ = jax.lax.scan(body, h, local_params)
        return h

    def stage_body(local_params, x, extras):
        s = jax.lax.axis_index(axis)
        is_first = s == 0
        is_last = s == n - 1
        T = M + n - 1
        perm = [(i, i + 1) for i in range(n - 1)]
        recv_h = jnp.zeros(x.shape[1:], x.dtype)
        recv_e = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape[1:], a.dtype), extras)
        recv_mb = jnp.zeros((), jnp.int32)
        outputs = jnp.zeros_like(x)

        def tick(t, carry):
            (recv_h, recv_e, recv_mb), outputs = carry
            feed_at = jnp.clip(t, 0, M - 1)
            feed_h = jax.lax.dynamic_index_in_dim(x, feed_at, keepdims=False)
            feed_e = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, feed_at,
                                                       keepdims=False),
                extras)
            inp_h = jnp.where(is_first, feed_h, recv_h)
            inp_e = jax.tree_util.tree_map(
                lambda f, r: jnp.where(is_first, f, r), feed_e, recv_e)
            inp_mb = jnp.where(is_first, feed_at, recv_mb)
            h = local_stage(local_params, inp_h, inp_e, inp_mb)
            mb_idx = t - s          # microbatch this stage just computed
            active = (mb_idx >= 0) & (mb_idx < M)
            write_at = jnp.clip(mb_idx, 0, M - 1)
            prev = jax.lax.dynamic_index_in_dim(outputs, write_at,
                                                keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(active & is_last, h, prev), write_at, 0)
            recv_h = jax.lax.ppermute(h, axis, perm)
            recv_e = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, axis, perm), inp_e)
            recv_mb = jax.lax.ppermute(inp_mb, axis, perm)
            return ((recv_h, recv_e, recv_mb), outputs)

        _, outputs = jax.lax.fori_loop(
            0, T, tick, ((recv_h, recv_e, recv_mb), outputs))
        # outputs are only valid on the last stage: replicate via psum
        outputs = jnp.where(is_last, outputs, 0.0)
        return jax.lax.psum(outputs, axis)

    param_specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    return jax.shard_map(
        stage_body, mesh=mesh,
        in_specs=(param_specs, x_spec, extras_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stacked_params, x_microbatches, extras)


def gpipe_layer_stack(
    apply_layer: Callable,
    params_list,
    x,
    *,
    num_microbatches: int,
    layer_keys=None,
    extras: Any = None,
    extras_spec: Any = None,
    x_spec: Optional[P] = None,
    mesh: Optional[Mesh] = None,
):
    """Model-facing wrapper: run a stack of identical layers through the
    GPipe schedule. Handles param stacking, per-layer dropout-key
    stacking with microbatch + data-shard decorrelation (every (dp,fsdp)
    shard holds different rows and must draw different masks), batch
    microbatching, and the reshape back.

    ``apply_layer(layer_params, h, extra, key) -> h``; ``params_list`` is
    the per-layer param dicts in order — or an ALREADY-STACKED pytree
    with (L, ...) leaves (the nn.module.StackedLayers layout, which is
    pp-sharded from init and skips the in-graph stack + reshard);
    ``x``: (B, ...) activations; ``extras``: optional (M, ...)
    per-microbatch side inputs (microbatch them before calling). Used by
    BERT and GPT's pipeline paths.
    """
    M = num_microbatches
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible by "
                         f"pp_microbatches={M}")
    stacked = (stack_layer_params(list(params_list))
               if isinstance(params_list, (list, tuple)) else params_list)
    has_keys = layer_keys is not None and layer_keys[0] is not None
    if has_keys:
        stacked = (stacked, jnp.stack(list(layer_keys)))

    def block(lp, h, extra, mb_idx):
        if has_keys:
            layer_params, lkey = lp
            k = jax.random.fold_in(lkey, mb_idx)
            k = jax.random.fold_in(
                k, jax.lax.axis_index(("dp", "fsdp")))
        else:
            layer_params, k = lp, None
        return apply_layer(layer_params, h, extra, k)

    if x_spec is None:
        x_spec = P(*((None, ("dp", "fsdp")) + (None,) * (x.ndim - 1)))
    x_mb = x.reshape((M, b // M) + x.shape[1:])
    out = gpipe(block, stacked, x_mb, extras=extras, x_spec=x_spec,
                extras_spec=extras_spec, mesh=mesh)
    return out.reshape(x.shape)


def microbatch(batch, num_microbatches: int):
    """(B, ...) -> (M, B/M, ...) over every leaf."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((num_microbatches, -1) + x.shape[1:]), batch)


def unmicrobatch(batch):
    return jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[2:]), batch)
