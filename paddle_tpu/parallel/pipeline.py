"""Pipeline parallelism: GPipe schedule over the "pp" mesh axis.

Reference mapping: fluid's pipeline is a runtime construct — the program is
cut into sections, each run by a ``SectionWorker`` thread with scope-queues
between stages (``PipelineOptimizer`` optimizer.py:2931, ``PipelineTrainer``
trainer.h:113, ``SectionWorker`` device_worker.h:267). TPU-native: the
schedule is *traced* — a fori_loop over M + n - 1 ticks inside a shard_map
over "pp"; activations hop stages via ``lax.ppermute`` (ICI neighbor
transfer), and autodiff through the loop yields the reverse pipeline, so
one jitted train step contains the whole fwd+bwd schedule.

Constraint (same as scan-over-layers): pipelined blocks must be
structurally identical — true for transformer stacks. Embedding/head run
outside the pipelined middle.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core import mesh as mesh_lib


def stack_layer_params(params_list):
    """[{layer params}, ...] -> single pytree with stacked (L, ...) leaves
    (the pipeline's weight layout; ≙ section programs per device)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *params_list)


def gpipe(
    block_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    x_microbatches,
    *,
    mesh: Optional[Mesh] = None,
    axis: str = mesh_lib.PP,
    remat: bool = True,
):
    """Run microbatches through a pipelined stack of identical blocks.

    block_fn(layer_params, h) -> h; ``stacked_params`` leaves are
    (L_total, ...) with L_total divisible by the "pp" axis size;
    ``x_microbatches``: (M, mb, ...) microbatched activations.
    Returns (M, mb, ...) outputs (replicated over "pp").
    """
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None:
        raise ValueError("gpipe requires a mesh")
    n = mesh.shape[axis]
    M = x_microbatches.shape[0]
    if remat:
        block_fn = jax.checkpoint(block_fn)

    def local_stage(local_params, h):
        # apply this stage's L_total/n layers (scan over stacked leaves)
        def body(h, layer_params):
            return block_fn(layer_params, h), None
        h, _ = jax.lax.scan(body, h, local_params)
        return h

    def stage_body(local_params, x):
        s = jax.lax.axis_index(axis)
        is_first = s == 0
        is_last = s == n - 1
        T = M + n - 1
        perm = [(i, i + 1) for i in range(n - 1)]
        mb_shape = x.shape[1:]
        received = jnp.zeros(mb_shape, x.dtype)
        outputs = jnp.zeros_like(x)

        def tick(t, carry):
            received, outputs = carry
            mb_idx = t - s
            active = (mb_idx >= 0) & (mb_idx < M)
            feed = jax.lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, M - 1), keepdims=False)
            inp = jnp.where(is_first, feed, received)
            h = local_stage(local_params, inp)
            write_at = jnp.clip(mb_idx, 0, M - 1)
            prev = jax.lax.dynamic_index_in_dim(outputs, write_at,
                                                keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(active & is_last, h, prev), write_at, 0)
            received = jax.lax.ppermute(h, axis, perm)
            return received, outputs

        _, outputs = jax.lax.fori_loop(0, T, tick, (received, outputs))
        # outputs are only valid on the last stage: replicate via psum
        outputs = jnp.where(is_last, outputs, 0.0)
        return jax.lax.psum(outputs, axis)

    param_specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    return jax.shard_map(
        stage_body, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, x_microbatches)


def microbatch(batch, num_microbatches: int):
    """(B, ...) -> (M, B/M, ...) over every leaf."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((num_microbatches, -1) + x.shape[1:]), batch)


def unmicrobatch(batch):
    return jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[2:]), batch)
