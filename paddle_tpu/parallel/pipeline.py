"""Pipeline parallelism: GPipe schedule over the "pp" mesh axis.

Reference mapping: fluid's pipeline is a runtime construct — the program is
cut into sections, each run by a ``SectionWorker`` thread with scope-queues
between stages (``PipelineOptimizer`` optimizer.py:2931, ``PipelineTrainer``
trainer.h:113, ``SectionWorker`` device_worker.h:267). TPU-native: the
schedule is *traced* — a fori_loop over M + n - 1 ticks inside a shard_map
over "pp"; activations hop stages via ``lax.ppermute`` (ICI neighbor
transfer), and autodiff through the loop yields the reverse pipeline, so
one jitted train step contains the whole fwd+bwd schedule.

Composition: the shard_map binds the FULL mesh, so the activation can stay
sharded over (dp, fsdp) batch axes and the "sp" sequence axis via
``x_spec`` while layers hop over "pp" (stage params are replicated over the
other axes; their backward psums the grad contributions automatically).
Per-microbatch side inputs (attention bias, the microbatch index for
dropout PRNG folding) ride the ring alongside the activation.

Constraint (same as scan-over-layers): pipelined blocks must be
structurally identical — true for transformer stacks. Embedding/head run
outside the pipelined middle.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core import mesh as mesh_lib


def stack_layer_params(params_list):
    """[{layer params}, ...] -> single pytree with stacked (L, ...) leaves
    (the pipeline's weight layout; ≙ section programs per device)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *params_list)


def _get_at(tree, path):
    for p in path:
        tree = tree[p]
    return tree


def _set_at(tree, path, value):
    if not path:
        return value
    return {**tree, path[0]: _set_at(tree[path[0]], path[1:], value)}


def stack_params_at(params, path, num_layers: int):
    """Convert the LayerList-layout subtree at ``path`` (per-layer dicts
    keyed "0".."L-1") into stacked (L, ...) leaves — checkpoint migration
    into the StackedLayers layout. E.g. BERT: path=("bert", "encoder");
    GPT: path=("blocks",)."""
    node = _get_at(params, path)
    stacked = stack_layer_params([node[str(i)] for i in range(num_layers)])
    return _set_at(params, tuple(path), stacked)


def unstack_params_at(params, path, num_layers: int):
    """Inverse of :func:`stack_params_at`."""
    node = _get_at(params, path)
    per = {str(i): jax.tree_util.tree_map(lambda x: x[i], node)
           for i in range(num_layers)}
    return _set_at(params, tuple(path), per)


def gpipe(
    block_fn: Callable,
    stacked_params: Any,
    x_microbatches,
    *,
    extras: Any = None,
    mesh: Optional[Mesh] = None,
    axis: str = mesh_lib.PP,
    remat: bool = True,
    x_spec: Optional[P] = None,
    extras_spec: Any = None,
):
    """Run microbatches through a pipelined stack of identical blocks.

    ``block_fn(layer_params, h, extra, mb_idx) -> h``; ``stacked_params``
    leaves are (L_total, ...) with L_total divisible by the "pp" axis size;
    ``x_microbatches``: (M, mb, ...) microbatched activations; ``extras``:
    optional pytree of (M, ...) per-microbatch side inputs (e.g. attention
    bias). Extras must be REPLICATED over the "pp" axis (as
    :func:`microbatch_extras` produces): only the scalar microbatch index
    rides the ring, and every stage indexes its local extras copy by it —
    an extras leaf sharded over "pp" would be silently mis-indexed, so
    ``extras_spec`` mentioning the pp axis is rejected. ``mb_idx`` is the
    traced int32 microbatch index (for dropout key folding).

    ``x_spec``/``extras_spec``: PartitionSpecs for the (M, ...) arrays so
    batch/sequence sharding over the other (non-pp) mesh axes is preserved
    inside the pipeline (default: replicated). Returns (M, mb, ...)
    outputs (replicated over "pp", sharded per ``x_spec`` elsewhere).
    """
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None:
        raise ValueError("gpipe requires a mesh")
    n = mesh.shape[axis]
    M = x_microbatches.shape[0]
    if remat:
        block_fn = jax.checkpoint(block_fn)
    x_spec = x_spec if x_spec is not None else P()
    if extras_spec is None:
        extras_spec = jax.tree_util.tree_map(lambda _: P(), extras)
    _check_pp_replicated(x_spec, axis, "x_spec")
    _check_pp_replicated(extras_spec, axis, "extras_spec")

    def local_stage(local_params, h, extra, mb):
        # apply this stage's L_total/n layers (scan over stacked leaves)
        def body(h, layer_params):
            return block_fn(layer_params, h, extra, mb), None
        h, _ = jax.lax.scan(body, h, local_params)
        return h

    def stage_body(local_params, x, extras):
        s = jax.lax.axis_index(axis)
        is_first = s == 0
        is_last = s == n - 1
        T = M + n - 1
        perm = [(i, i + 1) for i in range(n - 1)]
        recv_h = jnp.zeros(x.shape[1:], x.dtype)
        recv_mb = jnp.zeros((), jnp.int32)
        outputs = jnp.zeros_like(x)

        def tick(t, carry):
            (recv_h, recv_mb), outputs = carry
            feed_at = jnp.clip(t, 0, M - 1)
            feed_h = jax.lax.dynamic_index_in_dim(x, feed_at, keepdims=False)
            inp_h = jnp.where(is_first, feed_h, recv_h)
            inp_mb = jnp.where(is_first, feed_at, recv_mb)
            # extras are replicated over "pp": every stage indexes its
            # microbatch's extra locally by the mb index that rides the
            # ring — only the scalar hops, never the (possibly
            # activation-sized) extra itself
            inp_e = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, jnp.clip(inp_mb, 0, M - 1), keepdims=False),
                extras)
            h = local_stage(local_params, inp_h, inp_e, inp_mb)
            mb_idx = t - s          # microbatch this stage just computed
            active = (mb_idx >= 0) & (mb_idx < M)
            write_at = jnp.clip(mb_idx, 0, M - 1)
            outputs = _masked_row_update(outputs, write_at, h,
                                         active & is_last)
            recv_h = jax.lax.ppermute(h, axis, perm)
            recv_mb = jax.lax.ppermute(inp_mb, axis, perm)
            return ((recv_h, recv_mb), outputs)

        _, outputs = jax.lax.fori_loop(
            0, T, tick, ((recv_h, recv_mb), outputs))
        # outputs are only valid on the last stage: replicate via psum
        outputs = jnp.where(is_last, outputs, 0.0)
        return jax.lax.psum(outputs, axis)

    param_specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    from paddle_tpu.core.compat import shard_map
    return shard_map(
        stage_body, mesh=mesh,
        in_specs=(param_specs, x_spec, extras_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stacked_params, x_microbatches, extras)


def _check_pp_replicated(spec_tree, axis, what):
    """Activations and extras are indexed locally by the riding
    microbatch index, which requires every leaf to be replicated over
    the pp axis — a pp-sharded leaf would shrink the local microbatch
    dimension and be silently mis-indexed (clamped), so reject it."""
    for spec in jax.tree_util.tree_leaves(
            spec_tree, is_leaf=lambda s: isinstance(s, P)):
        if not isinstance(spec, P):
            continue
        for entry in spec:
            names = entry if isinstance(entry, tuple) else (entry,)
            if axis in names:
                raise ValueError(
                    f"{what} {spec} shards over the pipeline axis "
                    f"{axis!r}; {what} must be pp-replicated (see "
                    f"microbatch_extras) because stages index the "
                    f"(M, ...) arrays locally by microbatch index")


def _masked_row_update(buf, idx, row, pred):
    prev = jax.lax.dynamic_index_in_dim(buf, idx, keepdims=False)
    return jax.lax.dynamic_update_index_in_dim(
        buf, jnp.where(pred, row, prev), idx, 0)


def interleave_stack(stacked_params, n_stages: int, num_circuits: int):
    """Re-arrange stacked (L, ...) leaves from contiguous-stage order into
    the circular schedule's interleaved placement, so that contiguous
    P("pp") sharding hands device s chunks s, s+n, ..., s+(v-1)n (the
    Megatron interleaved-1F1B assignment). Apply ONCE at param-layout
    time (init / checkpoint load) and pass
    ``circular_pipeline(..., pre_interleaved=True)``; arranging inside
    the train step costs a cross-device reshuffle of every weight (and
    its gradient) per step."""
    n, v = n_stages, num_circuits

    def arrange(a):
        k = a.shape[0] // (n * v)
        return a.reshape((v, n, k) + a.shape[1:]).swapaxes(0, 1).reshape(
            (a.shape[0],) + a.shape[1:])

    return jax.tree_util.tree_map(arrange, stacked_params)


def uninterleave_stack(stacked_params, n_stages: int, num_circuits: int):
    """Inverse of :func:`interleave_stack` (checkpoint export)."""
    n, v = n_stages, num_circuits

    def arrange(a):
        k = a.shape[0] // (n * v)
        return a.reshape((n, v, k) + a.shape[1:]).swapaxes(0, 1).reshape(
            (a.shape[0],) + a.shape[1:])

    return jax.tree_util.tree_map(arrange, stacked_params)


def pipeline_bubble_fraction(n_stages: int, num_microbatches: int,
                             num_circuits: int = 1) -> float:
    """Fraction of stage-computations that are pipeline bubble.

    The traced SPMD schedule executes every stage every tick, so waste is
    structural: GPipe runs M + n - 1 ticks for M useful microbatch-passes
    per stage -> (n-1)/(M+n-1). The circular schedule with v virtual
    stage chunks per device runs v*M + n - 1 ticks of 1/v-size chunks ->
    (n-1)/(v*M+n-1). (The reference's threaded SectionWorker 1F1B,
    section_worker.cc:27, has the same (n-1)-slot bubble; its win is
    concurrency across scopes, which SPMD tracing gets for free.)"""
    n, M, v = n_stages, num_microbatches, num_circuits
    return (n - 1) / (v * M + n - 1)


def circular_pipeline(
    block_fn: Callable,
    stacked_params: Any,
    x_microbatches,
    *,
    num_circuits: int,
    extras: Any = None,
    mesh: Optional[Mesh] = None,
    axis: str = mesh_lib.PP,
    remat: bool = True,
    x_spec: Optional[P] = None,
    extras_spec: Any = None,
    pre_interleaved: bool = False,
):
    """Interleaved (1F1B-circular) pipeline schedule: each device owns
    ``num_circuits`` (v) non-adjacent chunks of the layer stack and every
    microbatch rides the "pp" ring v times (device s holds layer chunks
    s, s+n, ..., s+(v-1)n — the Megatron-LM interleaved-1F1B placement).

    Dense timetable (requires M >= n): device s computes (circuit c,
    microbatch m) at tick t = c*M + m + s; an item leaving the last stage
    re-enters stage 0 after n ticks and waits in a slot buffer for its
    next circuit. Total ticks v*M + n - 1 of 1/v-size chunks, so the
    bubble fraction is (n-1)/(v*M+n-1) versus GPipe's (n-1)/(M+n-1) —
    see :func:`pipeline_bubble_fraction`. Backward through the traced
    loop reverses the same schedule, and only ~n chunk activations are
    live per tick (1F1B's memory profile) instead of GPipe's M.

    Wall-clock caveat (measured, tools/PIPELINE_TIMING.md): the
    structural win only converts to step time when per-tick fixed
    overhead (ring ppermute + banking) is small against per-chunk
    compute — per-tick cost is ``a + (L/(n*v))*c``, and circular runs
    more ticks. On the 8-device CPU mesh (a/c ~ 0.3) circular only
    reaches parity at dim>=1024, mb>=32, pp=4; on TPU the ICI hop makes
    a/c orders smaller, but that number is still hardware-gated. GPipe
    is the default schedule; circular is opt-in for long microbatch
    streams on real interconnects.

    Same contract as :func:`gpipe` otherwise; ``stacked_params`` leaves
    are (L, ...) with L divisible by n * num_circuits.
    """
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None:
        raise ValueError("circular_pipeline requires a mesh")
    n = mesh.shape[axis]
    v = num_circuits
    M = x_microbatches.shape[0]
    if M < n:
        raise ValueError(
            f"circular schedule needs microbatches >= pp stages "
            f"(got M={M} < n={n}); use gpipe for short streams")
    L = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if L % (n * v):
        raise ValueError(f"layers {L} not divisible by pp*circuits "
                         f"{n}*{v}")
    if remat:
        block_fn = jax.checkpoint(block_fn)
    x_spec = x_spec if x_spec is not None else P()
    if extras_spec is None:
        extras_spec = jax.tree_util.tree_map(lambda _: P(), extras)
    _check_pp_replicated(x_spec, axis, "x_spec")
    _check_pp_replicated(extras_spec, axis, "extras_spec")

    # contiguous P(axis) sharding must hand device s its v interleaved
    # chunks in circuit order; pre-arrange at layout time when possible
    # (pre_interleaved=True) to keep the weight reshuffle out of the step
    k = L // (n * v)
    arranged = (stacked_params if pre_interleaved else
                interleave_stack(stacked_params, n, v))

    def stage_body(local_params, x, extras):
        # local_params leaves: (v*k, ...) -> (v, k, ...) chunks
        local_params = jax.tree_util.tree_map(
            lambda a: a.reshape((v, k) + a.shape[1:]), local_params)
        s = jax.lax.axis_index(axis)
        is_first = s == 0
        T = v * M + n - 1
        ring = [(i, (i + 1) % n) for i in range(n)]
        zero_h = jnp.zeros(x.shape[1:], x.dtype)
        carry = dict(
            recv_h=zero_h, recv_mb=jnp.zeros((), jnp.int32),
            buf=jnp.zeros_like(x),        # stage-0 inter-circuit slots
            outputs=jnp.zeros_like(x),
        )

        def chunk_apply(c, h, extra, mb):
            chunk = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, c, keepdims=False),
                local_params)

            def body(h, layer_params):
                return block_fn(layer_params, h, extra, mb), None
            h, _ = jax.lax.scan(body, h, chunk)
            return h

        def tick(t, carry):
            # -- stage 0: bank the arriving item (next circuit or output)
            arr_t = t - n                      # item (c_in, slot) arriving
            arr_valid = arr_t >= 0
            slot = jnp.clip(arr_t, 0, v * M - 1) % M
            c_in = jnp.clip(arr_t, 0, v * M - 1) // M
            done = c_in == v - 1
            put = is_first & arr_valid
            buf = _masked_row_update(carry["buf"], slot,
                                     carry["recv_h"], put & ~done)
            outputs = _masked_row_update(carry["outputs"], slot,
                                         carry["recv_h"], put & done)

            # -- select this tick's input
            c = jnp.clip(t, 0, v * M - 1) // M
            m = jnp.clip(t, 0, v * M - 1) % M
            feed_h = jnp.where(
                c == 0,
                jax.lax.dynamic_index_in_dim(x, m, keepdims=False),
                jax.lax.dynamic_index_in_dim(buf, m, keepdims=False))
            inp_h = jnp.where(is_first, feed_h, carry["recv_h"])
            inp_mb = jnp.where(is_first, m, carry["recv_mb"])
            # extras are pp-replicated: index locally by the riding mb
            # index instead of shipping the extra itself over the ring
            inp_e = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, jnp.clip(inp_mb, 0, M - 1), keepdims=False),
                extras)

            # -- compute this device's chunk for the item it holds
            my_c = jnp.clip((t - s), 0, v * M - 1) // M
            h = chunk_apply(my_c, inp_h, inp_e, inp_mb)

            # -- ring hop
            return dict(
                recv_h=jax.lax.ppermute(h, axis, ring),
                recv_mb=jax.lax.ppermute(inp_mb, axis, ring),
                buf=buf, outputs=outputs)

        carry = jax.lax.fori_loop(0, T, tick, carry)
        # the final item ((v-1, M-1)) arrives after the last tick's hop
        outputs = _masked_row_update(carry["outputs"], jnp.asarray(M - 1),
                                     carry["recv_h"], is_first)
        outputs = jnp.where(is_first, outputs, 0.0)
        return jax.lax.psum(outputs, axis)

    param_specs = jax.tree_util.tree_map(lambda _: P(axis), arranged)
    from paddle_tpu.core.compat import shard_map
    return shard_map(
        stage_body, mesh=mesh,
        in_specs=(param_specs, x_spec, extras_spec),
        out_specs=x_spec,
        check_vma=False,
    )(arranged, x_microbatches, extras)


def gpipe_layer_stack(
    apply_layer: Callable,
    params_list,
    x,
    *,
    num_microbatches: int,
    layer_keys=None,
    extras: Any = None,
    extras_spec: Any = None,
    x_spec: Optional[P] = None,
    mesh: Optional[Mesh] = None,
    schedule: str = "gpipe",
    num_circuits: int = 1,
    pre_interleaved: bool = False,
):
    """Model-facing wrapper: run a stack of identical layers through a
    pipeline schedule (``schedule="gpipe"`` or ``"circular"`` — the
    interleaved 1F1B placement with ``num_circuits`` virtual stages per
    device; see :func:`circular_pipeline`).
    Handles param stacking, per-layer dropout-key
    stacking with microbatch + data-shard decorrelation (every (dp,fsdp)
    shard holds different rows and must draw different masks), batch
    microbatching, and the reshape back.

    ``apply_layer(layer_params, h, extra, key) -> h``; ``params_list`` is
    the per-layer param dicts in order — or an ALREADY-STACKED pytree
    with (L, ...) leaves (the nn.module.StackedLayers layout, which is
    pp-sharded from init and skips the in-graph stack + reshard);
    ``x``: (B, ...) activations; ``extras``: optional (M, ...)
    per-microbatch side inputs (microbatch them before calling). Used by
    BERT and GPT's pipeline paths.
    """
    M = num_microbatches
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible by "
                         f"pp_microbatches={M}")
    stacked = (stack_layer_params(list(params_list))
               if isinstance(params_list, (list, tuple)) else params_list)
    if pre_interleaved and schedule != "circular":
        raise ValueError(
            "pre_interleaved params hold the circular schedule's layer "
            "order; running them through schedule="
            f"{schedule!r} would apply layers in the wrong order — "
            "convert back with uninterleave_stack first")
    has_keys = layer_keys is not None and layer_keys[0] is not None
    if has_keys:
        lkeys = jnp.stack(list(layer_keys))
        if pre_interleaved and schedule == "circular":
            # params are stored interleaved but keys are built fresh in
            # canonical layer order every step — arrange them to match
            # so the layer->key binding is layout-independent
            mesh_ = mesh or mesh_lib.current_mesh()
            lkeys = interleave_stack(lkeys, mesh_.shape[mesh_lib.PP],
                                     num_circuits)
        stacked = (stacked, lkeys)

    def block(lp, h, extra, mb_idx):
        if has_keys:
            layer_params, lkey = lp
            k = jax.random.fold_in(lkey, mb_idx)
            k = jax.random.fold_in(
                k, jax.lax.axis_index(("dp", "fsdp")))
        else:
            layer_params, k = lp, None
        return apply_layer(layer_params, h, extra, k)

    if x_spec is None:
        x_spec = P(*((None, ("dp", "fsdp")) + (None,) * (x.ndim - 1)))
    x_mb = x.reshape((M, b // M) + x.shape[1:])
    if schedule == "circular":
        out = circular_pipeline(block, stacked, x_mb,
                                num_circuits=num_circuits, extras=extras,
                                x_spec=x_spec, extras_spec=extras_spec,
                                mesh=mesh, pre_interleaved=pre_interleaved)
    elif schedule == "gpipe":
        out = gpipe(block, stacked, x_mb, extras=extras, x_spec=x_spec,
                    extras_spec=extras_spec, mesh=mesh)
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    return out.reshape(x.shape)


def microbatch(batch, num_microbatches: int):
    """(B, ...) -> (M, B/M, ...) over every leaf."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((num_microbatches, -1) + x.shape[1:]), batch)


def microbatch_extras(tree, num_microbatches: int):
    """Microbatch per-example side inputs for the pipeline schedules and
    build their PartitionSpecs: (B, ...) -> (M, B/M, ...) with the
    microbatch-local batch dim sharded over (dp, fsdp) and everything
    else replicated (extras never shard over "pp" — stages index them
    locally by the riding microbatch index). Shared by the BERT and
    Transformer pipeline paths."""
    out = microbatch(tree, num_microbatches)
    specs = jax.tree_util.tree_map(
        lambda a: P(*((None, ("dp", "fsdp")) + (None,) * (a.ndim - 2))),
        out)
    return out, specs


def unmicrobatch(batch):
    return jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[2:]), batch)
