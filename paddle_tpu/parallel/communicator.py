"""Async-update communicators: the reference's non-BSP training modes.

Reference mapping (``operators/distributed/communicator.h``):
- ``AsyncCommunicator`` (:276): trainers enqueue per-var gradients; a
  background thread merges up to ``max_merge_var_num`` pending grads and
  sends them to the pserver, which applies them to the global params;
  trainers keep computing on (stale) pulled params.
- ``GeoSgdCommunicator`` (:323, ``transpiler/geo_sgd_transpiler.py``):
  trainers run LOCAL SGD; every ``geo_need_push_nums`` steps each sends the
  DELTA of its params since the last sync (scaled by 1/trainers) and pulls
  the merged globals.

TPU-native redesign:
- :class:`AsyncCommunicator`: the "pserver" is a host-resident master copy
  of the dense params; device steps produce grads, a host thread merges and
  applies them with the optimizer while the device keeps stepping on stale
  params — update application is off the device critical path (sparse
  tables get the same mode from HostKVStore's async push).
- GeoSGD has two forms: :func:`geo_sgd_sync`, a pure-functional delta-merge
  over a mesh axis (shard_map + psum — workers are dp shards, the "server"
  is the collective), and :class:`GeoSgdCommunicator`, the host-side
  variant for stacked local replicas (K, ...) leaves.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


class AsyncCommunicator:
    """Background gradient-merge/apply loop over a host master copy.

    ``push(grads)`` never blocks on the optimizer; the worker thread
    drains the queue, merges up to ``max_merge`` pending gradient pytrees
    (the send-queue merge of communicator.h:166), and applies ONE
    optimizer update for the merged batch. ``pull()`` snapshots the
    current master params (what a trainer would fetch from the pserver).
    """

    def __init__(self, optimizer, params, *, max_merge: int = 20,
                 queue_size: int = 64):
        self.optimizer = optimizer
        self._lock = threading.Lock()
        self._params = params
        self._opt_state = optimizer.init(params)
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._cv = threading.Condition()
        self._pending = 0
        self.max_merge = max_merge
        self.merged_updates = 0    # optimizer applications
        self.pushed = 0            # grads received
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    # -- trainer side ------------------------------------------------------
    def push(self, grads):
        """Enqueue one step's gradients (host copies; non-blocking unless
        the queue is full — backpressure like a bounded send queue)."""
        self._raise_if_failed()
        grads = jax.tree_util.tree_map(jax.device_get, grads)
        with self._cv:
            self._pending += 1
        self._q.put(grads)

    def pull(self):
        with self._lock:
            return self._params

    # -- server side ---------------------------------------------------------
    def _worker(self):
        while not self._stop.is_set() or not self._q.empty():
            try:
                merged = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            count = 1
            try:
                while count < self.max_merge:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        break
                    merged = jax.tree_util.tree_map(jnp.add, merged, nxt)
                    count += 1
                mean = jax.tree_util.tree_map(lambda g: g / count, merged)
                with self._lock:
                    self._params, self._opt_state = self.optimizer.update(
                        mean, self._opt_state, self._params)
                    self.merged_updates += 1
                    self.pushed += count
            except Exception as e:
                # surface at the next flush()/push() instead of silently
                # killing the thread and deadlocking waiters
                self._error = e
            with self._cv:
                self._pending -= count
                self._cv.notify_all()

    _error: Optional[Exception] = None

    def _raise_if_failed(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("AsyncCommunicator worker failed") from err

    def flush(self):
        """Wait until every pushed gradient has been applied."""
        with self._cv:
            self._cv.wait_for(lambda: self._pending == 0)
        self._raise_if_failed()

    def stop(self):
        self.flush()
        self._stop.set()
        self._thread.join()


def geo_sgd_sync(params, anchor, *, axis="dp", mesh=None):
    """One GeoSGD sync point, SPMD form: every worker (= shard of ``axis``)
    contributes its delta since ``anchor``; the merged params become the
    new anchor everywhere.

        merged = anchor + psum(params - anchor) / n

    Call it under jit every ``sync_every`` steps (or via lax.cond on the
    step counter); between syncs the per-worker params must NOT be
    all-reduced — train them with a local (non-psum) step.
    Returns (new_params, new_anchor), identical on every worker.
    """
    from paddle_tpu.core import mesh as mesh_lib
    from jax.sharding import PartitionSpec as P

    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None:
        raise ValueError("geo_sgd_sync requires a mesh")

    def body(params, anchor):
        n = jax.lax.axis_size(axis)

        def merge(p, a):
            return a + jax.lax.psum(p - a, axis) / n

        merged = jax.tree_util.tree_map(merge, params, anchor)
        return merged, merged

    spec = jax.tree_util.tree_map(lambda _: P(), params)
    return jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec), out_specs=(spec, spec),
        check_vma=False,
    )(params, anchor)


class GeoSgdCommunicator:
    """Host-side GeoSGD over K stacked local replicas.

    Replica params live as stacked (K, ...) leaves (train them with
    ``jax.vmap`` over independent data shards). ``maybe_sync`` merges
    deltas every ``sync_every`` steps:

        anchor' = anchor + sum_k(params_k - anchor) / K
        params_k' = anchor'
    """

    def __init__(self, sync_every: int):
        if sync_every < 1:
            raise ValueError("sync_every must be >= 1")
        self.sync_every = sync_every

    def init_anchor(self, stacked_params):
        """Anchor = replica 0 (replicas must start identical)."""
        return jax.tree_util.tree_map(lambda x: x[0], stacked_params)

    def sync(self, stacked_params, anchor):
        new_anchor = jax.tree_util.tree_map(
            lambda p, a: a + (p - a).sum(axis=0) / p.shape[0],
            stacked_params, anchor)
        k = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        new_stacked = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a[None], (k,) + a.shape),
            new_anchor)
        return new_stacked, new_anchor

    def maybe_sync(self, stacked_params, anchor, step: int):
        """Host-loop form: sync when ``step`` hits the cadence."""
        if (step + 1) % self.sync_every == 0:
            return self.sync(stacked_params, anchor)
        return stacked_params, anchor
