"""Async-update communicators: the reference's non-BSP training modes.

Reference mapping (``operators/distributed/communicator.h``):
- ``AsyncCommunicator`` (:276): trainers enqueue per-var gradients; a
  background thread merges up to ``max_merge_var_num`` pending grads and
  sends them to the pserver, which applies them to the global params;
  trainers keep computing on (stale) pulled params.
- ``GeoSgdCommunicator`` (:323, ``transpiler/geo_sgd_transpiler.py``):
  trainers run LOCAL SGD; every ``geo_need_push_nums`` steps each sends the
  DELTA of its params since the last sync (scaled by 1/trainers) and pulls
  the merged globals.

TPU-native redesign:
- :class:`AsyncCommunicator`: the "pserver" is a host-resident master copy
  of the dense params; device steps produce grads, a host thread merges and
  applies them with the optimizer while the device keeps stepping on stale
  params — update application is off the device critical path (sparse
  tables get the same mode from HostKVStore's async push).
- GeoSGD has two forms: :func:`geo_sgd_sync`, a pure-functional delta-merge
  over a mesh axis (shard_map + psum — workers are dp shards, the "server"
  is the collective), and :class:`GeoSgdCommunicator`, the host-side
  variant for stacked local replicas (K, ...) leaves.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from paddle_tpu.core.compat import axis_size as _axis_size
import numpy as np


class AsyncCommunicator:
    """Background gradient-merge/apply loop over a host master copy.

    ``push(grads)`` never blocks on the optimizer; the worker thread
    drains the queue, merges up to ``max_merge`` pending gradient pytrees
    (the send-queue merge of communicator.h:166), and applies ONE
    optimizer update for the merged batch. ``pull()`` snapshots the
    current master params (what a trainer would fetch from the pserver).
    """

    def __init__(self, optimizer, params, *, max_merge: int = 20,
                 queue_size: int = 64):
        self.optimizer = optimizer
        self._lock = threading.Lock()
        self._params = params
        self._opt_state = optimizer.init(params)
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._cv = threading.Condition()
        self._pending = 0
        self.max_merge = max_merge
        self.merged_updates = 0    # optimizer applications
        self.pushed = 0            # grads received
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    # -- trainer side ------------------------------------------------------
    def push(self, grads):
        """Enqueue one step's gradients (host copies; non-blocking unless
        the queue is full — backpressure like a bounded send queue)."""
        self._raise_if_failed()
        grads = jax.tree_util.tree_map(jax.device_get, grads)
        with self._cv:
            self._pending += 1
        self._q.put(grads)

    def pull(self):
        with self._lock:
            return self._params

    # -- server side ---------------------------------------------------------
    def _worker(self):
        while not self._stop.is_set() or not self._q.empty():
            try:
                items = [self._q.get(timeout=0.05)]
            except queue.Empty:
                continue
            # dequeue the whole merge batch FIRST: count is then known
            # before any compute can raise, so _pending stays accurate
            while len(items) < self.max_merge:
                try:
                    items.append(self._q.get_nowait())
                except queue.Empty:
                    break
            count = len(items)
            try:
                merged = items[0]
                for nxt in items[1:]:
                    merged = jax.tree_util.tree_map(jnp.add, merged, nxt)
                mean = jax.tree_util.tree_map(lambda g: g / count, merged)
                with self._lock:
                    self._params, self._opt_state = self.optimizer.update(
                        mean, self._opt_state, self._params)
                    self.merged_updates += 1
                    self.pushed += count
            except Exception as e:
                # surface at the next flush()/push() instead of silently
                # killing the thread and deadlocking waiters
                self._error = e
            with self._cv:
                self._pending -= count
                self._cv.notify_all()

    _error: Optional[Exception] = None

    def _raise_if_failed(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("AsyncCommunicator worker failed") from err

    def flush(self):
        """Wait until every pushed gradient has been applied."""
        with self._cv:
            self._cv.wait_for(lambda: self._pending == 0)
        self._raise_if_failed()

    def stop(self):
        self.flush()
        self._stop.set()
        self._thread.join()


def geo_sgd_sync(stacked_params, anchor, *, participants=None, axis="dp",
                 mesh=None):
    """One GeoSGD sync point, SPMD form. Worker k's locally-trained params
    are row k of the stacked (n, ...) leaves, SHARDED over ``axis`` (each
    device holds exactly its own row — the genuinely divergent state);
    ``anchor`` is replicated. ``participants`` is an (n,) bool mask of
    workers pushing THIS round (the reference's per-trainer
    ``geo_need_push_nums`` cadence — trainers reach their push threshold
    at different times). The delta merge

        anchor' = anchor + psum(m_k * (local_k - anchor)) / n
        local_k' = anchor' if m_k else local_k

    With everyone participating this reduces to replica averaging (use
    plain LocalSGD, optimizer/compression.py, if that is all you need);
    the anchor is load-bearing precisely when participation is partial.
    Returns (new_stacked, new_anchor).
    """
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.parallel import collective

    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None:
        raise ValueError("geo_sgd_sync requires a mesh")
    n_workers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    axis_size = mesh.shape[axis]
    if n_workers != axis_size:
        raise ValueError(
            f"stacked worker rows ({n_workers}) must equal mesh axis "
            f"'{axis}' size ({axis_size}) — each device holds exactly its "
            "own row")
    if participants is None:
        participants = jnp.ones((n_workers,), bool)

    def body(stacked, anchor, mask):
        n = _axis_size(axis)
        m = mask[0].astype(jnp.float32)       # this worker's flag

        def merge(p, a):
            return a + jax.lax.psum(m * (p[0] - a), axis) / n

        new_anchor = jax.tree_util.tree_map(merge, stacked, anchor)
        new_stacked = jax.tree_util.tree_map(
            lambda p, a: jnp.where(m > 0, a[None], p),
            stacked, new_anchor)
        return new_stacked, new_anchor

    stacked_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    repl_spec = jax.tree_util.tree_map(lambda _: P(), anchor)
    return collective.shard_map(
        body, mesh=mesh, in_specs=(stacked_spec, repl_spec, P(axis)),
        out_specs=(stacked_spec, repl_spec),
    )(stacked_params, anchor, participants)


class GeoSgdCommunicator:
    """Host-side GeoSGD over K stacked local replicas.

    Replica params live as stacked (K, ...) leaves (train them with
    ``jax.vmap`` over independent data shards). Each replica pushes on its
    OWN cadence (``sync_every`` can be per-replica, matching the
    reference's per-trainer ``geo_need_push_nums``); at a sync point the
    participating replicas' deltas move the anchor and those replicas
    reset to it while the rest keep training locally:

        anchor' = anchor + sum_{k in S}(params_k - anchor) / K
        params_k' = anchor'  (k in S);  unchanged otherwise

    With S = all replicas this is plain replica averaging — prefer
    LocalSGD (optimizer/compression.py) then; the anchor earns its keep
    under partial/asynchronous participation.
    """

    def __init__(self, sync_every):
        every = np.atleast_1d(np.asarray(sync_every, np.int64))
        if (every < 1).any():
            raise ValueError("sync_every must be >= 1")
        self.sync_every = every

    def init_anchor(self, stacked_params):
        """Anchor = replica 0 (replicas must start identical)."""
        return jax.tree_util.tree_map(lambda x: x[0], stacked_params)

    def sync(self, stacked_params, anchor, participants=None):
        k = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        if participants is None:
            participants = jnp.ones((k,), bool)
        m = jnp.asarray(participants)

        def bmask(a):
            return m.reshape((k,) + (1,) * (a.ndim - 1))

        new_anchor = jax.tree_util.tree_map(
            lambda p, a: a + jnp.where(bmask(p), p - a, 0.0).sum(0) / k,
            stacked_params, anchor)
        new_stacked = jax.tree_util.tree_map(
            lambda p, a: jnp.where(bmask(p), a[None], p),
            stacked_params, new_anchor)
        return new_stacked, new_anchor

    def maybe_sync(self, stacked_params, anchor, step: int):
        """Host-loop form: replicas whose cadence divides ``step + 1``
        participate this round."""
        participants = (step + 1) % self.sync_every == 0
        if not participants.any():
            return stacked_params, anchor
        k = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        mask = jnp.asarray(np.broadcast_to(participants, (k,)))
        return self.sync(stacked_params, anchor, mask)


class FLCommunicator:
    """Federated-averaging server (the fl_listen_and_serv variant,
    ``operators/distributed_ops/fl_listen_and_serv_op.cc:244`` — a sync
    RPC loop over ``Fanin`` clients; the FL transpiler resends merged
    globals each round).

    TPU-native redesign: rounds are explicit. Each round the caller
    trains a SUBSET of clients locally from the current globals (clients
    are rows of stacked (K, ...) leaves — vmap them over their private
    shards), then :meth:`aggregate` folds the participants back with
    FedAvg example-count weighting:

        global' = sum_{k in S} n_k * params_k / sum_{k in S} n_k

    Unlike GeoSGD (delta-to-anchor, everyone keeps local state), FedAvg
    re-seeds every participant from the new globals — client state
    between rounds is the globals, which is what makes it federated.
    """

    def __init__(self, min_fanin: int = 1):
        if min_fanin < 1:
            raise ValueError("min_fanin must be >= 1")
        self.min_fanin = min_fanin
        self.rounds = 0

    def broadcast(self, global_params, num_clients: int):
        """globals -> stacked (K, ...) client copies for this round."""
        return jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (num_clients,) + x.shape),
            global_params)

    def aggregate(self, stacked_params, *, num_examples,
                  participants=None):
        """FedAvg merge. ``num_examples`` (K,) per-client sample counts
        this round; ``participants`` optional (K,) bool mask (clients
        that reported back — the Fanin barrier admits stragglers out).
        Returns the new global params."""
        k = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        n = jnp.asarray(num_examples, jnp.float32)
        if n.shape != (k,):
            raise ValueError(f"num_examples must be ({k},), got {n.shape}")
        m = (jnp.ones((k,), bool) if participants is None
             else jnp.asarray(participants).reshape((k,)))
        if int(m.sum()) < self.min_fanin:
            raise ValueError(
                f"only {int(m.sum())} clients reported; fanin "
                f"{self.min_fanin} required (fl_listen_and_serv Fanin)")
        w = n * m
        total = float(w.sum())
        if total <= 0.0:
            raise ValueError(
                "every participating client reported 0 examples — "
                "aggregating would zero the globals; skip this round")
        w = w / total

        def merge(p):
            # cast back per-leaf: tensordot with f32 weights must not
            # silently promote bf16/int leaves round over round
            return jnp.tensordot(w, p, axes=1).astype(p.dtype)

        self.rounds += 1
        return jax.tree_util.tree_map(merge, stacked_params)
