"""Parameter-server process + remote client for the host KV store.

Reference mapping: ``listen_and_serv_op.cc:110`` (the pserver's blocking
serve loop), ``send_op``/``recv_op`` and ``distributed_lookup_table`` —
fluid's gRPC substrate for sparse tables shared across trainer hosts. The
TPU-native server (native/kv_server.cc) serves the C++ KV store over a
length-prefixed TCP protocol; :class:`RemoteKVStore` is API-compatible
with :class:`~paddle_tpu.parallel.host_kv.HostKVStore`, so
``HostKVEmbedding`` (and the whole DeepFM KV pipeline) runs unchanged
against a remote table — pulls/pushes become one round trip per batch,
prefetch overlap hides the wire latency exactly as it hides the hash
lookups.

Run a standalone pserver (the listen_and_serv process):
    python -m paddle_tpu.parallel.kv_server --dim 9 --port 0
It prints ``PORT <n>`` once serving.
"""

from __future__ import annotations

import ctypes
import socket
import struct
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from paddle_tpu import native
from paddle_tpu.parallel.host_kv import _OPT_NAMES

OP_PULL, OP_PUSH, OP_SET, OP_SIZE, OP_DIM, OP_SAVE, OP_LOAD = range(1, 8)


def _lib():
    lib = native.load_library("kvserver", ["kv_server.cc", "kv_store.cc"])
    lib.kvs_start.restype = ctypes.c_void_p
    lib.kvs_start.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_float,
                              ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
                              ctypes.c_int]
    lib.kvs_port.restype = ctypes.c_int
    lib.kvs_port.argtypes = [ctypes.c_void_p]
    lib.kvs_stop.argtypes = [ctypes.c_void_p]
    return lib


class KVServer:
    """In-process handle on a serving pserver (native accept loop)."""

    # class-level defaults: a partially-constructed server (native
    # build/load failed mid-__init__) must still stop() cleanly
    _h = None
    _lib = None

    def __init__(self, dim: int, *, optimizer: str = "adagrad",
                 init_scale: float = 0.01, seed: int = 0,
                 num_shards: int = 64, num_threads: int = 8,
                 port: int = 0):
        self._lib = _lib()
        self._h = self._lib.kvs_start(
            dim, _OPT_NAMES[optimizer], float(init_scale), int(seed),
            int(num_shards), int(num_threads), int(port)) or None
        if not self._h:
            raise RuntimeError("kv server failed to start")
        self.dim = dim
        self.port = int(self._lib.kvs_port(self._h))

    def stop(self):
        """Idempotent shutdown; safe when the native library never
        loaded (no AttributeError spew at interpreter exit)."""
        h, self._h = getattr(self, "_h", None), None
        lib = getattr(self, "_lib", None)
        if h and lib is not None:
            lib.kvs_stop(h)

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class _Conn:
    def __init__(self, host, port, timeout: Optional[float] = None):
        # timeout covers connect AND each recv (liveness probes must not
        # block through the TCP retry schedule on a partitioned server)
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def request(self, op: int, n: int, payload: bytes,
                resp_len: int) -> bytes:
        self.sock.sendall(struct.pack("<BQ", op, n) + payload)
        out = bytearray()
        while len(out) < resp_len:
            chunk = self.sock.recv(resp_len - len(out))
            if not chunk:
                raise ConnectionError("kv server closed the connection")
            out.extend(chunk)
        return bytes(out)

    def close(self):
        self.sock.close()


class RemoteKVStore:
    """Client for a KV pserver; drop-in for HostKVStore (same surface, so
    HostKVEmbedding/run_kv_epoch work against a remote table).

    Thread-safety: a small connection pool backs the async calls; each
    in-flight operation owns one connection.
    """

    def __init__(self, host: str, port: int, *, pool_size: int = 4):
        self._host, self._port = host, port
        self._pool = [_Conn(host, port)]
        self._pool_lock = threading.Lock()
        self._executor = ThreadPoolExecutor(max_workers=pool_size)
        self._futures = []
        self._fut_lock = threading.Lock()
        d = self._call(OP_DIM, 0, b"", 4)
        self.dim = struct.unpack("<I", d)[0]

    # -- connection pool ---------------------------------------------------
    def _acquire(self) -> _Conn:
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return _Conn(self._host, self._port)

    def _release(self, conn: _Conn):
        with self._pool_lock:
            self._pool.append(conn)

    def _call(self, op, n, payload, resp_len) -> bytes:
        conn = self._acquire()
        try:
            out = conn.request(op, n, payload, resp_len)
        except Exception:
            # a failed/half-read socket is protocol-desynced: drop it so
            # the pool never hands it to the next call
            try:
                conn.close()
            except Exception:
                pass
            raise
        self._release(conn)
        return out

    # -- HostKVStore-compatible surface -----------------------------------
    def pull(self, ids: np.ndarray, out: Optional[np.ndarray] = None
             ) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        raw = self._call(OP_PULL, ids.size, ids.tobytes(),
                         ids.size * self.dim * 4)
        vals = np.frombuffer(raw, np.float32).reshape(ids.size, self.dim)
        if out is None:
            # writable copy: HostKVStore.pull returns mutable rows
            return vals.copy()
        out[:ids.size] = vals   # one copy, straight into the caller buffer
        return out[:ids.size]

    def pull_async(self, ids: np.ndarray,
                   out: Optional[np.ndarray] = None) -> "_RemoteHandle":
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        fut = self._executor.submit(self.pull, ids, out)
        self._track(fut)
        return _RemoteHandle(fut, out)

    def push(self, ids: np.ndarray, grads: np.ndarray, lr: float,
             wait: bool = True):
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        grads = np.ascontiguousarray(grads, np.float32)
        if grads.shape != (ids.size, self.dim):
            raise ValueError(f"grads shape {grads.shape} != "
                             f"({ids.size}, {self.dim})")
        payload = struct.pack("<f", lr) + ids.tobytes() + grads.tobytes()

        def do():
            r = self._call(OP_PUSH, ids.size, payload, 1)
            if r != b"\x01":
                raise IOError("kv server push failed")

        if wait:
            do()
        else:
            self._track(self._executor.submit(do))

    def set_rows(self, ids: np.ndarray, vals: np.ndarray):
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        vals = np.ascontiguousarray(vals, np.float32)
        if vals.shape != (ids.size, self.dim):
            raise ValueError(f"vals shape {vals.shape} != "
                             f"({ids.size}, {self.dim})")
        r = self._call(OP_SET, ids.size, ids.tobytes() + vals.tobytes(), 1)
        if r != b"\x01":
            raise IOError("kv server set_rows failed")

    def _track(self, fut):
        with self._fut_lock:
            self._futures = [f for f in self._futures if not f.done()]
            self._futures.append(fut)

    def flush(self):
        with self._fut_lock:
            futures, self._futures = self._futures, []
        for f in futures:
            f.result()   # re-raises remote errors

    def __len__(self):
        return struct.unpack("<Q", self._call(OP_SIZE, 0, b"", 8))[0]

    def save(self, path: str):
        self.flush()
        p = str(path).encode()
        if self._call(OP_SAVE, len(p), p, 1) != b"\x01":
            raise IOError(f"remote kv_save({path}) failed")

    def load(self, path: str):
        p = str(path).encode()
        if self._call(OP_LOAD, len(p), p, 1) != b"\x01":
            raise IOError(f"remote kv_load({path}) failed")

    def ping(self, timeout: float = 2.0) -> bool:
        """Liveness probe: one cheap size round-trip on a FRESH, timed
        connection (pooled sockets can look alive after a server death
        until their next use; a hung/partitioned server must time out,
        not block the watchdog)."""
        try:
            c = _Conn(self._host, self._port, timeout=timeout)
            try:
                c.request(OP_SIZE, 0, b"", 8)
                return True
            finally:
                c.close()
        except OSError:
            return False

    def close(self):
        self._executor.shutdown(wait=True)
        with self._pool_lock:
            for c in self._pool:
                c.close()
            self._pool = []


class PSMonitor:
    """Parameter-server liveness watchdog — the pserver half of the
    reference's failure detection (heart_beat_monitor.cc:57 tracks
    worker beats on the pserver; trainers learn of a dead pserver from
    failed RPC). Pings the remote store every ``check_every_s``; after
    ``misses`` consecutive failures calls ``on_lost()`` once and stops.
    Compose with fleet.ElasticCoordinator (or any restart policy) to
    respawn a pserver and :meth:`RemoteKVStore.load` its last snapshot.
    """

    def __init__(self, store: "RemoteKVStore", *, check_every_s: float = 1.0,
                 misses: int = 2, on_lost=None, log_fn=print):
        self._store = store
        self._stop = threading.Event()
        self.lost = threading.Event()

        def watch():
            failed = 0
            while not self._stop.wait(check_every_s):
                if self._store.ping(timeout=max(0.5, check_every_s)):
                    failed = 0
                    continue
                failed += 1
                if failed >= misses:
                    log_fn(f"[ps-monitor] pserver "
                           f"{self._store._host}:{self._store._port} "
                           f"lost ({failed} failed pings)")
                    self.lost.set()
                    if on_lost is not None:
                        on_lost()
                    return

        self._thread = threading.Thread(target=watch, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)


class _RemoteHandle:
    """Matches host_kv.PullHandle: wait() returns the pulled rows (the
    padded ``out`` buffer when one was supplied)."""

    def __init__(self, fut, out):
        self._fut = fut
        self._out = out

    def wait(self) -> np.ndarray:
        res = self._fut.result()
        return self._out if self._out is not None else res


def main():
    import argparse
    import signal
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, required=True)
    ap.add_argument("--optimizer", default="adagrad")
    ap.add_argument("--init-scale", type=float, default=0.01)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()
    server = KVServer(args.dim, optimizer=args.optimizer,
                      init_scale=args.init_scale, seed=args.seed,
                      port=args.port)
    print(f"PORT {server.port}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    server.stop()
    sys.exit(0)


if __name__ == "__main__":
    main()
