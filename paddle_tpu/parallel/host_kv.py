"""Host-resident KV embedding: the parameter-server world, TPU-native.

Reference mapping: fluid's sparse tables live in pserver processes and the
trainer pulls/pushes rows over RPC (``FleetWrapper::PullSparseVarsSync``
fleet_wrapper.h:76, ``PushSparsePush``/``PushDenseVarsAsync`` :96;
``listen_and_serv_op.cc:110``; async merge via ``communicator.h:166``). On
TPU the beyond-HBM table lives in HOST memory (paddle_tpu/native/
kv_store.cc): the device step only sees the gathered rows for the current
batch, so the "RPC" is a host hash lookup + a few-MB host→HBM copy that a
prefetch thread overlaps with the previous device step.

Pipeline per batch (sync mode):
  uniq, inv = np.unique(feat_ids)            # host dedup
  rows = store.pull(uniq)                    # host KV gather (C++ threads)
  ...device: emb = rows[inv]; grads w.r.t. rows arrive via XLA scatter-add
  store.push(uniq, grad_rows, lr)            # host sparse optimizer

Async mode: ``prefetch_batch`` starts the pull for batch N+1 while batch N
runs on device; ``apply_grads(..., wait=False)`` applies the push on
background threads (hogwild-delayed, the AsyncCommunicator analog).

The number of unique ids varies per batch; ``rows`` is padded to a bucketed
size so the jitted train step compiles O(log U_max) times, not per batch.
"""

from __future__ import annotations

import ctypes
from typing import NamedTuple, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu import native

OPT_SGD = 0
OPT_ADAGRAD = 1
_OPT_NAMES = {"sgd": OPT_SGD, "adagrad": OPT_ADAGRAD}


def _lib():
    lib = native.load_library("kvstore", ["kv_store.cc"])
    lib.kv_create.restype = ctypes.c_void_p
    lib.kv_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_float,
                              ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
    lib.kv_destroy.argtypes = [ctypes.c_void_p]
    P_I64 = ctypes.POINTER(ctypes.c_int64)
    P_F32 = ctypes.POINTER(ctypes.c_float)
    lib.kv_pull.argtypes = [ctypes.c_void_p, P_I64, ctypes.c_int64, P_F32]
    lib.kv_pull_async.restype = ctypes.c_int64
    lib.kv_pull_async.argtypes = [ctypes.c_void_p, P_I64, ctypes.c_int64,
                                  P_F32]
    lib.kv_push.argtypes = [ctypes.c_void_p, P_I64, ctypes.c_int64, P_F32,
                            ctypes.c_float]
    lib.kv_push_async.restype = ctypes.c_int64
    lib.kv_push_async.argtypes = [ctypes.c_void_p, P_I64, ctypes.c_int64,
                                  P_F32, ctypes.c_float]
    lib.kv_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.kv_flush.argtypes = [ctypes.c_void_p]
    lib.kv_set_rows.argtypes = [ctypes.c_void_p, P_I64, ctypes.c_int64,
                                P_F32]
    lib.kv_size.restype = ctypes.c_int64
    lib.kv_size.argtypes = [ctypes.c_void_p]
    lib.kv_save.restype = ctypes.c_int
    lib.kv_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.kv_load.restype = ctypes.c_int
    lib.kv_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    return lib


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class HostKVStore:
    """ctypes handle over the native sharded KV table.

    ``dim`` is the row width visible to the model (optimizer slot state is
    held natively alongside, invisible here). Rows materialize lazily on
    first pull with deterministic per-id init (uniform ±init_scale).
    """

    # class-level defaults so a partially-constructed instance (native
    # build/load failed mid-__init__) still tears down cleanly
    _h = None
    _lib = None

    def __init__(self, dim: int, *, optimizer: str = "adagrad",
                 init_scale: float = 0.01, seed: int = 0,
                 num_shards: int = 64, num_threads: int = 8):
        self._lib = _lib()
        self.dim = int(dim)
        self.optimizer = optimizer
        self._h = self._lib.kv_create(
            self.dim, _OPT_NAMES[optimizer], float(init_scale), int(seed),
            int(num_shards), int(num_threads)) or None
        if not self._h:
            raise RuntimeError("kv_create failed")

    def _handle(self):
        """Native handle, or a clean Python error after close() — a
        NULL handle handed to ctypes would segfault in native code."""
        h = self._h
        if h is None:
            raise RuntimeError("HostKVStore is closed")
        return h

    def pull(self, ids: np.ndarray, out: Optional[np.ndarray] = None
             ) -> np.ndarray:
        """Gather rows for ``ids``. ``out`` (if given) must be a C-contiguous
        float32 array with at least ids.size rows; rows are written into its
        leading slice (lets callers pull straight into a padded buffer)."""
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        if out is None:
            out = np.empty((ids.size, self.dim), np.float32)
        else:
            self._check_out(ids, out)
        self._lib.kv_pull(self._handle(), _i64p(ids), ids.size, _f32p(out))
        return out[:ids.size]

    def pull_async(self, ids: np.ndarray,
                   out: Optional[np.ndarray] = None) -> "PullHandle":
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        if out is None:
            out = np.empty((ids.size, self.dim), np.float32)
        else:
            self._check_out(ids, out)
        ticket = self._lib.kv_pull_async(self._handle(), _i64p(ids),
                                         ids.size, _f32p(out))
        return PullHandle(self, ticket, ids, out)

    def _check_out(self, ids, out):
        if (out.dtype != np.float32 or not out.flags.c_contiguous
                or out.ndim != 2 or out.shape[0] < ids.size
                or out.shape[1] != self.dim):
            raise ValueError(
                f"out buffer must be C-contiguous float32 (>= {ids.size},"
                f" {self.dim}); got {out.dtype} {out.shape}")

    def push(self, ids: np.ndarray, grads: np.ndarray, lr: float,
             wait: bool = True):
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        grads = np.ascontiguousarray(grads, np.float32)
        if grads.shape != (ids.size, self.dim):
            raise ValueError(f"grads shape {grads.shape} != "
                             f"({ids.size}, {self.dim})")
        if wait:
            self._lib.kv_push(self._handle(), _i64p(ids), ids.size,
                              _f32p(grads), float(lr))
        else:
            # native copies the buffers; applied by pool threads
            self._lib.kv_push_async(self._handle(), _i64p(ids), ids.size,
                                    _f32p(grads), float(lr))

    def set_rows(self, ids: np.ndarray, vals: np.ndarray):
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        vals = np.ascontiguousarray(vals, np.float32)
        if vals.shape != (ids.size, self.dim):
            raise ValueError(f"vals shape {vals.shape} != "
                             f"({ids.size}, {self.dim})")
        self._lib.kv_set_rows(self._handle(), _i64p(ids), ids.size,
                              _f32p(vals))

    def flush(self):
        """Barrier for all outstanding async pulls/pushes."""
        self._lib.kv_flush(self._handle())

    def __len__(self):
        return int(self._lib.kv_size(self._handle()))

    def save(self, path: str):
        self.flush()
        if self._lib.kv_save(self._handle(), str(path).encode()) != 0:
            raise IOError(f"kv_save({path}) failed")

    def load(self, path: str):
        if self._lib.kv_load(self._handle(), str(path).encode()) != 0:
            raise IOError(f"kv_load({path}) failed (dim/optimizer mismatch "
                          "or unreadable file)")

    def close(self):
        """Idempotent teardown: flush outstanding async ops and destroy
        the native table. Safe to call repeatedly, and safe on a store
        whose native library never loaded (``_lib()`` raised mid-
        ``__init__``) — the interpreter-exit ``__del__`` path must not
        spew AttributeErrors over a half-built instance."""
        h, self._h = getattr(self, "_h", None), None
        lib = getattr(self, "_lib", None)
        if h and lib is not None:
            try:
                lib.kv_flush(h)
            finally:
                lib.kv_destroy(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass  # interpreter teardown: never raise from __del__


class PullHandle:
    """An in-flight async pull; buffers are pinned here until wait().

    The native pool writes into ``ids``/``out`` directly, so an abandoned
    handle must still wait before the buffers are garbage-collected —
    ``__del__`` guarantees that (pushes copy their inputs; pulls do not).
    """

    def __init__(self, store: HostKVStore, ticket: int, ids, out):
        self._store, self._ticket = store, ticket
        self._ids, self._out = ids, out
        self._done = False

    def wait(self) -> np.ndarray:
        if not self._done:
            h = self._store._h
            if h is not None:       # closed store already flushed
                self._store._lib.kv_wait(h, self._ticket)
            self._done = True
        return self._out

    def __del__(self):
        try:
            self.wait()
        except Exception:
            pass  # store already torn down


class SparseBatch(NamedTuple):
    """Device-ready view of one batch's sparse rows.

    rows[inv] reconstructs the per-feature embeddings; ``uniq`` is padded
    with -1 (rows zero-padded) to a bucketed size for a bounded number of
    jit compilations.
    """
    uniq: np.ndarray   # (U_pad,) int64, -1 padding
    rows: np.ndarray   # (U_pad, dim) float32
    inv: np.ndarray    # feat_ids.shape int32 indices into rows


def _bucket(n: int, minimum: int) -> int:
    b = max(minimum, 1)
    while b < n:
        b *= 2
    return b


class HostKVEmbedding:
    """Batch-level orchestration over :class:`HostKVStore`.

    The model-side contract: the jitted step takes ``rows`` (U_pad, dim)
    as a differentiable input and ``inv`` as indices; its grad w.r.t.
    ``rows`` (XLA scatter-add over the gather) is what ``apply_grads``
    pushes back. lr lives host-side (sparse optimizer runs on host).
    """

    def __init__(self, store: HostKVStore, *, lr: float = 0.01,
                 min_bucket: int = 256):
        self.store = store
        self.lr = lr
        self.min_bucket = min_bucket

    # -- pulls ---------------------------------------------------------------
    def _dedup(self, feat_ids: np.ndarray):
        uniq, inv = np.unique(np.asarray(feat_ids, np.int64),
                              return_inverse=True)
        pad = _bucket(uniq.size, self.min_bucket)
        uniq_p = np.full((pad,), -1, np.int64)
        uniq_p[:uniq.size] = uniq
        return uniq, uniq_p, inv.reshape(feat_ids.shape).astype(np.int32)

    def lookup_batch(self, feat_ids: np.ndarray) -> SparseBatch:
        uniq, uniq_p, inv = self._dedup(feat_ids)
        rows = np.zeros((uniq_p.size, self.store.dim), np.float32)
        self.store.pull(uniq, out=rows)   # fills rows[:U] in place
        return SparseBatch(uniq_p, rows, inv)

    def prefetch_batch(self, feat_ids: np.ndarray) -> "SparsePrefetch":
        uniq, uniq_p, inv = self._dedup(feat_ids)
        rows = np.zeros((uniq_p.size, self.store.dim), np.float32)
        return SparsePrefetch(self.store.pull_async(uniq, out=rows),
                              uniq_p, inv)

    # -- push ----------------------------------------------------------------
    def apply_grads(self, batch: SparseBatch, grad_rows, *,
                    wait: bool = True):
        grad_rows = np.asarray(grad_rows, np.float32)
        real = batch.uniq >= 0
        self.store.push(batch.uniq[real], grad_rows[real], self.lr,
                        wait=wait)

    def flush(self):
        self.store.flush()


class SparsePrefetch:
    """In-flight pull straight into the padded rows buffer."""

    def __init__(self, handle: PullHandle, uniq_p, inv):
        self._handle, self._uniq_p, self._inv = handle, uniq_p, inv

    def wait(self) -> SparseBatch:
        self._handle.wait()
        return SparseBatch(self._uniq_p, self._handle._out, self._inv)


def fits_hbm(vocab_size: int, dim: int, *, budget_bytes: int,
             dtype_bytes: int = 4, optimizer_slots: int = 2) -> bool:
    """Placement policy: a table (plus device optimizer state) must fit the
    per-table HBM budget to be GSPMD-sharded on chip; otherwise it goes to
    the host KV world (the pslib beyond-HBM case)."""
    return vocab_size * dim * dtype_bytes * (1 + optimizer_slots) \
        <= budget_bytes


def build_kv_train_step(loss_fn, optimizer):
    """Train step for models with host-resident sparse tables.

    ``loss_fn(params, rows, **batch)`` -> scalar or (scalar, aux); ``rows``
    is the pulled (U_pad, dim) array. Returns ``step(state, rows, **batch)
    -> (state, grad_rows, metrics)`` — dense params update on device (the
    hogwild "dense vars" path), ``grad_rows`` goes back to the host store.
    Jit it once; compile count is bounded by the row-bucket count.
    """
    import jax

    def forward(params, rows, batch):
        out = loss_fn(params, rows, **batch)
        if isinstance(out, tuple):
            return out
        return out, {}

    grad_fn = jax.value_and_grad(forward, argnums=(0, 1), has_aux=True)

    def step(state, rows, **batch):
        (loss, aux), (grads, grad_rows) = grad_fn(
            state["params"], rows, batch)
        params, opt_state = optimizer.update(
            grads, state["opt"], state["params"])
        new_state = dict(state)
        new_state.update(params=params, opt=opt_state,
                         step=state["step"] + 1)
        return new_state, grad_rows, {"loss": loss, **aux}

    return step


def run_kv_epoch(step_fn, state, emb: HostKVEmbedding, batches,
                 ids_key: str = "feat_ids", *, prefetch: bool = True,
                 async_push: bool = False):
    """Drive one epoch of host-KV training.

    ``prefetch=True`` pulls batch i+1's rows (C++ threads, no GIL) while
    batch i runs on device — the parameter-prefetch overlap of the
    reference's DownpourWorker pipeline. ``async_push=True`` applies
    gradient pushes on background threads (delayed/hogwild updates, the
    AsyncCommunicator mode); reads may then be one batch stale — exactly
    the reference's async semantics. Use prefetch=False, async_push=False
    for strictly synchronous (parity-testable) training.

    ``batches`` yields dicts; ``batch[ids_key]`` are the sparse feature
    ids, every other key is fed to ``step_fn``.
    """
    import numpy as _np

    history = []
    it = iter(batches)
    batch = next(it, None)
    pf = None
    while batch is not None:
        nxt = next(it, None) if prefetch else None
        if prefetch:
            # this batch's pull was issued last iteration (or is the first)
            sb = pf.wait() if pf is not None \
                else emb.lookup_batch(batch[ids_key])
        else:
            # strictly synchronous: pull AFTER the previous push landed
            sb = emb.lookup_batch(batch[ids_key])
        feed = {k: v for k, v in batch.items() if k != ids_key}
        state, grad_rows, metrics = step_fn(
            state, sb.rows, inv=sb.inv, **feed)
        if prefetch and nxt is not None:
            # issue the NEXT batch's dedup + pull only after this step
            # is dispatched: jax dispatch is async, so the np.unique
            # sort AND the C++ pull threads both run while the device
            # executes — issuing before dispatch (the old order) left
            # the dedup serial on the critical path, which on small
            # steps cost more than the overlap won back
            pf = emb.prefetch_batch(nxt[ids_key])
        emb.apply_grads(sb, _np.asarray(grad_rows), wait=not async_push)
        history.append(metrics)
        batch = nxt if prefetch else next(it, None)
    emb.flush()
    return state, history
