"""Sharded embedding engine: the TPU-native parameter-server replacement.

Reference mapping (SURVEY.md §5.8 "PS/gRPC world"): fluid serves massive
sparse embeddings through a parameter server — ``lookup_table_op`` with
``SelectedRows`` sparse grads, ``distributed_lookup_table_op``/
``parameter_prefetch.cc`` remote lookups, pslib KV store via
``FleetWrapper::PullSparseVarsSync`` (``fleet_wrapper.h:76``). On TPU the
table is GSPMD-sharded over a mesh axis and the "prefetch RPC" becomes an
on-chip collective:

- rows sharded over "tp"/"ep" (Megatron vocab-parallel): each device masks
  ids to its row range, gathers locally, and a psum merges partials — one
  all-reduce instead of a pserver round trip.
- gradients flow through ``jnp.take`` (XLA scatter-add on the backward) —
  the ``SelectedRows`` sparse-grad machinery is subsumed by XLA.

For tables beyond aggregate HBM, the host-resident KV engine
(``paddle_tpu/parallel/host_kv.py`` over ``native/kv_store.cc``) holds the
table in host memory and the device step consumes pulled rows — see
:func:`paddle_tpu.parallel.host_kv.fits_hbm` for the placement policy
(SURVEY.md §7 step 8).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.module import Layer
from paddle_tpu.core.compat import axis_size as _axis_size


def vocab_parallel_lookup(ids, table, *, axis: str = mesh_lib.TP,
                          mesh: Optional[Mesh] = None):
    """Megatron-style sharded lookup: ``table`` rows sharded over ``axis``.

    ids: int array (any shape); table: (V, D) with V sharded. Returns
    embeddings of shape ids.shape + (D,), replicated over ``axis``.
    Under jit+mesh, GSPMD sees an explicit shard_map: local masked take +
    psum (≙ the pserver prefetch+merge round, parameter_prefetch.cc).
    """
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None:
        # single-device / no-mesh: plain lookup
        return jnp.take(table, ids, axis=0)

    def body(ids, table):
        n = _axis_size(axis)
        shard_rows = table.shape[0]
        start = jax.lax.axis_index(axis) * shard_rows
        local = ids - start
        in_range = (local >= 0) & (local < shard_rows)
        safe = jnp.clip(local, 0, shard_rows - 1)
        out = jnp.take(table, safe, axis=0)
        out = jnp.where(in_range[..., None], out, 0.0)
        if n > 1:
            out = jax.lax.psum(out, axis)
        return out

    batch_size = mesh.shape["dp"] * mesh.shape["fsdp"] \
        if all(a in mesh.shape for a in mesh_lib.BATCH_AXES) else 1
    if ids.ndim and batch_size > 1 and ids.shape[0] % batch_size == 0:
        ids_spec = P(mesh_lib.BATCH_AXES)
    else:  # odd batch (or scalar ids): keep ids replicated
        ids_spec = P()
    from paddle_tpu.core.compat import shard_map
    return shard_map(
        body, mesh=mesh,
        in_specs=(ids_spec, P(axis, None)),
        out_specs=ids_spec,
        check_vma=False,
    )(ids, table)


class ShardedEmbedding(Layer):
    """Embedding with rows sharded over a mesh axis; lookup via
    :func:`vocab_parallel_lookup` when a mesh is active.

    ``combiner``: None returns (..., num_ids, D); "sum"/"mean" reduce over
    the ids dim (fluid ``embedding`` + ``sequence_pool`` fusion — the
    MultiSlot CTR pattern, data_feed.h MultiSlot slots)."""

    def __init__(self, num_embeddings, embedding_dim, *, axis=mesh_lib.TP,
                 combiner: Optional[str] = None, weight_init=None,
                 padding_idx: Optional[int] = None):
        super().__init__()
        self.axis = axis
        self.combiner = combiner
        self.padding_idx = padding_idx
        self.num_embeddings = num_embeddings
        self.weight = self.create_parameter(
            "weight", (num_embeddings, embedding_dim),
            initializer=weight_init or I.normal(0.0, 0.01),
            sharding=P(axis, None))

    def forward(self, params, ids):
        out = vocab_parallel_lookup(ids, params["weight"], axis=self.axis)
        if self.padding_idx is not None:
            valid = ids != self.padding_idx
            out = jnp.where(valid[..., None], out, 0.0)
        if self.combiner == "sum":
            out = out.sum(axis=-2)
        elif self.combiner == "mean":
            if self.padding_idx is not None:
                # mean over VALID ids only (sequence_pool "average" parity)
                denom = jnp.maximum(
                    valid.sum(axis=-1, keepdims=True), 1).astype(out.dtype)
                out = out.sum(axis=-2) / denom
            else:
                out = out.mean(axis=-2)
        return out
