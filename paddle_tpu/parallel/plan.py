"""Sharding plans: declarative parameter/state placement over a named mesh.

TPU-native replacement for the reference's multi-device graph builders
(``ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:39,594,677`` —
which clone ops per device and insert collectives per gradient) and the
DistributeTranspiler's param-block placement (``transpiler/
distribute_transpiler.py:494``). Here, placement is data, not graph surgery:
a :class:`ShardingPlan` maps parameter paths to ``PartitionSpec``s; pjit +
GSPMD then insert all collectives (the AllReduceOpHandle /
ReduceOpHandle / BroadcastOpHandle world) automatically.

Precedence for a parameter's spec:
  1. first matching plan rule (regex over the "/"-joined path)
  2. the ParamSpec.sharding hint declared by the layer
  3. replicated (P())

Axes of size 1 in the mesh are harmless in any spec, so plans are written
once and reused across mesh shapes (dp-only, dp x tp, fsdp, ...).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core import mesh as mesh_lib


@dataclasses.dataclass
class Rule:
    pattern: str           # regex matched against "/".join(path)
    spec: Optional[P]      # PartitionSpec (None = replicated)

    def __post_init__(self):
        self._re = re.compile(self.pattern)

    def matches(self, path_str: str) -> bool:
        return self._re.search(path_str) is not None


class ShardingPlan:
    """Ordered rules mapping param paths to PartitionSpecs.

    ``fsdp_largest_dim=True`` additionally shards the largest dim of any
    big parameter over the "fsdp" axis when no rule/hint names it (ZeRO-3
    analog — capability absent in the reference, SURVEY.md §2.6 last row).
    """

    def __init__(self, rules: Sequence[Tuple[str, Optional[P]]] = (),
                 *, fsdp_largest_dim: bool = False,
                 fsdp_min_size: int = 2 ** 16):
        self.rules = [Rule(p, s) for p, s in rules]
        self.fsdp_largest_dim = fsdp_largest_dim
        self.fsdp_min_size = fsdp_min_size

    def spec_for(self, path: Tuple[str, ...], hint: Optional[P],
                 shape: Tuple[int, ...] = ()) -> P:
        path_str = "/".join(path)
        for rule in self.rules:
            if rule.matches(path_str):
                return rule.spec if rule.spec is not None else P()
        spec = hint if hint is not None else P()
        if self.fsdp_largest_dim and shape and not _names_axis(spec, "fsdp"):
            size = 1
            for d in shape:
                size *= d
            if size >= self.fsdp_min_size:
                spec = _add_fsdp(spec, shape)
        return spec

    # -- tree builders ----------------------------------------------------
    def params_specs(self, params, hints=None) -> Any:
        """Pytree of PartitionSpecs matching ``params``.

        ``hints`` is an optional matching pytree of PartitionSpec-or-None
        (e.g. ``model.sharding_specs(params)``).
        """
        def walk(tree, hint_tree, path):
            if isinstance(tree, dict):
                return {
                    k: walk(v,
                            hint_tree.get(k) if isinstance(hint_tree, dict)
                            else None,
                            path + (k,))
                    for k, v in tree.items()
                }
            hint = hint_tree if isinstance(hint_tree, (P, type(None))) else None
            shape = getattr(tree, "shape", ())
            return self.spec_for(path, hint, tuple(shape))

        return walk(params, hints or {}, ())

    def state_specs(self, state, hints=None) -> Any:
        """Specs for a full train state {params, opt, step, ...}.

        Optimizer slot buffers inherit their parameter's spec (the reference
        keeps accumulators on the param's device for the same reason —
        ``optimizer.py`` accumulators live beside params). Scalars/steps are
        replicated.
        """
        pspecs = self.params_specs(state["params"], hints)
        out = {}
        for key, val in state.items():
            if key == "params":
                out[key] = pspecs
            elif key == "opt":
                out[key] = _opt_specs(val, pspecs)
            else:
                out[key] = jax.tree_util.tree_map(lambda _: P(), val)
        return out


def _opt_specs(opt_state, pspecs):
    if isinstance(opt_state, dict):
        out = {}
        for k, v in opt_state.items():
            if k == "slots" and isinstance(v, dict):
                out[k] = {name: pspecs for name in v}
            else:
                out[k] = jax.tree_util.tree_map(lambda _: P(), v)
        return out
    return jax.tree_util.tree_map(lambda _: P(), opt_state)


def _names_axis(spec: P, axis: str) -> bool:
    for entry in spec:
        if entry == axis:
            return True
        if isinstance(entry, tuple) and axis in entry:
            return True
    return False


def _add_fsdp(spec: P, shape: Tuple[int, ...]) -> P:
    """Shard the largest currently-unsharded dim over "fsdp"."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if entries[i] is None:
            entries[i] = "fsdp"
            break
        if isinstance(entries[i], str):
            entries[i] = (entries[i], "fsdp")
            break
    return P(*entries)


def named_shardings(mesh: Mesh, specs: Any) -> Any:
    """PartitionSpec pytree -> NamedSharding pytree."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P) or x is None,
    )


# Canned plans --------------------------------------------------------------

def replicated_plan() -> ShardingPlan:
    """Pure data parallel: all params replicated; grads all-reduced by XLA.
    ≙ AllReduceSSAGraphBuilder (multi_devices_graph_pass.cc:594)."""
    return ShardingPlan()


def fsdp_plan(min_size: int = 2 ** 16) -> ShardingPlan:
    """ZeRO-3 style: big params sharded over "fsdp"."""
    return ShardingPlan(fsdp_largest_dim=True, fsdp_min_size=min_size)


def megatron_plan() -> ShardingPlan:
    """Honor per-layer TP hints (Linear declares Megatron col/row specs);
    everything else replicated."""
    return ShardingPlan()


def serving_tp_plan() -> ShardingPlan:
    """Specs for the serving engine's head-major tensor-parallel param
    layout (``ServingEngine(mesh=...)``, ISSUE 15): the fused qkv
    weight reshaped ``(D, 3, H, Dh)`` is column-sharded over "tp" on
    the HEAD axis and the output projection reshaped ``(H, Dh, D)`` is
    row-sharded — the canonical SpecLayout qkv-col / attn-out-row
    Megatron split (SNIPPETS.md), applied at head granularity because a
    raw ``(D, 3D)`` column shard would straddle the q/k/v boundaries.
    Everything else (embeddings, layer norms, MLP, logits) is
    replicated: decode is KV-bandwidth-bound, and keeping the MLP
    replicated is what holds the sharded step to ONE collective — the
    psum at each layer's attention output."""
    return ShardingPlan(rules=[
        (r"attn/qkv_tp/weight$", P(None, None, "tp", None)),
        (r"attn/qkv_tp/bias$", P(None, "tp", None)),
        (r"attn/out_tp/weight$", P("tp", None, None)),
        (r"^", P()),      # everything else replicated
    ])


def serving_prefill_tp_plan() -> ShardingPlan:
    """:func:`serving_tp_plan` plus the Megatron MLP split the PREFILL
    tier wants (ISSUE 19): prefill is flops-bound, so the MLP matmuls
    dominate and sharding them is worth a second collective per layer.
    ``fc1`` (the SpecLayout ``ffn_up``) is column-sharded over "tp" on
    its output dim, ``fc2`` (``down``) is row-sharded on its input dim,
    and the fc2 bias stays replicated so it is added exactly once AFTER
    the psum of the row-parallel partial products. Decode-tier and
    colocated engines keep :func:`serving_tp_plan`'s replicated MLP and
    its single-psum step shape."""
    return ShardingPlan(rules=[
        (r"attn/qkv_tp/weight$", P(None, None, "tp", None)),
        (r"attn/qkv_tp/bias$", P(None, "tp", None)),
        (r"attn/out_tp/weight$", P("tp", None, None)),
        (r"mlp/fc1/weight$", P(None, "tp")),
        (r"mlp/fc1/bias$", P("tp")),
        (r"mlp/fc2/weight$", P("tp", None)),
        (r"^", P()),      # everything else (incl. fc2 bias) replicated
    ])


def paged_pool_specs(pages) -> list:
    """PartitionSpec pytree for a :class:`~paddle_tpu.serving
    .PagedKVCache` page pool under tp: K/V page arrays sharded over
    "tp" on the head axis (per-shard pools), int8 scale rows replicated
    (per-token scales are head-global — see ``quantize_kv``'s
    ``psum_axis``). Mirrors the pool's per-layer tuple structure, so it
    drops straight into ``shard_map`` in/out specs."""
    kv = P(None, None, "tp", None)
    out = []
    for ent in pages:
        specs = [kv, kv]
        specs.extend(P() for _ in ent[2:])      # int8 scale rows
        out.append(tuple(specs))
    return out
