"""Ring attention: sequence/context parallelism over the "sp" mesh axis.

Capability ABSENT in the reference (SURVEY.md §5.7 — fluid 1.5 predates
long-context training; its story was LoD ragged tensors + DynamicRNN). The
TPU build adds it as a first-class axis: q/k/v are sharded on the sequence
dim over "sp"; each device computes attention between its local queries and
a rotating k/v block that travels the ring via ``lax.ppermute`` (ICI
neighbor exchange), merging partial results with the flash-attention
online-softmax recurrence. Memory per device is O(S/n · S/n) per block and
the k/v transfer overlaps compute under XLA's async collectives.

Composes with GSPMD: call :func:`ring_attention` under jit with a mesh
context; the shard_map boundary converts the GSPMD-sharded (B,H,S,D)
arrays to per-device local blocks and back.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.ops.attention import NEG_INF
from paddle_tpu.core.compat import axis_size as _axis_size


def _block_update(carry, kv, *, scale, causal, q_offset, k_offset, seq_q_blk):
    """One online-softmax step: fold (k,v[,bias]) block into (m, l, acc).

    q_offset/k_offset are the GLOBAL start positions of the local q block
    and the visiting k block (traced ints ok) — used for causal masking.
    """
    m_prev, l_prev, acc = carry
    q, k, v, bias = kv
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(s.dtype)
    if causal:
        blk_k = k.shape[2]
        row = q_offset + jax.lax.broadcasted_iota(
            jnp.int32, (seq_q_blk, blk_k), 0)
        col = k_offset + jax.lax.broadcasted_iota(
            jnp.int32, (seq_q_blk, blk_k), 1)
        s = jnp.where(col <= row, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_next = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_next)
    p = jnp.exp(s - m_next)
    l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    acc_next = acc * alpha + pv
    return m_next, l_next, acc_next


def _ring_attention_local(q, k, v, bias, *, axis, scale, causal):
    """Per-device body (inside shard_map). q,k,v local: (B,H,Sl,D)."""
    n = _axis_size(axis)
    idx = jax.lax.axis_index(axis)
    b, h, sl, d = q.shape
    q32 = q.astype(jnp.float32)

    m = jnp.full((b, h, sl, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sl, 1), jnp.float32)
    acc = jnp.zeros((b, h, sl, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        m, l, acc, k, v, bias = carry
        # block currently held arrived from (idx - i) mod n
        src = jax.lax.rem(idx - i + n, n)
        m, l, acc = _block_update(
            (m, l, acc),
            (q32, k.astype(jnp.float32), v, bias),
            scale=scale, causal=causal,
            q_offset=idx * sl, k_offset=src * sl, seq_q_blk=sl)
        k = jax.lax.ppermute(k, axis, perm)
        v = jax.lax.ppermute(v, axis, perm)
        if bias is not None:
            bias = jax.lax.ppermute(bias, axis, perm)
        return m, l, acc, k, v, bias

    if bias is None:
        # keep the carry pytree static: loop without a bias leaf
        def step_nb(i, carry):
            m, l, acc, k, v = carry
            m, l, acc, k2, v2, _ = step(i, (m, l, acc, k, v, None))
            return m, l, acc, k2, v2
        m, l, acc, _, _ = jax.lax.fori_loop(0, n, step_nb, (m, l, acc, k, v))
    else:
        m, l, acc, _, _, _ = jax.lax.fori_loop(0, n, step,
                                               (m, l, acc, k, v, bias))
    denom = jnp.where(l == 0.0, 1.0, l)
    return (acc / denom).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas-backed ring attention: flash kernel per visiting block
# ---------------------------------------------------------------------------
#
# The composed path above materializes fp32 (B,H,Sl,Sl) score blocks per
# ring step; at long context that caps MFU on HBM bandwidth. The flash path
# keeps flash-level arithmetic intensity: each ring step runs the Pallas
# forward kernel on (q_local, k_visiting) returning a NORMALIZED block
# output plus its logsumexp, and blocks merge with the streaming
# logaddexp recurrence:
#     lse'   = logaddexp(lse, lse_blk)
#     out'   = out * exp(lse - lse') + out_blk * exp(lse_blk - lse')
# The whole per-device ring is ONE custom_vjp: the backward re-rotates
# k/v around the ring with their grad accumulators, running the Pallas
# FA2 backward kernels per block against the GLOBAL lse (so recomputed
# probabilities match the merged forward exactly).


def _ring_flash_case(idx, src, n):
    """0 = diagonal block (causal masking inside), 1 = fully visible,
    2 = fully masked (skip)."""
    return jnp.where(src == idx, 0, jnp.where(src < idx, 1, 2))


def _make_ring_flash(axis: str, scale: float, causal: bool,
                     interpret: bool, block_q: int = 512,
                     block_k: int = 512):
    from paddle_tpu.ops import attention as A

    # Interpret/single-device mode routes each ring block through the
    # shared harness's lax fallback (paddle_tpu.kernels: the registered
    # flash kernel's lax_fn + block backward) instead of running the
    # Pallas kernel under the interpreter. Same numerics (the fallback
    # mirrors the kernel's masking/lse conventions exactly), but the
    # traced program contains no Pallas interpreter shim — which is what
    # used to lower a PartitionId op XLA refuses under SPMD partitioning
    # (the old strict-xfail in tests/test_ring_attention.py).
    def fwd_one(q, k, v, bias, blk_causal):
        if interpret:
            return A._lax_flash_fwd(q, k, v, bias, scale=scale,
                                    causal=blk_causal, return_lse=True)
        return A._flash_fwd(q, k, v, bias, scale=scale, causal=blk_causal,
                            block_q=block_q, block_k=block_k,
                            interpret=False, return_lse=True)

    def bwd_one(q, k, v, bias, out, lse, g, blk_causal):
        if interpret:
            return A._lax_flash_block_bwd(q, k, v, bias, out, lse, g,
                                          scale=scale, causal=blk_causal)
        return A._flash_bwd(q, k, v, bias, out, lse, g, scale=scale,
                            causal=blk_causal, block_q=block_q,
                            block_k=block_k, interpret=False)

    def fwd_block(q, k, v, bias, case):
        b, h, sl, d = q.shape

        def diag(q, k, v, bias):
            return fwd_one(q, k, v, bias, True)

        def full(q, k, v, bias):
            return fwd_one(q, k, v, bias, False)

        def skip(q, k, v, bias):
            return (jnp.zeros((b, h, sl, d), q.dtype),
                    jnp.full((b, h, sl), NEG_INF, jnp.float32))

        if not causal:
            return full(q, k, v, bias)
        return jax.lax.switch(case, [diag, full, skip], q, k, v, bias)

    def bwd_block(q, k, v, bias, out, lse, g, case):
        def diag(q, k, v, bias, out, lse, g):
            return bwd_one(q, k, v, bias, out, lse, g, True)

        def full(q, k, v, bias, out, lse, g):
            return bwd_one(q, k, v, bias, out, lse, g, False)

        def skip(q, k, v, bias, out, lse, g):
            return (jnp.zeros_like(q), jnp.zeros_like(k),
                    jnp.zeros_like(v))

        if not causal:
            return full(q, k, v, bias, out, lse, g)
        return jax.lax.switch(case, [diag, full, skip],
                              q, k, v, bias, out, lse, g)

    @jax.custom_vjp
    def ring_flash_local(q, k, v, bias):
        out, _ = _ring_flash_fwd(q, k, v, bias)
        return out

    def _rot(x, perm):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.ppermute(a, axis, perm), x)

    def _ring_flash_fwd(q, k, v, bias):
        n = _axis_size(axis)
        # axis_index only when the case matters: a dead PartitionId in
        # the non-causal lowering is exactly what XLA's SPMD partitioner
        # refuses ("PartitionId instruction is not supported...")
        idx = jax.lax.axis_index(axis) if causal else 0
        b, h, sl, d = q.shape
        perm = [(i, (i + 1) % n) for i in range(n)]
        out = jnp.zeros((b, h, sl, d), jnp.float32)
        lse = jnp.full((b, h, sl), NEG_INF, jnp.float32)

        def step(i, carry):
            out, lse, k, v, bias = carry
            case = (_ring_flash_case(idx, jax.lax.rem(idx - i + n, n), n)
                    if causal else 0)
            o_blk, lse_blk = fwd_block(q, k, v, bias, case)
            lse_new = jnp.logaddexp(lse, lse_blk)
            # guard fully-masked rows: both weights would be exp(NEG_INF -
            # NEG_INF-ish) garbage; forcing weights to 0 keeps out at 0
            alive = lse_new > NEG_INF / 2
            w_old = jnp.where(alive, jnp.exp(lse - lse_new), 0.0)
            w_blk = jnp.where(alive, jnp.exp(lse_blk - lse_new), 0.0)
            out = out * w_old[..., None] \
                + o_blk.astype(jnp.float32) * w_blk[..., None]
            k, v, bias = _rot((k, v, bias), perm)
            return out, lse_new, k, v, bias

        out, lse, _, _, _ = jax.lax.fori_loop(
            0, n, step, (out, lse, k, v, bias))
        return out.astype(q.dtype), lse

    def vjp_fwd(q, k, v, bias):
        out, lse = _ring_flash_fwd(q, k, v, bias)
        return out, (q, k, v, bias, out, lse)

    def vjp_bwd(res, g):
        q, k, v, bias, out, lse = res
        n = _axis_size(axis)
        idx = jax.lax.axis_index(axis) if causal else 0  # see fwd note
        perm = [(i, (i + 1) % n) for i in range(n)]
        # fp32 accumulators: each ring step adds a partial; rounding to the
        # input dtype per step would degrade grads as sp grows (the
        # single-device kernel accumulates in fp32 scratch and rounds once)
        dq = jnp.zeros(q.shape, jnp.float32)
        dk = jnp.zeros(k.shape, jnp.float32)
        dv = jnp.zeros(v.shape, jnp.float32)

        def step(i, carry):
            dq, k, v, bias, dk, dv = carry
            case = (_ring_flash_case(idx, jax.lax.rem(idx - i + n, n), n)
                    if causal else 0)
            dq_blk, dk_blk, dv_blk = bwd_block(
                q, k, v, bias, out, lse, g, case)
            dq = dq + dq_blk.astype(jnp.float32)
            dk = dk + dk_blk.astype(jnp.float32)
            dv = dv + dv_blk.astype(jnp.float32)
            # grads rotate WITH their block: after n hops they are home
            k, v, bias, dk, dv = _rot((k, v, bias, dk, dv), perm)
            return dq, k, v, bias, dk, dv

        dq, _, _, _, dk, dv = jax.lax.fori_loop(
            0, n, step, (dq, k, v, bias, dk, dv))
        # key-padding bias is a constant mask (flash_attention convention;
        # ring_attention stop-gradients bias for BOTH impls)
        dbias = jnp.zeros_like(bias) if bias is not None else None
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), \
            dbias

    ring_flash_local.defvjp(vjp_fwd, vjp_bwd)
    return ring_flash_local


def ring_attention(q, k, v, *, bias=None, causal=False,
                   scale: Optional[float] = None,
                   axis: str = mesh_lib.SP, mesh: Optional[Mesh] = None,
                   impl: str = "auto"):
    """Sequence-parallel attention. q,k,v: (B,H,S,D) with S sharded over
    ``axis``; ``bias`` optional key-padding bias (B,1,1,S) sharded on S.

    ``impl``: "xla" (composed online-softmax blocks), "flash" (Pallas
    kernel per ring block — flash-level arithmetic intensity under sp>1),
    "flash_interpret" (CPU: the shared harness's lax fallback per ring
    block — same numerics, no Pallas interpreter in the traced program),
    "auto" (flash on TPU, xla elsewhere). Dispatches through the shared
    kernel registry (:mod:`paddle_tpu.kernels`); the inner flash block
    sizes resolve from the autotuner at trace time. Must run under a
    mesh (pjit/jit with mesh context). Returns (B,H,S,D) with the same
    sharding as q.

    ``bias`` is a CONSTANT mask: it is stop-gradiented on every impl (the
    flash kernels do not produce bias cotangents; stopping it on the xla
    path too keeps gradients backend-independent). Trainable attention
    biases are incompatible with sequence-parallel ring attention here.
    """
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None:
        raise ValueError("ring_attention requires a mesh "
                         "(use mesh_context or pass mesh=)")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if bias is not None:
        bias = jax.lax.stop_gradient(bias)
    legacy = {"auto": "auto", "flash": "pallas",
              "flash_interpret": "pallas_interpret", "xla": "lax"}
    if impl not in legacy:
        raise ValueError(f"unknown impl {impl!r} "
                         f"(expected {'|'.join(legacy)})")
    from paddle_tpu import kernels
    return kernels.dispatch("ring_attention", q, k, v, bias,
                            impl=legacy[impl], causal=causal, scale=scale,
                            axis=axis, mesh=mesh)


def _ring_shard_map(body, mesh, axis, with_bias, args):
    qkv_spec = P(mesh_lib.BATCH_AXES, mesh_lib.TP, axis, None)
    bias_spec = P(mesh_lib.BATCH_AXES, None, None, axis)
    in_specs = (qkv_spec,) * 3 + ((bias_spec,) if with_bias else ())
    from paddle_tpu.core.compat import shard_map
    return shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=qkv_spec,
        check_vma=False,
    )(*args)


# ---------------------------------------------------------------------------
# kernel-registry entry (paddle_tpu.kernels)
# ---------------------------------------------------------------------------

def _ring_kernel_pallas(q, k, v, bias=None, *, block_sizes, interpret,
                        causal=False, scale=None, axis=mesh_lib.SP,
                        mesh=None):
    local = _make_ring_flash(axis, scale, causal, interpret=interpret,
                             block_q=block_sizes.get("block_q", 512),
                             block_k=block_sizes.get("block_k", 512))
    if bias is not None:
        return _ring_shard_map(lambda q, k, v, b: local(q, k, v, b),
                               mesh, axis, True, (q, k, v, bias))
    return _ring_shard_map(lambda q, k, v: local(q, k, v, None),
                           mesh, axis, False, (q, k, v))


def _ring_kernel_lax(q, k, v, bias=None, *, causal=False, scale=None,
                     axis=mesh_lib.SP, mesh=None):
    if bias is not None:
        return _ring_shard_map(
            lambda q, k, v, b: _ring_attention_local(
                q, k, v, b, axis=axis, scale=scale, causal=causal),
            mesh, axis, True, (q, k, v, bias))
    return _ring_shard_map(
        lambda q, k, v: _ring_attention_local(
            q, k, v, None, axis=axis, scale=scale, causal=causal),
        mesh, axis, False, (q, k, v))


def _ring_sample_inputs(seed):
    b, h, s, d = ((2, 2, 32, 8), (2, 4, 64, 16), (2, 4, 128, 32))[seed % 3]
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return ((jax.random.normal(kq, (b, h, s, d), jnp.float32),
             jax.random.normal(kk, (b, h, s, d), jnp.float32),
             jax.random.normal(kv, (b, h, s, d), jnp.float32)),
            {"causal": True})


def _ring_tune_signature(args, kwargs):
    q = args[0]
    b, h, s, d = q.shape
    return (("bh", b * h), ("s", s), ("d", d))


def _ring_parity_fn(seed):
    """Mesh-orchestrated battery: flash_interpret (shared-harness lax
    fallback per ring block) and the composed xla impl vs the dense
    full-attention reference, on an sp=2 mesh."""
    import numpy as np
    from paddle_tpu.core.mesh import MeshConfig, make_mesh, mesh_context
    from paddle_tpu.ops.attention import scaled_dot_product_attention
    n = len(jax.devices())
    # make_mesh needs ALL n devices; the b=2 samples need dp in {1, 2}
    # and the 32..128-token seqs need a pow2 sp — pick the largest fit,
    # and skip (not crash) on counts no such mesh covers (odd boxes)
    dims = next(((dp, sp) for dp in (2, 1) for sp in (8, 4, 2)
                 if dp * sp == n), None)  # prefer batch-sharded dp=2
    if dims is None:
        return {}                     # no ring-able mesh on this box
    (q, k, v), kw = _ring_sample_inputs(seed)
    ref = np.asarray(scaled_dot_product_attention(q, k, v, **kw),
                     np.float32)
    mesh = make_mesh(MeshConfig(dp=dims[0], sp=dims[1]))
    from paddle_tpu import kernels
    contract = kernels.get("ring_attention").contract
    errs = {}
    with mesh_context(mesh):
        for impl in ("xla", "flash_interpret"):
            out = np.asarray(jax.jit(
                lambda q, k, v: ring_attention(q, k, v, mesh=mesh,
                                               impl=impl, **kw))(q, k, v),
                np.float32)
            np.testing.assert_allclose(
                out, ref, atol=contract.atol, rtol=contract.rtol,
                err_msg=f"ring_attention[{impl}] diverged from the dense "
                        "reference")
            errs[impl] = float(np.max(np.abs(out - ref)))
    return errs


def _register_ring_kernel():
    from paddle_tpu import kernels
    kernels.register(kernels.KernelSpec(
        name="ring_attention",
        contract=kernels.KernelContract(
            version=1,
            arg_layouts={"q": "(B,H,S,D) S sharded over sp",
                         "k": "(B,H,S,D) S sharded over sp",
                         "v": "(B,H,S,D) S sharded over sp",
                         "bias": "(B,1,1,S) key padding, optional"},
            out_layout="(B,H,S,D) sharded like q",
            grid="ring of sp ppermute hops; inner flash kernel per "
                 "visiting block",
            block_candidates={"block_q": (512, 256, 128),
                              "block_k": (512, 256, 128)},
            atol=2e-5, rtol=2e-5),
        pallas_fn=_ring_kernel_pallas,
        lax_fn=_ring_kernel_lax,
        reference_fn=None,            # parity_fn orchestrates the mesh
        sample_inputs=_ring_sample_inputs,
        pallas_sites=(),              # reuses the flash kernel's sites
        requires_mesh=True,
        tune_signature=_ring_tune_signature,
        vmem_estimate=None,
        parity_fn=_ring_parity_fn))


_register_ring_kernel()
