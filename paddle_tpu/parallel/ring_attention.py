"""Ring attention: sequence/context parallelism over the "sp" mesh axis.

Capability ABSENT in the reference (SURVEY.md §5.7 — fluid 1.5 predates
long-context training; its story was LoD ragged tensors + DynamicRNN). The
TPU build adds it as a first-class axis: q/k/v are sharded on the sequence
dim over "sp"; each device computes attention between its local queries and
a rotating k/v block that travels the ring via ``lax.ppermute`` (ICI
neighbor exchange), merging partial results with the flash-attention
online-softmax recurrence. Memory per device is O(S/n · S/n) per block and
the k/v transfer overlaps compute under XLA's async collectives.

Composes with GSPMD: call :func:`ring_attention` under jit with a mesh
context; the shard_map boundary converts the GSPMD-sharded (B,H,S,D)
arrays to per-device local blocks and back.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.ops.attention import NEG_INF


def _block_update(carry, kv, *, scale, causal, q_offset, k_offset, seq_q_blk):
    """One online-softmax step: fold (k,v[,bias]) block into (m, l, acc).

    q_offset/k_offset are the GLOBAL start positions of the local q block
    and the visiting k block (traced ints ok) — used for causal masking.
    """
    m_prev, l_prev, acc = carry
    q, k, v, bias = kv
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(s.dtype)
    if causal:
        blk_k = k.shape[2]
        row = q_offset + jax.lax.broadcasted_iota(
            jnp.int32, (seq_q_blk, blk_k), 0)
        col = k_offset + jax.lax.broadcasted_iota(
            jnp.int32, (seq_q_blk, blk_k), 1)
        s = jnp.where(col <= row, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_next = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_next)
    p = jnp.exp(s - m_next)
    l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    acc_next = acc * alpha + pv
    return m_next, l_next, acc_next


def _ring_attention_local(q, k, v, bias, *, axis, scale, causal):
    """Per-device body (inside shard_map). q,k,v local: (B,H,Sl,D)."""
    n = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    b, h, sl, d = q.shape
    q32 = q.astype(jnp.float32)

    m = jnp.full((b, h, sl, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sl, 1), jnp.float32)
    acc = jnp.zeros((b, h, sl, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        m, l, acc, k, v, bias = carry
        # block currently held arrived from (idx - i) mod n
        src = jax.lax.rem(idx - i + n, n)
        m, l, acc = _block_update(
            (m, l, acc),
            (q32, k.astype(jnp.float32), v, bias),
            scale=scale, causal=causal,
            q_offset=idx * sl, k_offset=src * sl, seq_q_blk=sl)
        k = jax.lax.ppermute(k, axis, perm)
        v = jax.lax.ppermute(v, axis, perm)
        if bias is not None:
            bias = jax.lax.ppermute(bias, axis, perm)
        return m, l, acc, k, v, bias

    if bias is None:
        # keep the carry pytree static: loop without a bias leaf
        def step_nb(i, carry):
            m, l, acc, k, v = carry
            m, l, acc, k2, v2, _ = step(i, (m, l, acc, k, v, None))
            return m, l, acc, k2, v2
        m, l, acc, _, _ = jax.lax.fori_loop(0, n, step_nb, (m, l, acc, k, v))
    else:
        m, l, acc, _, _, _ = jax.lax.fori_loop(0, n, step,
                                               (m, l, acc, k, v, bias))
    denom = jnp.where(l == 0.0, 1.0, l)
    return (acc / denom).astype(q.dtype)


def ring_attention(q, k, v, *, bias=None, causal=False,
                   scale: Optional[float] = None,
                   axis: str = mesh_lib.SP, mesh: Optional[Mesh] = None):
    """Sequence-parallel attention. q,k,v: (B,H,S,D) with S sharded over
    ``axis``; ``bias`` optional key-padding bias (B,1,1,S) sharded on S.

    Must run under a mesh (pjit/jit with mesh context). Returns (B,H,S,D)
    with the same sharding as q.
    """
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None:
        raise ValueError("ring_attention requires a mesh "
                         "(use mesh_context or pass mesh=)")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    qkv_spec = P(mesh_lib.BATCH_AXES, mesh_lib.TP, axis, None)
    bias_spec = P(mesh_lib.BATCH_AXES, None, None, axis)
    in_specs = (qkv_spec, qkv_spec, qkv_spec)
    args = (q, k, v)
    if bias is not None:
        in_specs = in_specs + (bias_spec,)
        args = args + (bias,)

        def body(q, k, v, bias):
            return _ring_attention_local(q, k, v, bias, axis=axis,
                                         scale=scale, causal=causal)
    else:
        def body(q, k, v):
            return _ring_attention_local(q, k, v, None, axis=axis,
                                         scale=scale, causal=causal)

    return jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=qkv_spec,
        check_vma=False,
    )(*args)
