"""Preemption handling: drain the step, snapshot, exit with a known code.

TPU slices are preemptible resources: the scheduler delivers SIGTERM and
reclaims the hosts shortly after. The reference stack has no in-process
story for this (SURVEY.md §5.3 — death is detected by the pserver-side
monitor after the fact); here the signal becomes a clean shutdown:

1. :class:`PreemptionGuard` installs a SIGTERM handler that only sets a
   flag — signal-handler-safe, no IO, no jax calls.
2. The training loop (``Trainer.fit`` / ``Executor.train_from_dataset``)
   checks the flag once per step, so the in-flight step DRAINS — XLA's
   async dispatch completes and the state is consistent.
3. The loop takes an emergency snapshot (forced, synchronous) and calls
   :meth:`PreemptionGuard.exit`, which raises ``SystemExit`` with
   :data:`EXIT_PREEMPTED`.

``EXIT_PREEMPTED`` is deliberately NOT 143 (the shell's 128+SIGTERM code
for an unhandled kill): the launcher can tell "drained and snapshotted,
restart me cheaply" from "died rudely, state is whatever the last
periodic checkpoint says". ``fleet.ElasticCoordinator`` treats it as a
free restart that does not consume the crash budget.
"""

from __future__ import annotations

import signal
import threading
from typing import Callable, Iterable, Optional

from paddle_tpu import observability

# 64+19 is arbitrary but stable: outside the shell's 128+N signal band and
# distinct from every exit code the launcher/tests already use (0..9).
EXIT_PREEMPTED = 83

# Voluntary scale-in drain (the serving fleet's autoscaler shrinking the
# fleet): the worker migrated its in-flight state to peers and exited on
# purpose. Distinct from EXIT_PREEMPTED — a preempted worker WANTS a
# respawn (the platform took its slice), a drained worker must NOT be
# respawned (the fleet chose fewer replicas). ``fleet.ElasticCoordinator``
# retires a drained rank as done, consuming no respawn budget.
EXIT_DRAINED = 84


class PreemptionGuard:
    """Flag-setting signal trap with an explicit drain protocol.

    ``install=True`` hooks the given signals (default SIGTERM) when
    running on the main thread; elsewhere — or in tests — call
    :meth:`trigger` directly (``faults.simulate_preemption``). The
    previous handlers are preserved and restored by :meth:`uninstall`.
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,), *,
                 install: bool = True,
                 log_fn: Callable[[str], None] = print):
        self._flag = threading.Event()
        self._log = log_fn
        self._previous = {}
        self.signals = tuple(signals)
        if install:
            self.install()

    def install(self):
        for sig in self.signals:
            try:
                self._previous[sig] = signal.signal(sig, self._handler)
            except ValueError:
                # not the main thread: signal delivery is the launcher's
                # problem, manual trigger() still works
                self._log("[preempt] cannot install handler off the main "
                          "thread; rely on trigger()")
                return

    def uninstall(self):
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()

    def _handler(self, signum, frame):
        self.trigger(signum)

    def trigger(self, signum: Optional[int] = None):
        """Mark preemption requested. STRICTLY flag-only: this runs inside
        a signal handler on the main thread, which may already hold the
        (non-reentrant) observability registry locks mid-step — touching
        any lock here could deadlock the very thread that must drain.
        Metrics are recorded at the drain site (:meth:`exit`) instead."""
        self._flag.set()

    @property
    def triggered(self) -> bool:
        return self._flag.is_set()

    def exit(self, code: int = EXIT_PREEMPTED):
        """Leave the process with the launcher-visible preemption code."""
        observability.counter(
            "resilience_preemptions_total",
            "preemptions drained to a snapshot + clean exit").inc()
        self._log(f"[preempt] drained and snapshotted; exiting {code}")
        raise SystemExit(code)
