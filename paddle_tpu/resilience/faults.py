"""Deterministic fault injection for the resilience test suite.

The round-5 verdict's failures (silent bench death, tunnel drops, torn
tooling) all happened OUTSIDE any test's reach — nothing in the repo
could provoke a mid-write kill or a flaky filesystem on demand. These
wrappers make those failures reproducible unit-test inputs:

- :class:`TornWriteFS` — a filesystem whose process "dies" after writing
  N bytes: the write raises, and EVERY subsequent operation fails (a dead
  host does not come back to rename its manifest). Models kill -9 /
  preemption mid-save byte-exactly.
- :class:`FlakyFS` — the first K calls of selected operations raise
  ``IOError`` (transient NFS/HDFS hiccups), then the filesystem heals.
  Drives the retry/backoff path deterministically.
- :func:`corrupt_file` — flip a byte mid-file (bit rot / truncated
  upload) to exercise hash verification on restore.
- :func:`simulate_preemption` — trip a :class:`PreemptionGuard` exactly
  the way the real SIGTERM handler does (or deliver a real signal).

All wrappers delegate unknown attributes to the wrapped fs, so they slot
anywhere a :class:`paddle_tpu.fs.LocalFS`/``HDFSClient`` goes.
"""

from __future__ import annotations

import os
import signal
from typing import Iterable, Optional


class FaultInjected(IOError):
    """Raised by injected faults (subclasses IOError: retryable)."""


class HostDead(FaultInjected):
    """Any fs operation attempted after the simulated kill point."""


class _TornWriter:
    """File object that 'loses the host' after a byte budget: the prefix
    that fits is written (and flushed — it really lands on disk, exactly
    like a torn page), then :class:`FaultInjected` fires."""

    def __init__(self, f, fs: "TornWriteFS"):
        self._f = f
        self._fs = fs

    def write(self, data: bytes):
        fs = self._fs
        if fs.dead:
            raise HostDead("write after simulated kill")
        room = fs.kill_after_bytes - fs.bytes_written
        if len(data) > room:
            self._f.write(data[:max(0, room)])
            self._f.flush()
            fs.bytes_written = fs.kill_after_bytes
            fs.dead = True
            raise FaultInjected(
                f"simulated kill after {fs.kill_after_bytes} bytes")
        fs.bytes_written += len(data)
        return self._f.write(data)

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class TornWriteFS:
    """Kill-after-N-bytes filesystem wrapper (the mid-save host crash)."""

    _GUARDED = ("open_write", "rename", "upload", "touch", "mkdirs",
                "delete")

    def __init__(self, inner, kill_after_bytes: int):
        self.inner = inner
        self.kill_after_bytes = int(kill_after_bytes)
        self.bytes_written = 0
        self.dead = False

    def _check(self):
        if self.dead:
            raise HostDead("fs operation after simulated kill")

    def open_write(self, path: str):
        self._check()
        return _TornWriter(self.inner.open_write(path), self)

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if name in self._GUARDED and callable(attr):
            def guarded(*a, **kw):
                self._check()
                return attr(*a, **kw)
            return guarded
        return attr


class FlakyFS:
    """First ``fail_times`` calls of ``ops`` raise IOError, then heal."""

    def __init__(self, inner, fail_times: int,
                 ops: Iterable[str] = ("open_write", "rename", "upload")):
        self.inner = inner
        self.fail_times = int(fail_times)
        self.failures_injected = 0
        self.ops = tuple(ops)

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if name in self.ops and callable(attr):
            def flaky(*a, **kw):
                if self.failures_injected < self.fail_times:
                    self.failures_injected += 1
                    raise FaultInjected(
                        f"injected transient failure #"
                        f"{self.failures_injected} in {name}")
                return attr(*a, **kw)
            return flaky
        return attr


def corrupt_file(path: str, *, offset: Optional[int] = None):
    """Flip one byte of ``path`` in place (default: the middle)."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot corrupt empty file {path}")
    pos = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))
        f.flush()
        os.fsync(f.fileno())


def simulate_preemption(guard=None, *, real_signal: bool = False):
    """Trip preemption: through ``guard.trigger()`` (deterministic, any
    thread) or by delivering a real SIGTERM to this process."""
    if real_signal:
        os.kill(os.getpid(), signal.SIGTERM)
        return
    if guard is None:
        raise ValueError("pass a PreemptionGuard or real_signal=True")
    guard.trigger(signal.SIGTERM)
