"""Resilience subsystem: survive preemption, host crashes, torn saves.

The reference stack's fault tolerance is "restart from checkpoint"
(SURVEY.md §5.3) with a synchronous whole-tree save — a single host
failure or preemption MID-SAVE can leave the run unrestorable. This
package makes durable, restartable state a first-class subsystem, built
TPU-native on jax sharded arrays:

- :mod:`~paddle_tpu.resilience.snapshot` — async per-host **sharded**
  snapshots (each host writes only its addressable shards, background
  thread, double-buffered host copy) with a two-phase **atomic manifest
  commit**: per-shard sha256 hashes, fsync, then rename. A torn save is
  never restorable; restore verifies integrity before loading and falls
  back past corrupt saves.
- :mod:`~paddle_tpu.resilience.preempt` — SIGTERM/preemption guard that
  drains the current step, takes an emergency snapshot, and exits with
  :data:`~paddle_tpu.resilience.preempt.EXIT_PREEMPTED` so the launcher
  restarts without burning its crash budget.
- :mod:`~paddle_tpu.resilience.retry` — bounded exponential backoff with
  jitter + deadline for fs/HDFS traffic and manifest barriers, metered
  as ``resilience_retries_total``.
- :mod:`~paddle_tpu.resilience.faults` — deterministic fault injection
  (kill-after-N-bytes writes, flaky fs, simulated preemption) so every
  recovery path above is provable in CPU-only unit tests.

Wired through ``Trainer`` (auto-resume from the newest VALID manifest),
``Executor.train_from_dataset``, ``fleet`` (resume-step agreement +
preemption-aware ElasticCoordinator) and ``io.CheckpointManager`` (now a
thin facade over :class:`SnapshotEngine`).
"""

from paddle_tpu.resilience.faults import (FaultInjected, FlakyFS, HostDead,
                                          TornWriteFS, corrupt_file,
                                          simulate_preemption)
from paddle_tpu.resilience.preempt import (EXIT_DRAINED, EXIT_PREEMPTED,
                                           PreemptionGuard)
from paddle_tpu.resilience.retry import (RetryPolicy, retry_call, retrying)
from paddle_tpu.resilience.snapshot import (SnapshotCorruptionError,
                                            SnapshotEngine, SnapshotError,
                                            flatten_tree, unflatten_tree)

__all__ = [
    "EXIT_DRAINED", "EXIT_PREEMPTED", "FaultInjected", "FlakyFS", "HostDead",
    "PreemptionGuard", "RetryPolicy", "SnapshotCorruptionError",
    "SnapshotEngine", "SnapshotError", "TornWriteFS", "corrupt_file",
    "flatten_tree", "retry_call", "retrying", "simulate_preemption",
    "unflatten_tree",
]
