"""Bounded retry with exponential backoff, jitter and a hard deadline.

Reference mapping (SURVEY.md §5.3): the reference's fault story wraps
HDFS/gRPC calls in ad-hoc shell retries (``fs.cc`` retry loops, fleet
``hdfs.py`` re-running ``hadoop fs``); here retry is ONE policy object +
ONE driver used by the snapshot engine (shard uploads, manifest merge
polling) and anything else that talks to a flaky medium.

Design points:
- backoff = ``base * multiplier**(attempt-1)`` clamped to ``max_delay_s``,
  multiplied by a ±``jitter`` fraction so a fleet of hosts retrying the
  same dead NFS server doesn't thundering-herd it on a synchronized clock.
- the ``deadline_s`` budget is wall-clock from the FIRST attempt; when the
  next sleep would land past it, the ORIGINAL exception is re-raised —
  callers see the real failure, not a retry-framework wrapper.
- every retry bumps the ``resilience_retries_total`` counter (labelled by
  ``op``) so a run that is quietly limping on a sick filesystem is visible
  in the observability exposition long before it dies; every GIVE-UP —
  attempt budget spent or deadline crossed — bumps
  ``resilience_retry_exhausted_total{op}``, so a limping-then-dead
  dependency is distinguishable from a merely limping one.
- fully injectable (``sleep``, ``clock``, ``rng``) — the fault-injection
  suite drives it deterministically with zero real sleeping.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional, Tuple, Type

from paddle_tpu import observability

RETRYABLE_DEFAULT: Tuple[Type[BaseException], ...] = (
    IOError, OSError, TimeoutError)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How hard to try: attempts, backoff shape, deadline, what to catch."""

    max_attempts: int = 5
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25           # ± fraction of the computed delay
    deadline_s: float = 60.0       # wall-clock budget across ALL attempts
    retry_on: Tuple[Type[BaseException], ...] = RETRYABLE_DEFAULT

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        d = min(self.max_delay_s,
                self.base_delay_s * (self.multiplier ** (attempt - 1)))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, d)


def retry_call(fn: Callable, *args,
               policy: Optional[RetryPolicy] = None,
               op: str = "call",
               rng: Optional[random.Random] = None,
               sleep: Callable[[float], None] = time.sleep,
               clock: Callable[[], float] = time.monotonic,
               **kwargs):
    """Run ``fn(*args, **kwargs)``, retrying ``policy.retry_on`` failures.

    Gives up — re-raising the ORIGINAL exception — when either
    ``max_attempts`` is spent or the next backoff would cross
    ``deadline_s``. Non-retryable exceptions propagate immediately.
    """
    policy = policy or RetryPolicy()
    rng = rng or random.Random()
    start = clock()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            # exhaustion is its own signal: retries_total alone cannot
            # distinguish a limping dependency from a limping-then-DEAD
            # one — the give-up counter is what alerts page on
            if attempt >= policy.max_attempts:
                observability.counter(
                    "resilience_retry_exhausted_total",
                    "retry give-ups (attempt budget or deadline spent)"
                ).inc(op=op)
                raise
            delay = policy.delay(attempt, rng)
            if clock() + delay - start > policy.deadline_s:
                observability.counter(
                    "resilience_retry_exhausted_total",
                    "retry give-ups (attempt budget or deadline spent)"
                ).inc(op=op)
                raise  # the original error, not a deadline wrapper
            observability.counter(
                "resilience_retries_total",
                "transient failures absorbed by resilience.retry").inc(op=op)
            sleep(delay)


def retrying(policy: Optional[RetryPolicy] = None, op: str = "call",
             **driver_kwargs):
    """Decorator form of :func:`retry_call`."""
    def wrap(fn):
        def inner(*args, **kwargs):
            return retry_call(fn, *args, policy=policy, op=op,
                              **driver_kwargs, **kwargs)
        inner.__name__ = getattr(fn, "__name__", "retrying")
        inner.__doc__ = fn.__doc__
        return inner
    return wrap
