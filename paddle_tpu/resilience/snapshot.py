"""Async, per-host sharded snapshots with an atomic manifest commit.

The durable-state half of the resilience subsystem (SURVEY.md §5.3: the
reference's whole fault-tolerance story is restart-from-checkpoint, via
synchronous whole-tree ``save_persistables`` + checkpoint_notify; large
systems — TensorFlow OSDI'16 in PAPERS.md — make this a subsystem).

Write path (``SnapshotEngine.save``):

1. **Host copy, synchronously** (the double buffer): every jax array leaf
   is reduced to its *addressable* shards — each host copies out only the
   slices it owns (``Array.addressable_shards``), deduplicated by shard
   index, so an FSDP-sharded param tree costs 1/H of its bytes per host.
   The caller may mutate/donate the state the moment ``save`` returns.
2. **Background write**: one worker thread serializes and writes
   ``shards_pNNNNN.pkl`` through the injected fs (local/HDFS/fault
   wrapper), fsyncs, then writes a ``commit_pNNNNN.json`` with the file's
   content hash. At most ONE save is in flight and ONE pending (the
   second buffer); a third ``save`` blocks — backpressure, not unbounded
   host memory.
3. **Two-phase manifest commit** (process 0): wait (with retry/deadline)
   for every host's commit record, merge them into ``manifest.json.tmp``
   — per-shard-file sha256 + sizes + the flat tree schema — fsync, then
   atomically ``rename`` to ``manifest.json``. A save killed at ANY
   earlier point leaves no manifest: the step directory is garbage, never
   a lie.

Read path: ``latest_valid_manifest`` scans step dirs newest-first and
returns the first whose manifest parses AND whose shard files all match
their recorded hashes — a torn or bit-rotted save is skipped, falling
back to the previous good one. ``restore`` re-verifies hashes before
unpickling and refuses a corrupted shard (``SnapshotCorruptionError``).

Emits ``resilience_snapshot_seconds`` / ``resilience_restore_seconds``
histograms and ``resilience_snapshots_total`` counters.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu import fs as fs_lib
from paddle_tpu import observability
from paddle_tpu.analysis.concurrency import guarded_by
from paddle_tpu.resilience.retry import RetryPolicy, retry_call

MANIFEST = "manifest.json"
MANIFEST_TMP = MANIFEST + ".tmp"
FORMAT_VERSION = 1
_CHUNK = 1 << 16

# marker KEY for empty dict nodes (same contract as io._flatten: structure
# must survive the round trip or pjit sharding prefixes break on resume)
_EMPTY_KEY = "\x00empty"


class SnapshotError(IOError):
    """Base class for snapshot failures."""


class SnapshotCorruptionError(SnapshotError):
    """A shard file does not match the hash its manifest recorded."""


# -- pytree <-> flat dict ----------------------------------------------------

def flatten_tree(tree, prefix=()) -> Dict[str, Any]:
    if isinstance(tree, dict):
        if not tree:
            return {"/".join(prefix + (_EMPTY_KEY,)): np.int8(0)}
        out = {}
        for k in sorted(tree):
            if not isinstance(k, str):
                # str(k) would save fine but unflatten as a STR key — a
                # silent structure change the target check cannot see
                # (it str()s the target the same way). Refuse loudly.
                raise TypeError(
                    f"snapshot state dict keys must be str, got "
                    f"{type(k).__name__} key {k!r} at "
                    f"{'/'.join(prefix) or '<root>'}")
            out.update(flatten_tree(tree[k], prefix + (k,)))
        return out
    if isinstance(tree, (list, tuple)):
        # np.array would silently STACK same-shaped entries into one array
        # and restore() would hand the stack back where the container was
        # — corrupt state instead of a checkpoint. Refuse loudly.
        raise TypeError(
            f"snapshot state trees must be dicts with array leaves; got a "
            f"{type(tree).__name__} container at "
            f"{'/'.join(prefix) or '<root>'} — convert it to a dict "
            "(e.g. {'0': ..., '1': ...}) before checkpointing")
    return {"/".join(prefix): tree}


def unflatten_tree(flat: Dict[str, Any]):
    tree: dict = {}
    for key, val in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        if parts[-1] == _EMPTY_KEY:
            continue  # the walk above materialized the empty dict
        node[parts[-1]] = val
    return tree


# -- shard extraction --------------------------------------------------------

def _norm_index(index, shape) -> Tuple[Tuple[int, int], ...]:
    """Normalize a shard's tuple-of-slices to ((start, stop), ...)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _host_shards(leaf) -> Tuple[Tuple[int, ...], List[Tuple[tuple, np.ndarray]]]:
    """(global_shape, [(index, host_copy), ...]) for one leaf — only the
    shards THIS process can address, deduplicated by index (a replicated
    axis otherwise writes the same bytes once per local device)."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards:
        shape = tuple(leaf.shape)
        out, seen = [], set()
        for s in shards:
            idx = _norm_index(s.index, shape)
            if idx in seen:
                continue
            seen.add(idx)
            out.append((idx, np.asarray(s.data)))
        return shape, out
    a = np.array(leaf, copy=True)   # double-buffer guarantee for np leaves
    return tuple(a.shape), [(tuple((0, d) for d in a.shape), a)]


def _fsync(f):
    f.flush()
    try:
        os.fsync(f.fileno())
    except (AttributeError, OSError, ValueError):
        pass  # fs wrappers / non-file objects: flush is the best we have


def _write_bytes(fs, path: str, payload: bytes):
    """Chunked write + fsync so a mid-write kill tears at a real offset."""
    f = fs.open_write(path)
    try:
        for off in range(0, len(payload), _CHUNK):
            f.write(payload[off:off + _CHUNK])
        _fsync(f)
    finally:
        f.close()


def _shard_file(process: int) -> str:
    return f"shards_p{process:05d}.pkl"


def _commit_file(process: int) -> str:
    return f"commit_p{process:05d}.json"


def _step_dirname(step: int) -> str:
    return f"step_{int(step):010d}"


def _parse_step(name: str) -> Optional[int]:
    if not name.startswith("step_"):
        return None
    try:
        return int(name[len("step_"):])
    except ValueError:
        return None


@guarded_by("_err_lock", "_error")
class SnapshotEngine:
    """Sharded, async, atomically-committed checkpoints under ``directory``.

    ``fs`` defaults to scheme routing (:func:`paddle_tpu.fs.get_fs` —
    local or HDFS); the fault-injection suite passes wrapped filesystems.
    ``process_index``/``process_count`` default to the jax runtime; the
    directory must be shared across hosts (NFS/HDFS) for multi-host runs.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 fs=None, retry: Optional[RetryPolicy] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 manifest_wait_s: float = 300.0):
        if fs is None:
            fs, directory = fs_lib.get_fs(directory)
        else:
            if directory.startswith("file://"):
                directory = directory[len("file://"):]
        self.fs = fs
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.retry = retry or RetryPolicy(max_attempts=3, base_delay_s=0.1,
                                          deadline_s=manifest_wait_s)
        self.manifest_wait_s = manifest_wait_s
        if process_index is None or process_count is None:
            import jax
            process_index = jax.process_index()
            process_count = jax.process_count()
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.fs.mkdirs(self.directory)
        # writer-thread failure handoff: the worker sets it, the next
        # save()/wait() read-and-clears it — two threads, so the pair
        # of operations goes through _err_lock (a bare read-then-clear
        # can drop an error that lands between the two statements)
        self._err_lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._worker = threading.Thread(
            target=self._drain, name="snapshot-writer", daemon=True)
        self._worker.start()
        self._closed = False

    # -- write side ---------------------------------------------------------
    def save(self, step: int, state: Any, *, wait: bool = False):
        """Snapshot ``state`` at ``step``. Returns once the host copy is
        taken (double buffer) — the write happens on the worker thread;
        ``wait=True`` blocks until the manifest is committed. A failure in
        a previous background save is re-raised here (or in ``wait``)."""
        self._raise_pending()
        t0 = time.perf_counter()
        flat = flatten_tree(state)
        leaves = {}
        for key, leaf in flat.items():
            shape, shards = _host_shards(leaf)
            leaves[key] = {"shape": shape, "shards": shards}
        blocking_s = time.perf_counter() - t0
        observability.histogram(
            "resilience_snapshot_blocking_seconds",
            "host-copy time save() spends on the caller's thread").observe(
                blocking_s)
        # a completed span on the CALLER's thread; the queue carries it
        # so the writer thread's snapshot.write span parents to it —
        # cross-thread parentage ties one save's host copy and its
        # background write into a single trace
        tracer = observability.tracing.default()
        span = None
        if tracer.enabled:
            span = tracer.record_span("snapshot.save_blocking",
                                      duration_s=blocking_s, step=step)
        # blocks when one save is already pending behind the in-flight one:
        # bounded memory, the caller feels backpressure instead of OOM
        self._queue.put((int(step), leaves, t0, span))
        if wait:
            self.wait_until_finished()

    def _drain(self):
        tracer = observability.tracing.default()
        while True:
            job = self._queue.get()
            try:
                if job is None:
                    return
                step, leaves, t0, parent = job
                tw0 = tracer.now()
                self._write_snapshot(step, leaves)
                if tracer.enabled:
                    tracer.record_span("snapshot.write", start=tw0,
                                       parent=parent, step=step)
                observability.histogram(
                    "resilience_snapshot_seconds",
                    "save() start to manifest commit").observe(
                        time.perf_counter() - t0)
                observability.counter(
                    "resilience_snapshots_total",
                    "successfully committed snapshots").inc()
            except BaseException as e:  # surfaced on next save()/wait()
                with self._err_lock:
                    self._error = e
            finally:
                self._queue.task_done()

    def _write_snapshot(self, step: int, leaves: Dict[str, dict]):
        sdir = self._step_dir(step)
        if self.fs.is_exist(os.path.join(sdir, MANIFEST)):
            # step already committed: snapshots are immutable once their
            # manifest exists, so a re-save (e.g. the emergency snapshot
            # landing on the same step a periodic save just wrote) is a
            # no-op. Deleting + rewriting here would race other hosts'
            # in-flight writes for this step and destroy a good snapshot.
            observability.counter(
                "resilience_snapshot_already_committed_total",
                "saves skipped because the step was already committed"
            ).inc()
            return
        self.fs.mkdirs(sdir)
        payload = pickle.dumps(
            {"format": FORMAT_VERSION, "process": self.process_index,
             "leaves": leaves},
            protocol=pickle.HIGHEST_PROTOCOL)
        digest = hashlib.sha256(payload).hexdigest()
        fname = _shard_file(self.process_index)
        retry_call(_write_bytes, self.fs, os.path.join(sdir, fname),
                   payload, policy=self.retry, op="shard_write")
        commit = {"file": fname, "sha256": digest, "bytes": len(payload),
                  "process": self.process_index}
        retry_call(_write_bytes, self.fs,
                   os.path.join(sdir, _commit_file(self.process_index)),
                   json.dumps(commit).encode(),
                   policy=self.retry, op="commit_write")
        if self.process_index == 0:
            self._commit_manifest(step, sdir, leaves)
            self._gc()

    def _commit_manifest(self, step: int, sdir: str, leaves: Dict[str, dict]):
        """Phase two: merge every host's commit record, write tmp, fsync,
        rename. Only an intact rename makes the snapshot visible."""
        files = {}
        deadline = time.monotonic() + self.manifest_wait_s
        for p in range(self.process_count):
            cpath = os.path.join(sdir, _commit_file(p))
            while True:
                if self.fs.is_exist(cpath):
                    with self.fs.open_read(cpath) as f:
                        rec = json.loads(f.read().decode())
                    files[rec["file"]] = {"sha256": rec["sha256"],
                                          "bytes": rec["bytes"]}
                    break
                if time.monotonic() > deadline:
                    raise SnapshotError(
                        f"host {p} never committed its shards for step "
                        f"{step} (waited {self.manifest_wait_s}s)")
                time.sleep(0.02)
        manifest = {
            "format": FORMAT_VERSION,
            "step": int(step),
            "process_count": self.process_count,
            "files": files,
            "tree": {k: {"shape": list(v["shape"])} for k, v in
                     sorted(leaves.items())},
            "created_unix": time.time(),
        }
        tmp = os.path.join(sdir, MANIFEST_TMP)
        retry_call(_write_bytes, self.fs, tmp,
                   json.dumps(manifest, indent=1).encode(),
                   policy=self.retry, op="manifest_write")
        self.fs.rename(tmp, os.path.join(sdir, MANIFEST))

    def _gc(self):
        """Keep the newest ``max_to_keep`` committed snapshots; also sweep
        uncommitted (torn) step dirs strictly OLDER than the newest
        committed one (a torn dir newer than it may be another host's
        in-flight save — keep it).

        "Committed" here means the manifest FILE exists — no hash pass:
        GC runs after every background save, and re-reading every byte of
        every kept snapshot per save (what ``all_steps`` does) is exactly
        the IO the async design avoids. Integrity is the READ path's job;
        a corrupt-but-committed snapshot ages out like a good one."""
        committed = self._committed_steps()
        if not committed:
            return
        newest = committed[-1]
        for s in committed[:-self.max_to_keep] if self.max_to_keep else []:
            self.fs.delete(self._step_dir(s))
        dirs, _ = self.fs.ls_dir(self.directory)
        for name in dirs:
            s = _parse_step(name)
            if s is not None and s < newest and s not in committed:
                self.fs.delete(os.path.join(self.directory, name))

    # -- read side ----------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, _step_dirname(step))

    def _candidate_steps(self) -> List[int]:
        dirs, _ = self.fs.ls_dir(self.directory)
        steps = [s for s in (_parse_step(d) for d in dirs) if s is not None]
        return sorted(steps)

    def _committed_steps(self) -> List[int]:
        """Steps whose manifest FILE exists, ascending — a cheap existence
        scan, NO hash verification (use for gating/GC, not for restore)."""
        return [s for s in self._candidate_steps()
                if self.fs.is_exist(os.path.join(self._step_dir(s),
                                                 MANIFEST))]

    def _load_manifest(self, step: int) -> dict:
        """Parse + hash-verify one step's manifest; raises on any defect."""
        sdir = self._step_dir(step)
        mpath = os.path.join(sdir, MANIFEST)
        if not self.fs.is_exist(mpath):
            raise SnapshotError(f"no manifest for step {step} (torn save?)")
        with self.fs.open_read(mpath) as f:
            manifest = json.loads(f.read().decode())
        if manifest.get("format") != FORMAT_VERSION:
            raise SnapshotError(
                f"manifest format {manifest.get('format')!r} != "
                f"{FORMAT_VERSION}")
        for fname, meta in manifest["files"].items():
            fpath = os.path.join(sdir, fname)
            if not self.fs.is_exist(fpath):
                raise SnapshotCorruptionError(
                    f"step {step}: shard file {fname} is missing")
            h = hashlib.sha256()
            n = 0
            with self.fs.open_read(fpath) as f:
                while True:
                    chunk = f.read(_CHUNK)
                    if not chunk:
                        break
                    h.update(chunk)
                    n += len(chunk)
            if n != meta["bytes"] or h.hexdigest() != meta["sha256"]:
                raise SnapshotCorruptionError(
                    f"step {step}: shard file {fname} fails verification "
                    f"(got {n}B/{h.hexdigest()[:12]}, manifest says "
                    f"{meta['bytes']}B/{meta['sha256'][:12]})")
        return manifest

    def latest_valid_manifest(self) -> Optional[dict]:
        """Newest manifest that parses AND verifies, skipping past torn or
        corrupted saves. None when no restorable snapshot exists."""
        for step in reversed(self._candidate_steps()):
            try:
                return self._load_manifest(step)
            except SnapshotError:
                observability.counter(
                    "resilience_invalid_snapshots_total",
                    "snapshots skipped as torn/corrupt during scan").inc()
        return None

    def all_steps(self) -> List[int]:
        """Steps with a valid (verified) manifest, ascending."""
        out = []
        for step in self._candidate_steps():
            try:
                self._load_manifest(step)
                out.append(step)
            except SnapshotError:
                pass
        return out

    def latest_step(self, *, verify: bool = True) -> Optional[int]:
        """Newest restorable step. ``verify=True`` hash-checks (what a
        resume decision needs); ``verify=False`` is a cheap committed-
        manifest scan for gating/bookkeeping on hot paths."""
        if not verify:
            committed = self._committed_steps()
            return committed[-1] if committed else None
        m = self.latest_valid_manifest()
        return None if m is None else int(m["step"])

    def restore(self, step: Optional[int] = None, *, target: Any = None,
                shardings: Any = None):
        """Load a snapshot. ``step=None`` takes the newest valid one
        (falling back past corrupt saves); an explicit ``step`` is
        verified and REFUSED if corrupted. With ``target``, key/shape
        agreement is enforced first.

        Without ``shardings``: host-numpy pytree, every leaf assembled
        to its FULL global shape (fine for models that fit in host RAM).

        With ``shardings`` (a pytree of ``jax.sharding.Sharding`` leaves
        mirroring the state): the SHARDED restore path — each leaf is
        materialized only as the shard regions this host's addressable
        devices need, placed straight onto them, and stitched into a
        global ``jax.Array`` via ``make_array_from_single_device_arrays``
        — no full-tree host assembly, so a model that only fits in RAM
        when sharded restores at ~1/H bytes per host (the read-path twin
        of the 1/H write path). Leaves whose sharding entry is None fall
        back to full host assembly. ``resilience_restore_max_region_bytes``
        gauges the largest single host allocation either path made."""
        t0 = time.perf_counter()
        if step is None:
            manifest = self.latest_valid_manifest()
            if manifest is None:
                return None
            step = int(manifest["step"])
        else:
            manifest = self._load_manifest(step)  # raises on corruption
        sdir = self._step_dir(step)
        shapes = {k: tuple(v["shape"])
                  for k, v in manifest["tree"].items()}
        if target is not None:
            self._check_target(target, shapes)
        flat_sh: Dict[str, Any] = {}
        if shardings is not None:
            flat_sh = {k: v for k, v in flatten_tree(shardings).items()
                       if hasattr(v, "addressable_devices")}
        # required regions per leaf: {key: {region_idx: [devices]}}
        # (no shardings => one full-shape region, no devices)
        needed: Dict[str, Dict[tuple, list]] = {}
        for key, shape in shapes.items():
            sh = flat_sh.get(key)
            if sh is None:
                full = tuple((0, d) for d in shape)
                needed[key] = {full: []}
            else:
                regions: Dict[tuple, list] = {}
                imap = sh.addressable_devices_indices_map(shape)
                for dev, idx in imap.items():
                    regions.setdefault(_norm_index(idx, shape),
                                       []).append(dev)
                needed[key] = regions
        # stream shard files once, copying only intersecting slices into
        # lazily-allocated region buffers
        bufs: Dict[Tuple[str, tuple], np.ndarray] = {}
        max_region = 0
        for fname in manifest["files"]:
            with self.fs.open_read(os.path.join(sdir, fname)) as f:
                part = pickle.loads(f.read())
            for key, rec in part["leaves"].items():
                for region in needed.get(key, ()):
                    for idx, data in rec["shards"]:
                        buf = bufs.get((key, region))
                        if buf is None:
                            if idx == region:
                                # stored slice IS the region: alias it,
                                # no allocation or copy
                                bufs[(key, region)] = data
                                max_region = max(max_region, data.nbytes)
                                continue
                            rshape = tuple(b - a for a, b in region)
                            buf = np.empty(rshape, dtype=data.dtype)
                            bufs[(key, region)] = buf
                            max_region = max(max_region, buf.nbytes)
                        elif not buf.flags.writeable:
                            # aliased pickle-backed arrays are read-only
                            if idx == region:
                                continue     # duplicate full replica
                            buf = bufs[(key, region)] = np.array(buf)
                        _copy_overlap(buf, region, idx, data)
        flat = {}
        for key, regions in needed.items():
            sh = flat_sh.get(key)
            if sh is None:
                (region,) = regions
                flat[key] = bufs[(key, region)]
                continue
            import jax
            pieces = []
            for region, devs in regions.items():
                buf = bufs[(key, region)]
                pieces.extend(jax.device_put(buf, d) for d in devs)
            flat[key] = jax.make_array_from_single_device_arrays(
                shapes[key], sh, pieces)
        tree = unflatten_tree(flat)
        observability.gauge(
            "resilience_restore_max_region_bytes",
            "largest single host allocation the last restore made"
        ).set(float(max_region))
        restore_s = time.perf_counter() - t0
        observability.histogram(
            "resilience_restore_seconds",
            "verified manifest to assembled host pytree").observe(
                restore_s)
        tracer = observability.tracing.default()
        if tracer.enabled:
            tracer.record_span("snapshot.restore", duration_s=restore_s,
                               step=step, sharded=shardings is not None)
        return tree

    def _check_target(self, target: Any, shapes: Dict[str, tuple]):
        """Key/shape agreement between ``target`` and a manifest's tree
        schema, BEFORE any shard bytes are read."""
        tflat = flatten_tree(target)
        missing = set(tflat) - set(shapes)
        extra = set(shapes) - set(tflat)
        if missing or extra:
            raise SnapshotError(
                f"snapshot/target mismatch: missing={sorted(missing)[:5]}"
                f" extra={sorted(extra)[:5]}")
        for k, v in tflat.items():
            if hasattr(v, "shape") and shapes[k] != tuple(v.shape):
                raise SnapshotError(
                    f"shape mismatch for {k}: {shapes[k]} vs {v.shape}")

    # -- lifecycle ----------------------------------------------------------
    def _raise_pending(self):
        with self._err_lock:
            e, self._error = self._error, None
        if e is not None:
            raise e

    def wait_until_finished(self):
        self._queue.join()
        self._raise_pending()

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        self._worker.join()
        self._raise_pending()


def _covers_all(idx, shape) -> bool:
    return all(a == 0 and b == d for (a, b), d in zip(idx, shape))


def _copy_overlap(dst: np.ndarray, dst_idx, src_idx, data: np.ndarray):
    """Copy the intersection of a stored slice (``data`` covering
    ``src_idx`` of the global array) into a region buffer (``dst``
    covering ``dst_idx``); a no-op when they are disjoint."""
    sel_dst, sel_src = [], []
    for (da, db), (sa, sb) in zip(dst_idx, src_idx):
        lo, hi = max(da, sa), min(db, sb)
        if lo >= hi:
            return
        sel_dst.append(slice(lo - da, hi - da))
        sel_src.append(slice(lo - sa, hi - sa))
    dst[tuple(sel_dst)] = data[tuple(sel_src)]
