"""Decoder-only causal language model (GPT-style).

Beyond-reference capability (the reference era predates GPT training
recipes), included because the decoder stack, flash causal attention, and
sp/tp shardings make it free — and it is the canonical long-context
workload for ring attention. Pre-LN, learned positions, tied head.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Dropout, Embedding, LayerNorm
from paddle_tpu.nn.module import Layer, LayerList, StackedLayers
from paddle_tpu.nn.transformer import (ACT_SPEC, FeedForward,
                                       MultiHeadAttention, _constrain)


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_size: int = 3072
    max_position: int = 1024
    dropout: float = 0.0
    attn_impl: str = "auto"
    # GPipe the block stack over the "pp" mesh axis (parallel/pipeline.py)
    pipeline: bool = False
    pp_microbatches: int = 2
    pp_schedule: str = "gpipe"    # or "circular" (interleaved 1F1B)
    pp_circuits: int = 1
    pp_pre_interleaved: bool = False  # params pre-converted w/
    #   parallel.pipeline.interleave_stack (skips per-step reshuffle)
    # stacked (L, ...) scan-over-layers param layout (see BertConfig);
    # defaults on with pipeline. NOTE: changes the checkpoint tree —
    # migrate older per-layer trees with
    # parallel.pipeline.stack_params_at(params, ("blocks",), L).
    stacked_layers: Optional[bool] = None

    def __post_init__(self):
        if self.stacked_layers is None:
            self.stacked_layers = self.pipeline

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 128)
        kw.setdefault("hidden_size", 32)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 2)
        kw.setdefault("ffn_size", 64)
        kw.setdefault("max_position", 64)
        return cls(**kw)


class GPTBlock(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = LayerNorm(cfg.hidden_size)
        self.attn = MultiHeadAttention(cfg.hidden_size, cfg.num_heads,
                                       dropout=cfg.dropout, causal=True,
                                       attn_impl=cfg.attn_impl)
        self.ln2 = LayerNorm(cfg.hidden_size)
        self.mlp = FeedForward(cfg.hidden_size, cfg.ffn_size,
                               activation="gelu", dropout=cfg.dropout)

    def forward(self, params, x, *, key=None, training=False, cache=None,
                cache_pos=None, return_kv=False):
        k1 = k2 = None
        if key is not None:
            k1, k2 = jax.random.split(key)
        h = self.ln1(params["ln1"], x)
        if cache is not None:
            a, new_cache = self.attn(params["attn"], h, cache=cache,
                                     cache_pos=cache_pos)
            x = x + a
            x = x + self.mlp(params["mlp"], self.ln2(params["ln2"], x))
            return x, new_cache
        if return_kv:
            a, kv = self.attn(params["attn"], h, key=k1,
                              training=training, return_kv=True)
            x = x + a
            x = x + self.mlp(params["mlp"], self.ln2(params["ln2"], x),
                             key=k2, training=training)
            return x, kv
        x = x + self.attn(params["attn"], h, key=k1, training=training)
        x = x + self.mlp(params["mlp"], self.ln2(params["ln2"], x),
                         key=k2, training=training)
        return x


class GPT(Layer):
    """Causal LM: forward returns logits; loss is shifted next-token NLL."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = Embedding(cfg.vocab_size, cfg.hidden_size,
                             weight_init=I.normal(0.0, 0.02))
        self.wpe = Embedding(cfg.max_position, cfg.hidden_size,
                             weight_init=I.normal(0.0, 0.01), sharding=None)
        self.drop = Dropout(cfg.dropout)
        if cfg.stacked_layers:
            self.blocks = StackedLayers(GPTBlock(cfg), cfg.num_layers)
        else:
            self.blocks = LayerList([GPTBlock(cfg)
                                     for _ in range(cfg.num_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size)

    def forward(self, params, ids, *, key=None, training=False):
        cfg = self.cfg
        keys = [None] * (cfg.num_layers + 1)
        if key is not None:
            keys = list(jax.random.split(key, cfg.num_layers + 1))
        pos = jnp.arange(ids.shape[1], dtype=jnp.int32)[None, :]
        x = self.wte(params["wte"], ids) + self.wpe(params["wpe"], pos)
        x = self.drop(None, x, key=keys[0], training=training)
        x = _constrain(x, ACT_SPEC)
        if cfg.pipeline:
            x = self._blocks_pipelined(params, x, keys[1:], training)
        elif cfg.stacked_layers:
            lkeys = (jnp.stack(keys[1:]) if keys[1] is not None else None)
            x = self.blocks(params["blocks"], x, layer_keys=lkeys,
                            training=training)
        else:
            for i, block in enumerate(self.blocks):
                x = block(params["blocks"][str(i)], x, key=keys[i + 1],
                          training=training)
        x = self.ln_f(params["ln_f"], x)
        return jnp.einsum("bsd,vd->bsv", x, params["wte"]["weight"])

    def _blocks_pipelined(self, params, x, layer_keys, training):
        """GPipe over "pp" (shared schedule wrapper; the decoder-only
        stack has no per-microbatch bias — causality is inside the
        block)."""
        from paddle_tpu.parallel import pipeline as pp_lib

        cfg = self.cfg
        if cfg.stacked_layers:
            block0 = self.blocks.template
            blk_params = params["blocks"]        # pre-stacked (L, ...)
        else:
            block0 = self.blocks[0]
            blk_params = [params["blocks"][str(i)]
                          for i in range(cfg.num_layers)]
        return pp_lib.gpipe_layer_stack(
            lambda lp, h, extra, k: block0(lp, h, key=k,
                                           training=training),
            blk_params, x, num_microbatches=cfg.pp_microbatches,
            layer_keys=layer_keys, schedule=cfg.pp_schedule,
            num_circuits=cfg.pp_circuits,
            pre_interleaved=cfg.pp_pre_interleaved)

    def loss(self, params, ids, *, key=None, training=True):
        """Next-token LM loss over ids (B, S): predict ids[:,1:]."""
        logits = self.forward(params, ids[:, :-1], key=key,
                              training=training)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(logp, ids[:, 1:, None], -1)[..., 0]
        loss = nll.mean()
        return loss, {"ppl": jnp.exp(loss)}

    # ---- incremental decoding (KV cache) --------------------------------

    def init_cache(self, batch_size, max_len, dtype=jnp.float32):
        """Per-layer (k, v) buffers (B, H, max_len, Dh) for
        :meth:`generate(use_cache=True)`."""
        cfg = self.cfg
        shape = (batch_size, cfg.num_heads, max_len,
                 cfg.hidden_size // cfg.num_heads)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in range(cfg.num_layers)]

    def prefill(self, params, ids, cache):
        """Full-attention pass over the prompt that seeds the caches.
        Returns (logits (B, S0, V), cache)."""
        cfg = self.cfg
        s0 = ids.shape[1]
        pos = jnp.arange(s0, dtype=jnp.int32)[None, :]
        x = self.wte(params["wte"], ids) + self.wpe(params["wpe"], pos)
        x = _constrain(x, ACT_SPEC)
        new_cache = []
        for i, block in enumerate(self.blocks):
            x, (k, v) = block(params["blocks"][str(i)], x, return_kv=True)
            ck, cv = cache[i]
            new_cache.append((
                jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                             (0, 0, 0, 0)),
                jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                             (0, 0, 0, 0))))
        x = self.ln_f(params["ln_f"], x)
        return jnp.einsum("bsd,vd->bsv", x, params["wte"]["weight"]), \
            new_cache

    def decode_step(self, params, token_ids, pos, cache):
        """One cached decode step: ``token_ids`` (B,) at position ``pos``
        -> (logits (B, V), new_cache). O(S) work per token versus the
        uncached path's O(S^2) full refeed."""
        x = (self.wte(params["wte"], token_ids[:, None])
             + self.wpe(params["wpe"], pos[None, None]))
        new_cache = []
        for i, block in enumerate(self.blocks):
            x, kv = block(params["blocks"][str(i)], x, cache=cache[i],
                          cache_pos=pos)
            new_cache.append(kv)
        x = self.ln_f(params["ln_f"], x)
        return jnp.einsum("bd,vd->bv", x[:, 0],
                          params["wte"]["weight"]), new_cache

    def generate(self, params, prompt_ids, max_new_tokens=32,
                 temperature=1.0, key=None, use_cache=False,
                 cache_dtype=None):
        """Autoregressive sampling (greedy when key is None). Static-shape
        loop; prompt_ids (B, S0) with S0+max_new <= max_position.

        ``use_cache=True`` decodes incrementally through per-layer KV
        caches — same tokens, O(S) per step (LayerList layout only; the
        pipeline/stacked training layouts fall back to the full refeed).
        ``cache_dtype`` defaults to the params' compute dtype, so a bf16
        checkpoint gets a bf16 cache (half the HBM footprint).
        """
        cfg = self.cfg
        b, s0 = prompt_ids.shape
        total = s0 + max_new_tokens
        ids = jnp.concatenate(
            [prompt_ids,
             jnp.zeros((b, max_new_tokens), jnp.int32)], axis=1)

        def sample(logits, key):
            # one shape for both paths: split exactly like the uncached
            # body so cached/uncached sampling consume identical streams
            if key is None:
                return logits.argmax(-1).astype(jnp.int32), None
            key, new_key = jax.random.split(key)
            return jax.random.categorical(
                key, logits / temperature).astype(jnp.int32), new_key

        if use_cache and not (cfg.pipeline or cfg.stacked_layers):
            if cache_dtype is None:
                cache_dtype = params["wte"]["weight"].dtype
            cache = self.init_cache(b, total, dtype=cache_dtype)
            logits, cache = self.prefill(params, prompt_ids, cache)
            nxt, key = sample(logits[:, s0 - 1], key)
            ids = ids.at[:, s0].set(nxt)

            def body(t, carry):
                ids, cache, key = carry
                logits, cache = self.decode_step(
                    params, ids[:, t - 1], jnp.asarray(t - 1), cache)
                nxt, key = sample(logits, key)
                return ids.at[:, t].set(nxt), cache, key

            ids, _, _ = jax.lax.fori_loop(s0 + 1, total, body,
                                          (ids, cache, key))
            return ids

        def body(t, carry):
            ids, key = carry
            logits = self.forward(params, ids)[:, t - 1]
            nxt, key = sample(logits, key)
            return ids.at[:, t].set(nxt), key

        ids, _ = jax.lax.fori_loop(s0, total, body, (ids, key))
        return ids

    # ---- bucketed decoding (recompile cap) ------------------------------

    def _generate_padded_cached(self, params, padded_ids, prompt_len,
                                max_new_bucket):
        """Greedy cached decode where the REAL prompt length is a traced
        scalar: ``padded_ids`` (B, S0b) holds the prompt right-padded to
        the bucket; prefill seeds the cache causally over the padded
        buffer, the first token samples from ``prompt_len - 1``, and the
        decode loop overwrites the pad garbage in cache order (each step
        masks to ``<= cache_pos``, so garbage K/V past the write head is
        never attended). Returns generated tokens (B, max_new_bucket)."""
        b, s0b = padded_ids.shape
        cache = self.init_cache(b, s0b + max_new_bucket,
                                dtype=params["wte"]["weight"].dtype)
        logits, cache = self.prefill(params, padded_ids, cache)
        last = jnp.take_along_axis(
            logits, (prompt_len - 1)[None, None, None].astype(jnp.int32)
            .repeat(b, 0), axis=1)[:, 0]
        gen = jnp.zeros((b, max_new_bucket), jnp.int32)
        gen = gen.at[:, 0].set(jnp.argmax(last, -1).astype(jnp.int32))

        def body(t, carry):
            gen, cache = carry
            logits, cache = self.decode_step(
                params, gen[:, t - 1], prompt_len + t - 1, cache)
            return gen.at[:, t].set(
                jnp.argmax(logits, -1).astype(jnp.int32)), cache

        gen, _ = jax.lax.fori_loop(1, max_new_bucket, body, (gen, cache))
        return gen

    def generate_bucketed(self, params, prompt_ids, max_new_tokens=32,
                          *, min_bucket=8):
        """Greedy :meth:`generate` with power-of-two shape bucketing:
        the prompt is right-padded to the next pow2 length and the
        decode horizon rounded up the same way, so every request whose
        (prompt, horizon) lands in the same bucket reuses ONE compiled
        graph — a serving box sees a handful of compiles total instead
        of one per distinct request shape. Tokens are identical to
        ``generate(use_cache=True)`` because the real prompt length is a
        traced scalar (pad K/V is masked, then overwritten). LayerList
        layout only, greedy only. Returns (B, S0 + max_new_tokens) ids,
        same contract as :meth:`generate`."""
        cfg = self.cfg
        if cfg.pipeline or cfg.stacked_layers:
            raise ValueError("generate_bucketed needs the LayerList "
                             "layout (like generate(use_cache=True))")
        import numpy as np
        prompt_host = np.asarray(prompt_ids)
        b, s0 = prompt_host.shape

        def pow2(n):
            return 1 << max(int(n) - 1, 0).bit_length()

        s0b = min(max(pow2(s0), min_bucket), cfg.max_position)
        nb = max(pow2(max_new_tokens), min_bucket)
        if s0 + max_new_tokens > cfg.max_position:
            raise ValueError("prompt + max_new_tokens exceeds max_position")
        s0b = max(s0b, s0)  # max_position clamp must never truncate
        padded = np.zeros((b, s0b), np.int32)
        padded[:, :s0] = prompt_host
        jits = getattr(self, "_bucket_jit_cache", None)
        if jits is None:
            jits = {}
            object.__setattr__(self, "_bucket_jit_cache", jits)
        fn = jits.get((s0b, nb))
        if fn is None:
            fn = jax.jit(functools.partial(self._generate_padded_cached,
                                           max_new_bucket=nb))
            jits[(s0b, nb)] = fn
        gen = fn(params, jnp.asarray(padded),
                 jnp.asarray(s0, jnp.int32))
        # assemble on host: an eager jnp.concatenate would compile once
        # per prompt length — exactly the retraces bucketing removes
        return jnp.asarray(np.concatenate(
            [prompt_host.astype(np.int32),
             np.asarray(gen)[:, :max_new_tokens]], axis=1))
